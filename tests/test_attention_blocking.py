"""MXU-shaped attention blocking: the shared q-block core, the blocked
serving kernel, the flash training kernel, and the dot-shape gate.

The contract under test (ISSUE 16 / attention_core.py): every score
dot either kernel emits is [M, D] x [D, Bk] with M >= MIN_DOT_ROWS,
reached by q-token blocking plus head folding (grouped-query models) —
WITHOUT changing the numbers:

- blocked serving kernel vs the dense per-token reference across q-block
  remainders, GQA folds, multi-block token counts, and pad rows (whose
  measured work stays exactly zero)
- the host (numpy) and traced (jnp) block-plan builders agree slot for
  slot, so the serving scheduler's precomputed plan is the plan the
  eager/jit fallback derives
- flash training kernel forward AND gradients vs a jnp.einsum reference
  (causal and full), through the shared online-softmax core
- the serving planner floors token buckets at MIN_Q_TOKENS, so the
  q-blocks the engine dispatches reach the MXU sublane tile
- tools/check_dot_shapes.py (the ratchet form of all of the above) runs
  green from tier-1
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import attention_core as core
from paddle_tpu.ops.pallas.paged_attention import (
    build_block_plan, ragged_paged_attention, ragged_work_plan)
from paddle_tpu.ops.pallas.paged_attention import _block_plan_jnp

pytestmark = pytest.mark.heavy  # interpret-mode kernels compile slowly

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _dense_ref(q, k_pages, v_pages, pt, seq, bd):
    """Per-token dense reference with grouped-query head mapping."""
    T, H, D = q.shape
    KVH = k_pages.shape[2]
    fold = H // KVH
    out = np.zeros((T, H, D), np.float32)
    for t in range(T):
        b = int(bd[t])
        if b <= 0:
            continue
        ks = k_pages[pt[seq[t]]].reshape(-1, KVH, D)[:b]
        vs = v_pages[pt[seq[t]]].reshape(-1, KVH, D)[:b]
        for h in range(H):
            s = ks[:, h // fold] @ q[t, h] / np.sqrt(D)
            e = np.exp(s - s.max())
            out[t, h] = (e / e.sum()) @ vs[:, h // fold]
    return out


def _random_case(rng, T, H, KVH, B, W, P=4, D=8, n_pages=12):
    q = rng.standard_normal((T, H, D)).astype(np.float32)
    kp = rng.standard_normal((n_pages, P, KVH, D)).astype(np.float32)
    vp = rng.standard_normal((n_pages, P, KVH, D)).astype(np.float32)
    # distinct non-zero pages per row: page 0 is the reserved pad page
    pt = (1 + rng.permutation(n_pages - 1)[:B * W]).reshape(B, W)
    pt = pt.astype(np.int32)
    seq = rng.integers(0, B, T).astype(np.int32)
    bd = rng.integers(0, P * W + 1, T).astype(np.int32)
    return q, kp, vp, pt, seq, bd


class TestBlockedKernelEquality:
    @pytest.mark.parametrize("T,H,KVH,B,W", [
        (8, 2, 2, 2, 3),    # fold 1: M comes from the token block
        (5, 4, 2, 2, 3),    # odd T: one 5-row block, fold 2
        (12, 6, 3, 3, 2),   # fold 2 over 3 kv heads
        (16, 8, 1, 2, 4),   # MQA: fold 8
    ])
    def test_matches_dense_reference(self, T, H, KVH, B, W):
        rng = np.random.default_rng(T * 100 + H)
        q, kp, vp, pt, seq, bd = _random_case(rng, T, H, KVH, B, W)
        bd[T // 2] = 0  # at least one pad row in every case
        out = ragged_paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(pt), jnp.asarray(seq), jnp.asarray(bd),
            interpret=True)
        ref = _dense_ref(q, kp, vp, pt, seq, bd)
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)

    def test_small_q_block_splits_tokens_into_blocks(self):
        """Force multiple q-blocks (q_block < T) — block boundaries
        must not change the numbers, and the host plan for that block
        size must agree with the in-trace derivation."""
        rng = np.random.default_rng(7)
        T, H, KVH, B, W, P = 16, 2, 2, 2, 3, 4
        q, kp, vp, pt, seq, bd = _random_case(rng, T, H, KVH, B, W, P=P)
        ref = _dense_ref(q, kp, vp, pt, seq, bd)
        for q_block in (4, 8, 16):
            plan = build_block_plan(pt, seq, bd, P, q_block)
            out = ragged_paged_attention(
                jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(pt), jnp.asarray(seq), jnp.asarray(bd),
                interpret=True, q_block=q_block, block_plan=plan)
            np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5,
                                       err_msg=f"q_block={q_block}")

    def test_pad_rows_compute_zero_blocks(self):
        """A q-block of pure pads has blk_n == 0 — the DMA loop never
        starts — and the measured work counter stays the host formula
        (ceil(bound/P), 0 for pads) under any blocking."""
        rng = np.random.default_rng(3)
        T, P = 16, 4
        q, kp, vp, pt, seq, bd = _random_case(
            rng, T, 2, 2, 2, 3, P=P)
        bd[8:] = 0  # the whole second half pads: q-block 8..15 is empty
        seq[8:] = 0
        plan = build_block_plan(pt, seq, bd, P, 8)
        assert int(plan[3][1]) == 0  # second q-block: zero slots
        out, work = ragged_paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(pt), jnp.asarray(seq), jnp.asarray(bd),
            interpret=True, q_block=8, block_plan=plan,
            return_work=True)
        np.testing.assert_array_equal(np.asarray(work),
                                      ragged_work_plan(bd, P))
        assert np.asarray(out)[8:].any() == False  # noqa: E712
        np.testing.assert_allclose(
            np.asarray(out), _dense_ref(q, kp, vp, pt, seq, bd),
            atol=2e-5)

    def test_host_and_traced_block_plans_agree(self):
        """plan_ragged ships the numpy plan; eager/jit callers derive
        the jnp twin. Same slots, same order, same counts — or the
        serving path and the test-path kernels silently diverge."""
        rng = np.random.default_rng(11)
        for T, B, W, q_block in [(8, 2, 3, 8), (16, 3, 2, 4),
                                 (12, 2, 4, 12), (8, 1, 1, 8)]:
            P = 4
            pt = rng.integers(0, 10, (B, W)).astype(np.int32)
            seq = rng.integers(0, B, T).astype(np.int32)
            bd = rng.integers(0, P * W + 1, T).astype(np.int32)
            host = build_block_plan(pt, seq, bd, P, q_block)
            traced = _block_plan_jnp(jnp.asarray(pt), jnp.asarray(seq),
                                     jnp.asarray(bd), P, q_block)
            for name, h, t in zip(
                    ("blk_pages", "blk_seq", "blk_start", "blk_n"),
                    host, traced):
                # entries past blk_n are never read: compare the live
                # prefix per q-block, plus the counts exactly
                if name == "blk_n":
                    np.testing.assert_array_equal(h, np.asarray(t))
                    continue
                ta = np.asarray(t)
                for qb, n in enumerate(host[3]):
                    np.testing.assert_array_equal(
                        h[qb, :n], ta[qb, :n],
                        err_msg=f"{name}[{qb}] T={T} B={B} W={W}")

    def test_choose_q_block_respects_fold_cap(self):
        assert core.choose_q_block(256) == 128
        assert core.choose_q_block(256, cap=core.MXU_ROWS // 4) == 32
        assert core.choose_q_block(8) == 8
        assert core.choose_q_block(5) == 5      # odd: one block
        assert core.choose_q_block(1) == 1      # eager single token


class TestFlashKernel:
    def _ref(self, q, k, v, causal):
        B, T, H, D = q.shape
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
        if causal:
            mask = np.tril(np.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_and_grad_match_einsum(self, causal):
        from paddle_tpu.ops.pallas.flash_attention import \
            flash_attention_arrays
        rng = np.random.default_rng(0)
        B, T, H, D = 2, 32, 2, 8
        q, k, v = (jnp.asarray(
            rng.standard_normal((B, T, H, D)).astype(np.float32))
            for _ in range(3))

        def loss_flash(q, k, v):
            out = flash_attention_arrays(q, k, v, causal=causal,
                                         interpret=True)
            return jnp.sum(out * jnp.cos(out))

        def loss_ref(q, k, v):
            out = self._ref(q, k, v, causal)
            return jnp.sum(out * jnp.cos(out))

        np.testing.assert_allclose(
            np.asarray(flash_attention_arrays(q, k, v, causal=causal,
                                              interpret=True)),
            np.asarray(self._ref(q, k, v, causal)), atol=2e-5)
        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-4, err_msg=f"d{name}")

    def test_blocks_share_the_core_policy(self):
        # one source of truth: the kernel module re-exports nothing of
        # its own — block choice and the MXU floor live in the core
        from paddle_tpu.ops.pallas import flash_attention as fa
        assert fa.core is core
        bq, bk = core.choose_flash_blocks(2048, 2048, 64)
        assert bq == 1024 and bk == 1024
        bq, bk = core.choose_flash_blocks(2048, 2048, 128)
        assert bk == 512  # head dim scales the VMEM budget down


class TestServingBucketFloor:
    def test_pad_floor_constant_reaches_min_dot_rows(self):
        assert core.MIN_Q_TOKENS >= core.MIN_DOT_ROWS

    def test_warm_schedule_floors_and_collapses_token_buckets(self):
        """Every signature warm_async emits has T >= MIN_Q_TOKENS —
        the schedule _ragged_step's pad_t floor then lands on — and
        the floor COLLAPSES the sub-8 buckets (prefill chunk, its
        halved remainders, the decode step) onto one signature."""
        import paddle_tpu as paddle
        from paddle_tpu.jit import warm as jwarm
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
        from paddle_tpu.inference import GenerationEngine
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                        num_heads=2, max_position_embeddings=64)
        m = GPTForCausalLM(cfg)
        m.eval()
        eng = GenerationEngine(m, n_pages=16, page_size=4, max_batch=2,
                               max_new_tokens=3, name="floor_probe")
        try:
            jwarm.join(eng.warm_async(5, 3))
            sigs = {s[:3] for s in m._ragged_exec}
            # prompt 5 at page_size 4: chunk T=5->8, remainders
            # 4/2/1->8, decode 1->8; widths stay 2 — ONE signature
            assert sigs == {(8, 1, 2)}, sigs
        finally:
            eng.shutdown()


class TestDotShapeGate:
    def test_gate_green(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "check_dot_shapes.py")],
            capture_output=True, text=True, timeout=240,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert out.returncode == 0, f"{out.stdout}{out.stderr}"
        assert "OK:" in out.stdout

    def test_gate_red_on_narrow_dot(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_dot_shapes",
            os.path.join(REPO, "tools", "check_dot_shapes.py"))
        g = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(g)
        text = ("%5 = stablehlo.dot_general %3, %4 : "
                "(tensor<1x16xf32>, tensor<16x16xf32>) "
                "-> tensor<1x16xf32>")
        v, n = g.check_module("probe", text, 8)
        assert n == 1 and v and "M=1" in v[0]
        v, n = g.check_module("probe", "no dots here", 8)
        assert v and "vacuously" in v[0]
