"""Core runtime: Tensor, the eager autograd tape, and op dispatch.

TPU-native redesign of the reference's dygraph core
(paddle/fluid/imperative/tracer.cc + basic_engine.cc and the pten kernel
dispatch, paddle/pten/core/kernel_registry.h): instead of a C++ tracer
recording GradOpNodes and a per-place kernel registry, every op is a pure
JAX function executed eagerly on the device; when gradients are required we
record a lightweight Python tape node whose VJP is derived *at backward
time* via jax.vjp — so there is exactly one source of truth for op
semantics (the forward jax function) and XLA differentiates it.

The performance path does not use this tape at all: `paddle_tpu.jit` traces
Layer.forward into a single jitted function and uses jax.value_and_grad
(see jit/api.py), which is the idiomatic XLA formulation. The tape exists
for Paddle dygraph UX parity (`loss.backward()`; `opt.step()`).
"""
import threading
import weakref

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from .dtype import convert_dtype, get_default_dtype
from .debug import nan_check_enabled, check_numerics

__all__ = ["Tensor", "Parameter", "apply_op", "no_grad", "enable_grad",
           "set_grad_enabled", "is_grad_enabled", "to_tensor"]


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_grad_state = _GradState()


def is_grad_enabled():
    return _grad_state.enabled


class set_grad_enabled:
    """Context manager / function enabling or disabling tape recording."""

    def __init__(self, mode):
        self.prev = _grad_state.enabled
        _grad_state.enabled = bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self.prev
        return False


class no_grad:
    """paddle.no_grad parity: context manager and decorator."""

    def __enter__(self):
        self.prev = _grad_state.enabled
        _grad_state.enabled = False
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self.prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)
        return wrapper


class enable_grad(no_grad):
    def __enter__(self):
        self.prev = _grad_state.enabled
        _grad_state.enabled = True
        return self


class _Slot:
    """One immutable version of a tensor's value, a node in the grad DAG."""
    __slots__ = ("val", "node", "tensor_ref", "grad", "__weakref__")

    def __init__(self, val, node=None):
        self.val = val
        self.node = node          # _Node that produced it, None for leaves
        self.tensor_ref = None    # weakref to owning Tensor
        self.grad = None          # cotangent accumulated during backward


class _Node:
    """A recorded op: fn is a pure jax function over the diff inputs."""
    __slots__ = ("fn", "in_slots", "out_slots", "multi")

    def __init__(self, fn, in_slots, out_slots, multi=False):
        self.fn = fn
        self.in_slots = in_slots
        self.out_slots = out_slots
        self.multi = multi


class TensorHookRemoveHelper:
    """Handle returned by Tensor.register_hook (parity with the reference's
    TensorHookRemoveHelper, varbase_patch_methods.py)."""

    def __init__(self, tensor, hook):
        self._tensor = weakref.ref(tensor)
        self._hook = hook

    def remove(self):
        t = self._tensor()
        if t is None:
            return False
        hooks = getattr(t, "_grad_hooks", [])
        if self._hook in hooks:
            hooks.remove(self._hook)
            return True
        return False


def _is_diff_dtype(arr):
    return jnp.issubdtype(arr.dtype, jnp.floating) or jnp.issubdtype(
        arr.dtype, jnp.complexfloating)


class Tensor:
    """Eager tensor backed by a jax.Array.

    Semantics follow the reference Tensor
    (python/paddle/fluid/dygraph/varbase_patch_methods.py): user-created
    tensors default to stop_gradient=True; Parameters default to False;
    results of ops require grad iff any input does.
    """

    def __init__(self, data, dtype=None, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data.value
        if isinstance(data, _Slot):
            self._slot = data
        else:
            dt = convert_dtype(dtype)
            if isinstance(data, jax.Array) or type(data).__name__ == "ArrayImpl":
                arr = data if dt is None else data.astype(dt)
            else:
                npd = np.asarray(data)
                if dt is None and npd.dtype == np.float64:
                    dt = get_default_dtype()
                if dt is None and npd.dtype == np.int64:
                    dt = np.dtype(np.int64)
                arr = jnp.asarray(npd, dtype=dt)
            self._slot = _Slot(arr)
        self._slot.tensor_ref = weakref.ref(self)
        self.stop_gradient = stop_gradient
        self._name = name
        if name is not None:
            self._register_name()
        self.grad = None
        self._retain_grad = False

    _name_counter = [0]
    _name_registry = None  # weak name -> Tensor map, built on demand

    @property
    def name(self):
        """Reference tensors always carry a name (auto-generated when
        not user-set) — static doc examples fetch by `z.name`. Generate
        lazily so eager tensors stay cheap; generated/assigned names go
        in a weak registry so Executor.run can fetch by name."""
        if self._name is None:
            Tensor._name_counter[0] += 1
            self._name = f"generated_tensor_{Tensor._name_counter[0]}"
            self._register_name()
        return self._name

    @name.setter
    def name(self, value):
        self._name = value
        if value is not None:
            self._register_name()

    def _register_name(self):
        if Tensor._name_registry is None:
            Tensor._name_registry = weakref.WeakValueDictionary()
        Tensor._name_registry[self._name] = self

    # -- value plumbing -------------------------------------------------
    @property
    def value(self):
        return self._slot.val

    def _bind(self, slot):
        """Point this Tensor at a new value version (in-place ops)."""
        self._slot = slot
        slot.tensor_ref = weakref.ref(self)

    # -- introspection --------------------------------------------------
    @property
    def shape(self):
        return list(self.value.shape)

    @property
    def ndim(self):
        return self.value.ndim

    @property
    def size(self):
        return int(np.prod(self.value.shape)) if self.value.shape else 1

    @property
    def dtype(self):
        return np.dtype(self.value.dtype)

    @property
    def place(self):
        try:
            return str(next(iter(self.value.devices())))
        except Exception:
            return "tpu:0"

    @property
    def is_leaf(self):
        return self._slot.node is None

    def numpy(self):
        return np.asarray(self.value)

    def item(self, *args):
        return self.numpy().item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of a 0-D tensor")
        return self.shape[0]

    def __repr__(self):
        sg = self.stop_gradient
        return (f"Tensor(shape={self.shape}, dtype={self.value.dtype}, "
                f"place={self.place}, stop_gradient={sg},\n{self.numpy()})")

    def __bool__(self):
        if self.size != 1:
            raise ValueError("bool() of multi-element Tensor is ambiguous")
        return bool(self.numpy().reshape(()))

    def __int__(self):
        return int(self.item())

    def __float__(self):
        return float(self.item())

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- autograd -------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from ..autograd.backward_engine import run_backward
        run_backward(self, grad_tensor, retain_graph)

    def retain_grads(self):
        self._retain_grad = True

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor(self.value, stop_gradient=True)
        return t

    def clone(self):
        out = apply_op(lambda x: x + jnp.zeros((), x.dtype), self)
        out.stop_gradient = self.stop_gradient
        return out

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    def register_hook(self, hook):
        """Register a gradient hook, invoked by the backward engine when
        this tensor's gradient is finalized; a non-None return replaces the
        gradient flowing upstream (parity:
        python/paddle/fluid/dygraph/varbase_patch_methods.py:register_hook).
        Returns a removable handle."""
        if self.stop_gradient:
            raise RuntimeError(
                "register_hook on a tensor with stop_gradient=True")
        if not hasattr(self, "_grad_hooks"):
            self._grad_hooks = []
        self._grad_hooks.append(hook)
        return TensorHookRemoveHelper(self, hook)

    def get_value(self, scope=None):
        """Reference Variable.get_value parity (framework/io.py doc
        example: `var.get_value()` then `paddle.save(tensor, ...)`)."""
        return self

    # -- mutation (functional under the hood) ---------------------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value.value
        arr = jnp.asarray(np.asarray(value) if not isinstance(
            value, jax.Array) else value, dtype=self.value.dtype)
        if tuple(arr.shape) != tuple(self.value.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self.value.shape}")
        self._bind(_Slot(arr))

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    def __getitem__(self, idx):
        idx = _unwrap_index(idx)
        return apply_op(lambda x: x[idx], self)

    def __setitem__(self, idx, val):
        idx = _unwrap_index(idx)
        if isinstance(val, Tensor):
            new = apply_op(
                lambda x, v: x.at[idx].set(v.astype(x.dtype)), self, val)
        else:
            new = apply_op(lambda x: x.at[idx].set(val), self)
        self._bind(new._slot)

    # -- dtype / device -------------------------------------------------
    def astype(self, dt):
        dt = convert_dtype(dt)
        return apply_op(lambda x: x.astype(dt), self)

    cast = astype

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def tpu(self):
        return self

    def pin_memory(self):
        return self

    def to(self, *args, **kwargs):
        for a in args:
            try:
                return self.astype(a)
            except (TypeError, ValueError):
                continue
        if "dtype" in kwargs:
            return self.astype(kwargs["dtype"])
        return self


class Parameter(Tensor):
    """Trainable tensor. Parity: python/paddle/fluid/framework.py Parameter."""

    _name_counter = [0]

    def __init__(self, data, dtype=None, name=None, trainable=True):
        if name is None:
            Parameter._name_counter[0] += 1
            name = f"param_{Parameter._name_counter[0]}"
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx.value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(idx)
    return idx


def _requires_grad(t):
    return isinstance(t, Tensor) and not t.stop_gradient


def apply_op(fn, *tensors, n_outputs=None, op_name=None):
    """Execute a pure jax function over Tensor inputs; record tape if needed.

    `fn` takes the unwrapped jax arrays positionally (non-tensor config must
    be closed over by the caller) and returns one array or a tuple.

    `op_name` opts the op into amp.auto_cast dispatch: when autocast is
    active the policy dtype is resolved HERE (record time) and baked into
    the closure, so backward replay re-derives identical dtypes even though
    it runs outside the autocast context.
    """
    if op_name is not None:
        from ..amp import amp_op_dtype
        amp_dt = amp_op_dtype(op_name)
        if amp_dt is not None:
            inner = fn

            def fn(*args, _f=inner, _dt=amp_dt):
                cast = [a.astype(_dt)
                        if hasattr(a, "dtype")
                        and jnp.issubdtype(a.dtype, jnp.floating) else a
                        for a in args]
                return _f(*cast)

    arrays = [t.value if isinstance(t, Tensor) else t for t in tensors]
    out = fn(*arrays)
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]

    if nan_check_enabled():
        for o in outs:
            check_numerics(o, getattr(fn, "__qualname__", "op"))

    record = _grad_state.enabled and any(
        _requires_grad(t) and _is_diff_dtype(t.value)
        for t in tensors if isinstance(t, Tensor))
    # only differentiable outputs participate in the graph
    record = record and any(_is_diff_dtype(o) for o in outs)

    out_tensors = [Tensor(_Slot(o)) for o in outs]

    if record:
        diff_pos = [i for i, t in enumerate(tensors)
                    if _requires_grad(t) and isinstance(t, Tensor)
                    and _is_diff_dtype(t.value)]
        const = {i: a for i, a in enumerate(arrays) if i not in diff_pos}

        def baked_fn(*diff_args, _fn=fn, _dp=tuple(diff_pos), _const=const,
                     _n=len(arrays)):
            full = [None] * _n
            for i, a in zip(_dp, diff_args):
                full[i] = a
            for i, a in _const.items():
                full[i] = a
            return _fn(*full)

        in_slots = [tensors[i]._slot for i in diff_pos]
        out_slots = [t._slot for t in out_tensors]
        node = _Node(baked_fn, in_slots, out_slots, multi=multi)
        for t in out_tensors:
            t._slot.node = node
            t.stop_gradient = False
    return tuple(out_tensors) if multi else out_tensors[0]


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity (python/paddle/tensor/creation.py:to_tensor)."""
    if isinstance(data, Tensor):
        out = data.astype(dtype) if dtype is not None else data.clone()
        out.stop_gradient = stop_gradient
        return out
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
