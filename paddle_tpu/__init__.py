"""paddle_tpu — a TPU-native deep-learning framework with the PaddlePaddle
API surface (reference: YinLiu-91/Paddle, see SURVEY.md).

Compute path: JAX/XLA (+ Pallas TPU kernels in paddle_tpu.ops); scale-out:
jax.sharding Mesh + collectives (paddle_tpu.distributed); runtime extras in
C++ (paddle_tpu/runtime). The public namespace mirrors `import paddle`.
"""
__version__ = "0.1.0"

import jax as _jax

# Paddle semantics: int64 is the default integer dtype (indices, labels).
# Compute dtypes stay explicitly float32/bfloat16 throughout the framework,
# so this does not drag float64 onto the MXU.
_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: every paddle_tpu.jit / static.Executor /
# HybridTrainStep compile in any process is written to (and reloaded from)
# disk, so warm processes skip the cold compile. PADDLE_TPU_COMPILE_CACHE
# points it elsewhere or disables it ("0"); see framework/compile_cache.py.
from .framework.compile_cache import enable_compile_cache as _enable_cc

_enable_cc()

from .framework import (Tensor, Parameter, to_tensor, no_grad, enable_grad,
                        set_grad_enabled, is_grad_enabled, seed,
                        get_rng_state, set_rng_state,
                        dtype, float16, bfloat16, float32, float64, int8,
                        int16, int32, int64, uint8, bool_, complex64,
                        complex128, set_default_dtype, get_default_dtype,
                        iinfo, finfo)
from .framework.io import save, load
from .framework.param_attr import ParamAttr
from . import tensor
from .tensor import *  # noqa: F401,F403 — paddle.* op surface
from .tensor.creation import (to_tensor, zeros, ones, full, empty,
                              zeros_like, ones_like, full_like, empty_like,
                              arange, linspace, logspace, eye, meshgrid,
                              diag, diagflat, tril, triu, assign, clone,
                              numel, create_parameter)
from .tensor.logic import is_tensor
from .tensor.einsum import einsum
from . import autograd
from .autograd import grad
from . import device
from .device import (set_device, get_device, is_compiled_with_cuda,
                     is_compiled_with_rocm, is_compiled_with_xpu,
                     is_compiled_with_tpu, is_compiled_with_npu,
                     is_compiled_with_cinn)
from . import linalg
from . import version
from .tensor.search import where, nonzero, argmax, argmin  # noqa

# Subsystem imports are appended as each lands (see SURVEY.md §7 plan);
# keeping the namespace importable at every commit.
for _mod in ("nn", "optimizer", "amp", "io", "metric", "static", "jit",
             "vision", "distribution", "fft", "signal", "regularizer",
             "utils", "incubate", "distributed", "inference", "hapi",
             "profiler", "ops", "models", "text", "sparse", "hub",
             "sysconfig", "onnx", "compat", "callbacks", "reader",
             "dataset", "cost_model"):
    try:
        __import__(f"{__name__}.{_mod}")
    except ImportError:
        pass

try:
    from .hapi import Model
except ImportError:
    pass

# remaining reference top-level exports (python/paddle/__init__.py __all__)
bool = bool_  # noqa: A001 — paddle exposes `paddle.bool`
from .tensor.manipulation import flip as reverse  # noqa: E402
from .distributed import DataParallel  # noqa: E402


def tolist(x):
    return x.tolist()


def get_cuda_rng_state():
    return [get_rng_state()]


def set_cuda_rng_state(state):
    if state:
        set_rng_state(state[0])


def disable_signal_handler():
    pass


def check_shape(*args, **kwargs):
    pass


class CPUPlace:
    def __repr__(self):
        return "Place(cpu)"


class CUDAPlace:
    """Maps onto the TPU device in this backend (there is no CUDA)."""

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"Place(tpu:{self.device_id})"


class CUDAPinnedPlace(CPUPlace):
    pass


class NPUPlace(CUDAPlace):
    pass


class TPUPlace(CUDAPlace):
    pass


def _memcpy(x, place=None):
    """Copy a tensor, optionally "to" a place. XLA manages device
    residency, so every place maps to a plain copy; a CPUPlace target
    forces a host round-trip like the reference's memcpy op
    (tensor/creation.py _memcpy doc example)."""
    if isinstance(place, CPUPlace) and not isinstance(place, CUDAPlace):
        return to_tensor(x.numpy())
    return x.clone()


# paddle.disable_static / enable_static (dygraph is the default, like 2.x)
_static_mode = [False]


def enable_static():
    _static_mode[0] = True


def disable_static(place=None):
    _static_mode[0] = False


def in_dynamic_mode():
    return not _static_mode[0]


def is_grad_enabled_():
    return is_grad_enabled()


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.dynamic_flops import flops as _flops
    return _flops(net, input_size, custom_ops, print_detail)


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.model_summary import summary as _summary
    return _summary(net, input_size, dtypes, input)


# flags system (ref fluid/framework/flags): a real store; flags with a
# runtime behavior are applied on set, the rest are carried for
# introspection parity
_FLAGS = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_cudnn_deterministic": True,   # XLA is deterministic
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_fraction_of_gpu_memory_to_use": 0.0,
    "FLAGS_use_cinn": False,             # XLA is the compiler
}


def get_flags(flags=None):
    if flags is None:
        return dict(_FLAGS)
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}


def set_flags(flags):
    for k, v in dict(flags).items():
        _FLAGS[k] = v
        if k == "FLAGS_check_nan_inf":
            from .framework.debug import set_nan_inf_check
            # NB: bare `bool` here is paddle.bool (the dtype export)
            set_nan_inf_check(True if v else False)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None, **kwargs):
    """Reference signature (tensor/to_string.py set_printoptions):
    positional precision/threshold/edgeitems; sci_mode maps to numpy's
    suppress flag."""
    import numpy as np
    opts = dict(precision=precision, threshold=threshold,
                edgeitems=edgeitems, linewidth=linewidth)
    opts.update({k: v for k, v in kwargs.items()
                 if k in ("precision", "threshold", "edgeitems",
                          "linewidth")})
    np.set_printoptions(**{k: v for k, v in opts.items() if v is not None})
    if sci_mode is not None:
        np.set_printoptions(suppress=not sci_mode)


def batch(reader, batch_size, drop_last=False):
    """paddle.batch parity (python/paddle/batch.py)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched
