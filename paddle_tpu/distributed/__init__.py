"""paddle.distributed namespace.
Parity: python/paddle/distributed/__init__.py."""
from .env import (init_parallel_env, get_rank, get_world_size, barrier,
                  ParallelEnv, get_mesh, set_mesh, build_mesh,
                  is_initialized)
from .collective import (ReduceOp, all_reduce, all_gather, broadcast,
                         reduce, scatter, alltoall, send, recv,
                         reduce_scatter, split, new_group, get_group,
                         wait, psum, pmean, pmax, all_gather_axis,
                         ppermute, all_to_all_axis, axis_index)
from .entry_attr import (ProbabilityEntry, CountFilterEntry,
                         ShowClickEntry)
from .ps_dataset import InMemoryDataset, QueueDataset
from .parallel import DataParallel
from .spawn import spawn
from . import fleet
from . import auto_parallel
from .auto_parallel import shard_tensor, shard_op, ProcessMesh
from . import meta_parallel
from .fleet.utils.recompute import recompute
from . import checkpoint
from .checkpoint import (save_sharded, load_sharded, CheckpointManager,
                         AsyncSaveHandle)
from .elastic import ElasticController
from . import launch as launch_module


def launch():
    from .launch import main
    main()


# gloo_* — the reference's CPU-side gloo barrier API
# (python/paddle/distributed/parallel.py gloo_init_parallel_env /
# gloo_barrier / gloo_release). The TPU runtime is single-controller
# SPMD, so process-group bootstrap reduces to the mesh env; the gloo
# names map onto it for script compatibility.
_gloo_state = {"initialized": False}


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    _gloo_state.update(initialized=True, rank=rank_id, world=rank_num,
                       endpoint=server_endpoint)


def gloo_barrier():
    if not _gloo_state["initialized"]:
        raise RuntimeError("call gloo_init_parallel_env first")
    import jax
    if jax.process_count() > 1:
        # real cross-process rendezvous; env.barrier() is local-only
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu:gloo_barrier")
    else:
        barrier()


def gloo_release():
    _gloo_state["initialized"] = False
