"""nn.utils. Parity: python/paddle/nn/utils/."""
import numpy as np
import jax.numpy as jnp

from ...framework.core import Tensor, no_grad

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def parameters_to_vector(parameters, name=None):
    vals = [p.value.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(vals))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    with no_grad():
        for p in parameters:
            n = p.size
            p.set_value(vec.value[offset:offset + n].reshape(p.shape))
            offset += n


class _WeightNormHook:
    """Reparameterize weight = g * v / ||v|| via a forward-pre hook
    (reference: python/paddle/nn/utils/weight_norm_hook.py)."""

    def __init__(self, layer, name, dim):
        self.name = name
        self.dim = dim
        w = getattr(layer, name)
        from ...framework.core import Parameter
        wv = w.value
        norm = self._norm(wv)
        g = Parameter(norm, name=(w.name or name) + "_g")
        v = Parameter(wv, name=(w.name or name) + "_v")
        del layer._parameters[name]
        layer.add_parameter(name + "_g", g)
        layer.add_parameter(name + "_v", v)
        self._compute(layer)

    def _norm(self, wv):
        if self.dim is None:
            return jnp.sqrt(jnp.sum(jnp.square(wv))).reshape(())
        axes = tuple(i for i in range(wv.ndim) if i != self.dim)
        return jnp.sqrt(jnp.sum(jnp.square(wv), axis=axes))

    def _compute(self, layer):
        from ...framework.core import apply_op
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        dim = self.dim

        def fn(gv, vv):
            if dim is None:
                n = jnp.sqrt(jnp.sum(jnp.square(vv)))
                return gv * vv / jnp.maximum(n, 1e-12)
            axes = tuple(i for i in range(vv.ndim) if i != dim)
            n = jnp.sqrt(jnp.sum(jnp.square(vv), axis=axes, keepdims=True))
            shape = [1] * vv.ndim
            shape[dim] = -1
            return gv.reshape(shape) * vv / jnp.maximum(n, 1e-12)
        w = apply_op(fn, g, v)
        object.__setattr__(layer, "_wn_cached_" + self.name, w)

    def __call__(self, layer, inputs):
        self._compute(layer)
        return None


def weight_norm(layer, name="weight", dim=0):
    hook = _WeightNormHook(layer, name, dim)
    helper = layer.register_forward_pre_hook(hook)
    layer._wn_helper = helper
    layer._wn_hook = hook

    # route attribute access for `name` to the cached computed weight
    cls = type(layer)
    if not getattr(cls, "_wn_patched", False):
        orig_getattr = cls.__getattr__

        def patched(self, attr):
            if attr.startswith("_"):
                return orig_getattr(self, attr)
            hook_obj = self.__dict__.get("_wn_hook")
            if hook_obj is not None and attr == hook_obj.name:
                cached = self.__dict__.get("_wn_cached_" + attr)
                if cached is not None:
                    return cached
            return orig_getattr(self, attr)
        cls.__getattr__ = patched
        cls._wn_patched = True
    return layer


def remove_weight_norm(layer, name="weight"):
    hook = layer.__dict__.get("_wn_hook")
    if hook is None:
        return layer
    g = getattr(layer, name + "_g")
    v = getattr(layer, name + "_v")
    hook._compute(layer)
    w = layer.__dict__["_wn_cached_" + name]
    from ...framework.core import Parameter
    layer._wn_helper.remove()
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    layer.__dict__.pop("_wn_cached_" + name, None)
    layer.__dict__.pop("_wn_hook", None)
    layer.add_parameter(name, Parameter(w.value))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    from ..layer.norm import SpectralNorm as SNLayer
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = SNLayer(w.shape, dim=dim, power_iters=n_power_iterations, eps=eps)
    layer.add_sublayer("_spectral_norm", sn)
    orig_forward = layer.forward

    def forward(*args, **kwargs):
        with no_grad():
            pass
        normalized = sn(getattr(layer, name + "_orig"))
        object.__setattr__(layer, "_sn_cached", normalized)
        return orig_forward(*args, **kwargs)

    from ...framework.core import Parameter
    layer.add_parameter(name + "_orig", Parameter(w.value))
    del layer._parameters[name]
    cls = type(layer)
    orig_getattr = cls.__getattr__

    def patched(self, attr):
        if attr == name and "_sn_cached" in self.__dict__:
            return self.__dict__["_sn_cached"]
        if attr == name:
            return sn(orig_getattr(self, name + "_orig"))
        return orig_getattr(self, attr)
    cls.__getattr__ = patched
    layer.forward = forward
    return layer
