"""Remaining functional-surface ops: unpooling variants, niche losses,
beam-search utilities. Parity anchors noted per function."""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply_op
from ...framework.random import split_key

__all__ = ["elu_", "tanh_", "max_unpool1d", "max_unpool3d", "dice_loss",
           "hsigmoid_loss", "log_loss", "margin_cross_entropy",
           "gather_tree", "class_center_sample"]


def elu_(x, alpha=1.0, name=None):
    from .activation import elu
    out = elu(x, alpha)
    x._bind(out._slot)
    return x


def tanh_(x, name=None):
    from .activation import tanh
    out = tanh(x)
    x._bind(out._slot)
    return x


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """1-D unpool via the 2-D path (reference: unpooling op family)."""
    from .pooling import max_unpool2d
    from ...tensor.manipulation import unsqueeze, squeeze
    out = max_unpool2d(unsqueeze(x, 2), unsqueeze(indices, 2),
                       (1, kernel_size),
                       (1, stride if stride is not None else kernel_size),
                       (0, padding) if padding else 0,
                       output_size=([1] + list(output_size[-1:]))
                       if output_size else None)
    return squeeze(out, 2)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
        else [kernel_size] * 3
    st = stride if stride is not None else ks
    st = st if isinstance(st, (list, tuple)) else [st] * 3
    pd = padding if isinstance(padding, (list, tuple)) else [padding] * 3

    def fn(a, idx):
        N, C, D, H, W = a.shape
        if output_size is not None:
            od, oh, ow = [int(v) for v in output_size[-3:]]
        else:
            od = (D - 1) * st[0] + ks[0] - 2 * pd[0]
            oh = (H - 1) * st[1] + ks[1] - 2 * pd[1]
            ow = (W - 1) * st[2] + ks[2] - 2 * pd[2]
        out = jnp.zeros((N, C, od * oh * ow), a.dtype)
        flat = a.reshape(N, C, -1)
        fidx = idx.reshape(N, C, -1)
        out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(
            out, fidx, flat)
        return out.reshape(N, C, od, oh, ow)
    return apply_op(fn, x, indices)


def dice_loss(input, label, epsilon=1e-05, name=None):
    """Parity: nn/functional/loss.py:dice_loss (segmentation overlap)."""
    def fn(p, y):
        yh = jax.nn.one_hot(y[..., 0].astype(jnp.int32), p.shape[-1])
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * yh, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(yh, axis=red)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply_op(fn, input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_op(
        lambda p, y: -y * jnp.log(p + epsilon) -
        (1 - y) * jnp.log(1 - p + epsilon), input, label)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid (reference: hierarchical_sigmoid_op). Default
    complete-binary-tree coding when no custom paths are given."""
    depth = int(math.ceil(math.log2(max(num_classes, 2))))

    def fn(x, lab, w, *rest):
        b = rest[0] if bias is not None else None
        lab = lab.reshape(-1).astype(jnp.int32)
        B = x.shape[0]
        # complete binary tree: internal node ids 0..num_classes-2
        codes = []
        nodes = []
        cur = lab + (num_classes - 1)  # leaf position in heap order
        for _ in range(depth):
            parent = (cur - 1) // 2
            is_right = (cur % 2) == 0
            nodes.append(parent)
            codes.append(is_right.astype(jnp.float32))
            cur = parent
        nodes = jnp.stack(nodes, 1)           # [B, depth]
        codes = jnp.stack(codes, 1)           # [B, depth]
        valid = nodes >= 0
        nodes_safe = jnp.maximum(nodes, 0)
        wn = w[nodes_safe]                    # [B, depth, dim]
        logits = jnp.einsum("bd,btd->bt", x, wn)
        if b is not None:
            logits = logits + b[nodes_safe].reshape(logits.shape)
        # bce: label=code
        loss = jnp.maximum(logits, 0) - logits * codes + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))
        loss = jnp.where(valid, loss, 0.0)
        return jnp.sum(loss, axis=1, keepdims=True)
    args = [input, label, weight] + ([bias] if bias is not None else [])
    return apply_op(fn, *args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-style margin softmax (reference:
    paddle/fluid/operators/margin_cross_entropy_op.cu)."""
    def fn(lg, lab):
        lab = lab.reshape(-1).astype(jnp.int32)
        cos = jnp.clip(lg, -1.0, 1.0)
        theta = jnp.arccos(cos)
        tgt_theta = margin1 * theta + margin2
        tgt_cos = jnp.cos(tgt_theta) - margin3
        onehot = jax.nn.one_hot(lab, lg.shape[-1], dtype=lg.dtype)
        adjusted = jnp.where(onehot > 0, tgt_cos, cos) * scale
        logp = jax.nn.log_softmax(adjusted, -1)
        loss = -jnp.take_along_axis(logp, lab[:, None], 1)
        if reduction == "mean":
            loss_out = jnp.mean(loss)
        elif reduction == "sum":
            loss_out = jnp.sum(loss)
        else:
            loss_out = loss
        if return_softmax:
            return loss_out, jnp.exp(logp)
        return loss_out
    return apply_op(fn, logits, label)


def gather_tree(ids, parents):
    """Beam-search backtrace (reference: gather_tree_op). ids/parents:
    [max_time, batch, beam]."""
    def fn(ids_a, par_a):
        T = ids_a.shape[0]

        def step(carry, t):
            beams = carry  # [batch, beam] current beam indices
            tt = T - 1 - t
            out = jnp.take_along_axis(ids_a[tt], beams, axis=1)
            new_beams = jnp.take_along_axis(par_a[tt], beams, axis=1)
            return new_beams, out

        B, K = ids_a.shape[1], ids_a.shape[2]
        init = jnp.broadcast_to(jnp.arange(K)[None, :], (B, K))
        _, outs = jax.lax.scan(step, init, jnp.arange(T))
        return jnp.flip(outs, 0)
    return apply_op(fn, ids, parents)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Partial-FC negative class sampling (reference:
    class_center_sample_op). Returns remapped labels + sampled centers."""
    lab = np.asarray(label.numpy()).reshape(-1)
    pos = np.unique(lab)
    n_extra = max(num_samples - len(pos), 0)
    rest = np.setdiff1d(np.arange(num_classes), pos)
    rng = np.random.RandomState(int(np.sum(pos)) % (2 ** 31))
    extra = rng.choice(rest, size=min(n_extra, len(rest)), replace=False) \
        if n_extra else np.empty(0, np.int64)
    sampled = np.sort(np.concatenate([pos, extra]).astype(np.int64))
    remap = {c: i for i, c in enumerate(sampled)}
    new_lab = np.asarray([remap[c] for c in lab], np.int64)
    return Tensor(new_lab), Tensor(sampled)
