"""Framework core namespace. Parity: python/paddle/framework/__init__.py."""
from .core import Tensor, Parameter, apply_op, no_grad, enable_grad, \
    set_grad_enabled, is_grad_enabled, to_tensor
from .dtype import (dtype, float16, bfloat16, float32, float64, int8, int16,
                    int32, int64, uint8, bool_, complex64, complex128,
                    set_default_dtype, get_default_dtype, convert_dtype,
                    iinfo, finfo)
from .random import seed, get_rng_state, set_rng_state, rng_scope, split_key
from . import io
from . import compile_cache
from .compile_cache import enable_compile_cache, disable_compile_cache


def __getattr__(name):
    # the reference re-exports Places + mode helpers at paddle.framework
    # (python/paddle/framework/__init__.py); resolve them lazily to
    # avoid a circular import with the package root
    if name in ("CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "NPUPlace",
                "in_dygraph_mode", "in_dynamic_mode", "get_flags",
                "set_flags"):
        import paddle_tpu
        return getattr(paddle_tpu, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
