"""Reference import-path parity: every `from paddle.X.Y import Z` form a
migrating user relies on must resolve as a real module path here."""
import importlib

import pytest


@pytest.mark.parametrize("path,names", [
    ("paddle_tpu.incubate.nn",
     ["FusedMultiHeadAttention", "FusedFeedForward", "MoELayer"]),
    ("paddle_tpu.incubate.optimizer", ["LookAhead", "ModelAverage"]),
    ("paddle_tpu.device.cuda",
     ["synchronize", "device_count", "max_memory_allocated", "Stream",
      "Event"]),
    ("paddle_tpu.distributed.fleet.meta_parallel",
     ["PipelineLayer", "PipelineParallel"]),
    ("paddle_tpu.distributed.fleet.meta_parallel.parallel_layers",
     ["ColumnParallelLinear", "RowParallelLinear",
      "VocabParallelEmbedding"]),
    ("paddle_tpu.distributed.fleet.meta_parallel.sharding", []),
    ("paddle_tpu.nn.functional", ["relu", "cross_entropy"]),
    ("paddle_tpu.optimizer.lr", ["LRScheduler", "NoamDecay"]),
    ("paddle_tpu.vision.transforms", ["Compose", "Resize"]),
    ("paddle_tpu.static.nn", ["fc", "cond", "while_loop"]),
    ("paddle_tpu.compat", ["to_text", "to_bytes", "round",
                           "floor_division", "get_exception_message"]),
    ("paddle_tpu.callbacks", ["Callback", "EarlyStopping"]),
    ("paddle_tpu.reader", ["cache", "map_readers", "shuffle", "chain",
                           "compose", "buffered", "firstn",
                           "xmap_readers", "multiprocess_reader"]),
    ("paddle_tpu.dataset", ["mnist", "cifar", "imdb", "imikolov",
                            "movielens", "conll05", "uci_housing",
                            "wmt14", "wmt16", "flowers", "voc2012",
                            "image", "common"]),
    ("paddle_tpu.dataset.common", ["DATA_HOME", "md5file", "download",
                                   "split", "cluster_files_reader"]),
    ("paddle_tpu.cost_model", ["CostModel"]),
    ("paddle_tpu.inference", ["DataType", "PredictorPool", "get_version",
                              "get_trt_compile_version",
                              "get_trt_runtime_version",
                              "get_num_bytes_of_data_type"]),
])
def test_module_path_and_names(path, names):
    mod = importlib.import_module(path)
    for n in names:
        assert hasattr(mod, n), f"{path}.{n} missing"


def test_fleet_alias_is_same_package():
    import paddle_tpu.distributed.meta_parallel as real
    import paddle_tpu.distributed.fleet.meta_parallel as aliased
    assert aliased is real
