#!/usr/bin/env python
"""Schema lint for paddle_tpu metrics JSONL exports.

The per-step metrics file (PADDLE_TPU_METRICS_FILE, written by
paddle_tpu/profiler/monitor.py export_step) is a contract between the
framework, bench.py, and whatever driver/dashboard tails it. This tool
is the contract's enforcement point: tests/test_telemetry.py runs it on
a freshly emitted file, so the schema can't silently drift.

Schema (documented in docs/OBSERVABILITY.md):

  every line    one JSON object, no blank interior lines required keys:
                  ts    number   unix seconds
                  rank  int      process rank (0 single-controller)
                  kind  str      record type ("step", "scan", ...)
  kind == "step" additionally requires:
                  step         int     optimizer step index (>= 1)
                  step_time_s  number  wall seconds attributed to the step
                  compile_s    number  trace+compile seconds (0 warm)
                  cache_hit    bool    executable came from a cache
                  peak_bytes   int     device memory high-water mark
                  flops        number  per-step FLOPs (XLA cost analysis;
                                       0.0 when unavailable)
                  mfu          number  in [0, ~1]; 0.0 when unknown

Extra keys are allowed (the schema is open for forward compat); missing
or mistyped required keys are violations.

Usage: python tools/check_metrics_schema.py FILE [FILE...]
Exit 0 when every line of every file validates, 1 otherwise.
"""
import json
import sys

BASE_REQUIRED = {"ts": (int, float), "rank": int, "kind": str}
STEP_REQUIRED = {"step": int, "step_time_s": (int, float),
                 "compile_s": (int, float), "cache_hit": bool,
                 "peak_bytes": int, "flops": (int, float),
                 "mfu": (int, float)}


def _check_types(rec, required, where, errors):
    for key, types in required.items():
        if key not in rec:
            errors.append(f"{where}: missing required key {key!r}")
            continue
        val = rec[key]
        # bool is an int subclass: only cache_hit may be bool
        if isinstance(val, bool) and types is not bool:
            errors.append(f"{where}: key {key!r} is bool, expected "
                          f"{types}")
        elif not isinstance(val, types):
            errors.append(f"{where}: key {key!r} has type "
                          f"{type(val).__name__}, expected {types}")


def validate_line(line, where="<line>"):
    """Errors (list of strings, empty = valid) for one JSONL line."""
    errors = []
    try:
        rec = json.loads(line)
    except ValueError as e:
        return [f"{where}: not valid JSON ({e})"]
    if not isinstance(rec, dict):
        return [f"{where}: not a JSON object"]
    _check_types(rec, BASE_REQUIRED, where, errors)
    if rec.get("kind") == "step":
        _check_types(rec, STEP_REQUIRED, where, errors)
        if isinstance(rec.get("step"), int) and \
                not isinstance(rec.get("step"), bool) and rec["step"] < 1:
            errors.append(f"{where}: step must be >= 1, got {rec['step']}")
    return errors


def validate_file(path):
    """All violations in one file; ["<path>: empty file"] when empty."""
    errors = []
    with open(path) as f:
        lines = f.read().splitlines()
    if not any(line.strip() for line in lines):
        return [f"{path}: empty file (no records emitted)"]
    for lineno, line in enumerate(lines, 1):
        if not line.strip():
            continue
        errors.extend(validate_line(line, f"{path}:{lineno}"))
    return errors


def main(argv):
    if not argv:
        print(__doc__.strip().splitlines()[-2].strip())
        return 2
    all_errors = []
    for path in argv:
        all_errors.extend(validate_file(path))
    for err in all_errors:
        print(err)
    if all_errors:
        print(f"FAIL: {len(all_errors)} schema violation(s)")
        return 1
    print(f"OK: {len(argv)} file(s) validate")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
