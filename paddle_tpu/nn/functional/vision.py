"""Vision functionals. Parity: python/paddle/nn/functional/vision.py."""
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply_op


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            oc = C // (r * r)
            out = a.reshape(N, oc, r, r, H, W)
            out = out.transpose(0, 1, 4, 2, 5, 3)
            return out.reshape(N, oc, H * r, W * r)
        N, H, W, C = a.shape
        oc = C // (r * r)
        out = a.reshape(N, H, W, r, r, oc)
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(N, H * r, W * r, oc)
    return apply_op(fn, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            out = a.reshape(N, C, H // r, r, W // r, r)
            out = out.transpose(0, 1, 3, 5, 2, 4)
            return out.reshape(N, C * r * r, H // r, W // r)
        N, H, W, C = a.shape
        out = a.reshape(N, H // r, r, W // r, r, C)
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(N, H // r, W // r, C * r * r)
    return apply_op(fn, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(a):
        if data_format == "NCHW":
            N, C, H, W = a.shape
            out = a.reshape(N, groups, C // groups, H, W)
            out = out.transpose(0, 2, 1, 3, 4)
            return out.reshape(N, C, H, W)
        N, H, W, C = a.shape
        out = a.reshape(N, H, W, groups, C // groups)
        out = out.transpose(0, 1, 2, 4, 3)
        return out.reshape(N, H, W, C)
    return apply_op(fn, x)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    if isinstance(out_shape, Tensor):
        out_shape = [int(v) for v in out_shape.numpy()]

    def fn(th):
        N, C, H, W = [int(v) for v in out_shape]
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, H)
            xs = jnp.linspace(-1.0, 1.0, W)
        else:
            ys = (jnp.arange(H) * 2 + 1) / H - 1
            xs = (jnp.arange(W) * 2 + 1) / W - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # H,W,3
        out = jnp.einsum("hwk,nik->nhwi", base, th.astype(jnp.float32))
        return out.astype(th.dtype)
    return apply_op(fn, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    def fn(a, g):
        N, C, H, W = a.shape
        gx, gy = g[..., 0].astype(jnp.float32), g[..., 1].astype(jnp.float32)
        if align_corners:
            fx = (gx + 1) * (W - 1) / 2
            fy = (gy + 1) * (H - 1) / 2
        else:
            fx = ((gx + 1) * W - 1) / 2
            fy = ((gy + 1) * H - 1) / 2

        def sample(ix, iy):
            inb = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
            ixc = jnp.clip(ix, 0, W - 1)
            iyc = jnp.clip(iy, 0, H - 1)
            vals = a[jnp.arange(N)[:, None, None], :, iyc, ixc]
            if padding_mode == "zeros":
                vals = jnp.where(inb[..., None], vals, 0.0)
            return vals  # N,Hg,Wg,C

        if mode == "nearest":
            out = sample(jnp.round(fx).astype(jnp.int32),
                         jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = (fx - x0)[..., None]
            wy = (fy - y0)[..., None]
            out = (sample(x0, y0) * (1 - wx) * (1 - wy) +
                   sample(x1, y0) * wx * (1 - wy) +
                   sample(x0, y1) * (1 - wx) * wy +
                   sample(x1, y1) * wx * wy)
        return jnp.moveaxis(out, -1, 1).astype(a.dtype)
    return apply_op(fn, x, grid)
