"""paddle.hapi. Parity: python/paddle/hapi/__init__.py."""
from .model import Model
from . import callbacks
from .model_summary import summary
from .dynamic_flops import flops
