"""Debugging aids. Parity: paddle/fluid/framework/details/nan_inf_utils*
(check_nan_inf debug mode) + FLAGS_check_nan_inf.

TPU-native: eager mode checks each op output on the host; under jit use
enable_jit_nan_checks() which flips jax's debug_nans (XLA-level check that
re-runs the failing computation op-by-op to localize the NaN). Both paths
flight-record a structured `nan_detected` event before raising (the
profiler/flight_recorder.py ring + kind:"event" JSONL), so the failure is
on the timeline and in the crash bundle, not just in a traceback.
"""
import functools
import os

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["set_nan_inf_check", "check_numerics", "enable_jit_nan_checks",
           "TensorStats"]

_nan_check_enabled = [
    os.environ.get("FLAGS_check_nan_inf", "0") in ("1", "true")]


def set_nan_inf_check(enabled):
    _nan_check_enabled[0] = bool(enabled)


def nan_check_enabled():
    return _nan_check_enabled[0]


def _record_nan_event(op_name, n_nan, n_inf, where):
    """One structured anomaly into the flight-recorder ring (+ metrics
    JSONL when configured). Never raises — it runs inside jax host
    callbacks and right before user-visible exceptions."""
    try:
        from ..profiler import flight_recorder
        flight_recorder.record_event("nan_detected", op=str(op_name),
                                     n_nan=int(n_nan), n_inf=int(n_inf),
                                     where=where)
    except Exception:
        pass


def _jit_nan_tag(op_name, n_nan, n_inf):
    """Host side of the traced check_numerics tagging path
    (jax.debug.callback target): flight-record the hit, then raise — jax
    surfaces the FloatingPointError at the next synchronization point
    (or logs it, backend-dependent); either way the EVENT is durable."""
    n_nan, n_inf = int(n_nan), int(n_inf)
    if not (n_nan or n_inf):
        return
    _record_nan_event(op_name, n_nan, n_inf, "jit")
    raise FloatingPointError(
        f"NaN/Inf detected in traced output of '{op_name}': "
        f"{n_nan} NaNs, {n_inf} Infs")


def check_numerics(arr, op_name="op", jit_check=None):
    """Raise FloatingPointError when `arr` holds NaN/Inf (eager), and
    flight-record the detection first.

    Under tracing the check used to silently no-op; now a traced array
    routes through a `jax.debug.callback` tagging path: the non-finite
    COUNTS are computed in-graph (two reductions — the array itself
    never crosses to the host) and the callback records the anomaly
    event / raises when they are non-zero. The path is armed by
    `jit_check=True`, or by default when FLAGS_check_nan_inf /
    set_nan_inf_check is on; otherwise tracing stays zero-cost."""
    if isinstance(arr, jax.core.Tracer):
        armed = nan_check_enabled() if jit_check is None else jit_check
        if armed and jnp.issubdtype(arr.dtype, jnp.floating):
            jax.debug.callback(
                functools.partial(_jit_nan_tag, op_name),
                jnp.sum(jnp.isnan(arr)), jnp.sum(jnp.isinf(arr)))
        return arr
    if jnp.issubdtype(arr.dtype, jnp.floating) and \
            bool(jnp.any(~jnp.isfinite(arr))):
        n_nan = int(jnp.sum(jnp.isnan(arr)))
        n_inf = int(jnp.sum(jnp.isinf(arr)))
        _record_nan_event(op_name, n_nan, n_inf, "eager")
        raise FloatingPointError(
            f"NaN/Inf detected in output of '{op_name}': "
            f"{n_nan} NaNs, {n_inf} Infs, shape {arr.shape}")
    return arr


def enable_jit_nan_checks(enabled=True):
    """Flip jax_debug_nans: compiled programs re-run op-by-op on a NaN
    and raise FloatingPointError at dispatch. The train-step dispatch
    paths (jit/api.py, fleet/hybrid_train.py) catch that error,
    flight-record a `nan_detected` event, and write a debug bundle
    (PADDLE_TPU_DEBUG_DUMP) before re-raising."""
    jax.config.update("jax_debug_nans", bool(enabled))


class TensorStats:
    """Summarize a tensor for debugging (min/max/mean/nan counts)."""

    def __init__(self, t, name=""):
        arr = np.asarray(t.value if hasattr(t, "value") else t)
        self.name = name
        self.shape = arr.shape
        self.dtype = arr.dtype
        if np.issubdtype(arr.dtype, np.floating) and arr.size:
            self.min = float(np.nanmin(arr))
            self.max = float(np.nanmax(arr))
            self.mean = float(np.nanmean(arr))
            self.n_nan = int(np.isnan(arr).sum())
            self.n_inf = int(np.isinf(arr).sum())
        else:
            self.min = self.max = self.mean = None
            self.n_nan = self.n_inf = 0

    def __repr__(self):
        return (f"TensorStats({self.name} shape={self.shape} "
                f"dtype={self.dtype} min={self.min} max={self.max} "
                f"mean={self.mean} nan={self.n_nan} inf={self.n_inf})")
