"""Loss functionals. Parity: python/paddle/nn/functional/loss.py.

cross_entropy fuses log_softmax+gather; the Pallas softmax-xent kernel in
ops/pallas is substituted on the jit path for large vocab sizes.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply_op


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    def fn(logits, lab, *rest):
        # Opt-in Pallas softmax-xent path (PADDLE_TPU_PALLAS_XENT=1):
        # streams logits through VMEM with an online logsumexp. Measured
        # at the GPT bench shape [8192,50304] bf16, XLA's log_softmax
        # composition is faster fwd+bwd (4.3 ms vs 6.4 ms), so the
        # compiler path is the default.
        import os
        if (use_softmax and not soft_label and not rest
                and label_smoothing == 0.0 and logits.ndim >= 2
                and axis in (-1, logits.ndim - 1)
                and os.environ.get("PADDLE_TPU_PALLAS_XENT") == "1"
                and jax.default_backend() == "tpu"):
            from ...ops.pallas.softmax_xent import (softmax_xent_arrays,
                                                    supported)
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == logits.ndim:
                lab_i = jnp.squeeze(lab_i, axis=axis)
            n_rows = int(np.prod(logits.shape[:-1]))
            v = logits.shape[-1]
            if (lab_i.shape == logits.shape[:-1] and supported(n_rows, v)
                    and n_rows * v >= (1 << 22)):
                valid = lab_i != ignore_index
                # -1 never matches a vocab column: masked rows get a
                # zeroed loss here and a zeroed gradient via the mask
                loss = softmax_xent_arrays(
                    logits, jnp.where(valid, lab_i, -1))
                loss = jnp.where(valid, loss, 0.0)
                if reduction == "mean":
                    n = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
                    return jnp.sum(loss) / n
                return _reduce(loss, reduction)
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
        if soft_label:
            tgt = lab.astype(jnp.float32)
            if label_smoothing:
                k = logits.shape[axis]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=axis)
            valid = jnp.ones_like(loss, dtype=bool)
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == logp.ndim:  # [N,...,1] style labels
                lab_i = jnp.squeeze(lab_i, axis=axis)
            valid = lab_i != ignore_index
            safe = jnp.where(valid, lab_i, 0)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, axis), axis=axis)
            loss = -jnp.squeeze(picked, axis=axis)
            if label_smoothing:
                k = logits.shape[axis]
                smooth = -jnp.mean(logp, axis=axis)
                loss = (1 - label_smoothing) * loss + label_smoothing * smooth
            loss = jnp.where(valid, loss, 0.0)
            if rest:  # per-class weights
                w = rest[0].astype(jnp.float32)
                wsel = jnp.where(valid, jnp.take(w, safe), 0.0)
                loss = loss * wsel
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(wsel), 1e-12)
        if reduction == "mean":
            n = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(loss) / n
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op(fn, *args, op_name="cross_entropy")


softmax_with_cross_entropy = None  # defined below


def _softmax_with_cross_entropy(logits, label, soft_label=False,
                                ignore_index=-100, numeric_stable_mode=True,
                                return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    from .activation import softmax as _softmax
    from ...tensor.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


softmax_with_cross_entropy = _softmax_with_cross_entropy


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def fn(logp, lab, *rest):
        lab_i = lab.astype(jnp.int32)
        if lab_i.ndim == logp.ndim and lab_i.shape[-1] == 1:
            lab_i = jnp.squeeze(lab_i, -1)  # [N,1] labels (ref accepts)
        valid = lab_i != ignore_index
        safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        loss = -jnp.squeeze(picked, axis=1)
        w_all = None
        if rest:
            w_all = jnp.take(rest[0], safe)
            loss = loss * w_all
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(valid, w_all, 0.0)) if w_all is not None \
                else jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op(fn, *args)


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def fn(p, y, *rest):
        p32 = jnp.clip(p.astype(jnp.float32), 1e-12, 1 - 1e-7)
        loss = -(y * jnp.log(p32) + (1 - y) * jnp.log1p(-p32))
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op(fn, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def fn(z, y, *rest):
        z32 = z.astype(jnp.float32)
        y32 = y.astype(jnp.float32)
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
        base = jnp.maximum(z32, 0) - z32 * y32 + jnp.log1p(
            jnp.exp(-jnp.abs(z32)))
        i = 0
        if pos_weight is not None:
            pw = rest[i]
            i += 1
            logsig = jax.nn.log_sigmoid(z32)
            logsig_neg = jax.nn.log_sigmoid(-z32)
            base = -(pw * y32 * logsig + (1 - y32) * logsig_neg)
        if weight is not None:
            base = base * rest[i]
        return _reduce(base, reduction)
    args = [logit, label]
    if pos_weight is not None:
        args.append(pos_weight)
    if weight is not None:
        args.append(weight)
    return apply_op(fn, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.square(a - b), reduction),
                    input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss * delta, reduction)
    return apply_op(fn, input, label)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d,
                         delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply_op(fn, input, label)


def kl_div(input, label, reduction="mean", name=None):
    def fn(logp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return apply_op(fn, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def fn(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)
    return apply_op(fn, input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def fn(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply_op(fn, input, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op(fn, input1, input2, label)


def soft_margin_loss(input, label, reduction="mean", name=None):
    return apply_op(
        lambda a, y: _reduce(jnp.log1p(jnp.exp(-y * a)), reduction),
        input, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean",
                        name=None):
    def fn(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p,
                           axis=-1) ** (1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        loss = jnp.maximum(0.0, d_pos - d_neg + margin)
        return _reduce(loss, reduction)
    return apply_op(fn, input, positive, negative)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin,
                                   swap=swap, reduction=reduction)
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        from ...tensor.math import minimum
        d_neg = minimum(d_neg, distance_function(positive, negative))
    from ...tensor.math import maximum as tmax
    from ...tensor import mean as tmean, sum as tsum
    loss = tmax(d_pos - d_neg + margin, Tensor(np.float32(0.0)))
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def square_error_cost(input, label):
    return apply_op(lambda a, b: jnp.square(a - b), input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, y, *rest):
        p = jax.nn.sigmoid(z.astype(jnp.float32))
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return apply_op(fn, *args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via the classic alpha-recursion in log space (lax.scan over T).
    Reference kernel: paddle/fluid/operators/warpctc_op.* (warp-ctc);
    here it is a pure-XLA scan, jit-compatible."""
    def fn(lp, lab, ilen, llen):
        # lp: [T, N, C] log-softmaxed; paddle passes [T,N,C] logits
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, N, C = lp.shape
        S = lab.shape[1]
        ext = 2 * S + 1
        # extended label seq: blank l1 blank l2 ... blank
        ext_lab = jnp.full((N, ext), blank, dtype=jnp.int32)
        ext_lab = ext_lab.at[:, 1::2].set(lab.astype(jnp.int32))
        neg_inf = -1e30
        alpha0 = jnp.full((N, ext), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = jnp.take_along_axis(lp[0], ext_lab[:, 1:2], axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(llen > 0, first_lab, neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), bool),
             ext_lab[:, 2:] == ext_lab[:, :-2]], axis=1)

        def step(alpha, lp_t):
            a_prev1 = jnp.concatenate(
                [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_prev2 = jnp.concatenate(
                [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_prev2 = jnp.where(same_as_prev2, neg_inf, a_prev2)
            m = jnp.maximum(jnp.maximum(alpha, a_prev1), a_prev2)
            m_safe = jnp.maximum(m, neg_inf)
            summed = jnp.exp(alpha - m_safe) + jnp.exp(a_prev1 - m_safe) + \
                jnp.exp(a_prev2 - m_safe)
            new = m_safe + jnp.log(summed)
            emit = jnp.take_along_axis(lp_t, ext_lab, axis=1)
            return new + emit, new + emit

        alphaT, alphas = jax.lax.scan(step, alpha0, lp[1:])
        # stack alpha0 at t=0
        all_alphas = jnp.concatenate([alpha0[None], alphas], axis=0)
        t_idx = jnp.clip(ilen - 1, 0, T - 1).astype(jnp.int32)
        final = all_alphas[t_idx, jnp.arange(N)]  # [N, ext]
        endpos = 2 * llen.astype(jnp.int32)
        last_blank = jnp.take_along_axis(final, endpos[:, None], axis=1)[:, 0]
        last_lab = jnp.take_along_axis(
            final, jnp.maximum(endpos - 1, 0)[:, None], axis=1)[:, 0]
        m = jnp.maximum(last_blank, last_lab)
        ll = m + jnp.log(jnp.exp(last_blank - m) + jnp.exp(last_lab - m))
        loss = -ll
        if norm_by_times:
            loss = loss / jnp.maximum(ilen.astype(jnp.float32), 1.0)
        return _reduce(loss, reduction)
    return apply_op(fn, log_probs, labels, input_lengths, label_lengths)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, y):
        B = a.shape[0]
        sim = a @ p.T
        y = y.reshape(-1, 1)
        tgt = (y == y.T).astype(jnp.float32)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        xent = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) +
                        jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return xent + reg
    return apply_op(fn, anchor, positive, labels)
