"""paddle.jit.dy2static — the dygraph→static conversion subsystem.

Parity: python/paddle/fluid/dygraph/dygraph_to_static/ (~9.6k LoC of AST
transformation + runtime converters). TPU-native scope: conversion targets
jax.lax control flow through the convert_operators runtime; everything
data-independent stays plain Python and is simply traced.
"""
from .convert_operators import (
    UNDEFINED, convert_ifelse, convert_ifexp, convert_while_loop,
    convert_for, convert_for_range, convert_logical_and, convert_logical_or,
    convert_logical_not, convert_var_to_bool, convert_call, not_returned)
from .program_translator import (
    convert_to_static, conversion_enabled, ProgramTranslator,
    enable_to_static, unwrap_converted)

__all__ = [
    "UNDEFINED", "convert_ifelse", "convert_ifexp", "convert_while_loop",
    "convert_for", "convert_for_range", "convert_logical_and",
    "convert_logical_or", "convert_logical_not", "convert_var_to_bool",
    "convert_call", "not_returned", "convert_to_static",
    "conversion_enabled", "ProgramTranslator", "enable_to_static",
    "unwrap_converted",
]
