"""DenseNet family. Parity: python/paddle/vision/models/densenet.py
(DenseNet 121/161/169/201/264).

Pre-activation dense layers (BN-ReLU-1x1 -> BN-ReLU-3x3, channel concat)
with half-compression transitions. Concats are pure layout ops under XLA;
the 1x1 bottlenecks dominate FLOPs and land on the MXU.
"""
from ... import nn
from ...tensor.manipulation import concat, flatten

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

# layers -> (init_features, growth_rate, block config)
_DENSENET_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, num_channels, growth_rate, bn_size, dropout):
        super().__init__()
        inter = bn_size * growth_rate
        self.bn1 = nn.BatchNorm2D(num_channels)
        self.conv1 = nn.Conv2D(num_channels, inter, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(inter)
        self.conv2 = nn.Conv2D(inter, growth_rate, 3, padding=1,
                               bias_attr=False)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, num_channels, num_out):
        super().__init__()
        self.bn = nn.BatchNorm2D(num_channels)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(num_channels, num_out, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    """DenseNet model (ref: vision/models/densenet.py:187).

    Args mirror the reference: ``layers`` in {121, 161, 169, 201, 264},
    ``bn_size`` bottleneck multiplier, ``dropout`` inside dense layers.
    """

    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        assert layers in _DENSENET_CFG, (
            f"supported layers are {sorted(_DENSENET_CFG)} but input "
            f"layer is {layers}")
        num_init, growth, block_cfg = _DENSENET_CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv1 = nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(num_init)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)

        blocks = []
        channels = num_init
        for i, num_layers in enumerate(block_cfg):
            for _ in range(num_layers):
                blocks.append(_DenseLayer(channels, growth, bn_size,
                                          dropout))
                channels += growth
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(channels, channels // 2))
                channels //= 2
        self.features = nn.Sequential(*blocks)
        self.bn_last = nn.BatchNorm2D(channels)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(channels, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.relu(self.bn_last(self.features(x)))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def _densenet(layers, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require network access; load a local "
            "state_dict via model.set_state_dict instead")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
