"""Linear algebra. Parity: python/paddle/tensor/linalg.py + paddle/linalg.py.

matmul is THE op on TPU: it lowers to MXU systolic-array contractions.
Decompositions (qr/svd/eig/...) lower to XLA's linalg lib (CPU/TPU).
"""
import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply_op(fn, x, y, op_name="matmul")


def dot(x, y, name=None):
    def fn(a, b):
        if a.ndim == 2:
            return jnp.sum(a * b, axis=-1)
        return jnp.dot(a, b)
    return apply_op(fn, x, y)


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, x, y, op_name="bmm")


def mv(x, vec, name=None):
    return apply_op(jnp.matmul, x, vec)


def mm(input, mat2, name=None):
    return apply_op(jnp.matmul, input, mat2, op_name="mm")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y)


def cross(x, y, axis=9, name=None):
    def fn(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply_op(fn, x, y)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def fn(a):
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if p == "fro":
            if ax is None:
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            return jnp.linalg.norm(a, ord="fro" if isinstance(ax, tuple)
                                   else None, axis=ax, keepdims=keepdim)
        if p == np.inf or p == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax,
                           keepdims=keepdim)
        if ax is None:
            a = a.reshape(-1)
            ax = 0
        return jnp.sum(jnp.abs(a) ** p, axis=ax,
                       keepdims=keepdim) ** (1.0 / p)
    return apply_op(fn, x)


def dist(x, y, p=2, name=None):
    def fn(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype))
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply_op(fn, x, y)


def cond(x, p=None, name=None):
    return apply_op(lambda a: jnp.linalg.cond(a, p=p), x)


def cholesky(x, upper=False, name=None):
    def fn(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return apply_op(fn, x)


def cholesky_solve(x, y, upper=False, name=None):
    import jax.scipy.linalg as jsl
    def fn(b, L):
        return jsl.cho_solve((L, not upper), b)
    return apply_op(fn, x, y)


def qr(x, mode="reduced", name=None):
    outs = apply_op(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x)
    return outs


def svd(x, full_matrices=False, name=None):
    return apply_op(
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x)


def eig(x, name=None):
    w, v = np.linalg.eig(x.numpy())
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    return apply_op(lambda a: tuple(jnp.linalg.eigh(a,
                    symmetrize_input=True)), x)


def eigvals(x, name=None):
    return Tensor(np.linalg.eigvals(x.numpy()))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(lambda a: jnp.linalg.eigvalsh(a), x)


def inverse(x, name=None):
    """Alias of inv (reference paddle.inverse)."""
    return inv(x)


def inv(x, name=None):
    return apply_op(jnp.linalg.inv, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(
        lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), x)


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    import jax.scipy.linalg as jsl
    def fn(a, b):
        return jsl.solve_triangular(a, b, lower=not upper,
                                    trans=1 if transpose else 0,
                                    unit_diagonal=unitriangular)
    return apply_op(fn, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank.astype(jnp.int32), sv
    sol, res, rank, sv = apply_op(fn, x, y)
    return sol, res, rank, sv


def matrix_power(x, n, name=None):
    return apply_op(lambda a: jnp.linalg.matrix_power(a, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    tv = tol.value if isinstance(tol, Tensor) else tol
    return apply_op(lambda a: jnp.linalg.matrix_rank(a, rtol=tv), x)


def det(x, name=None):
    return apply_op(jnp.linalg.det, x)


def slogdet(x, name=None):
    def fn(a):
        s, l = jnp.linalg.slogdet(a)
        return jnp.stack([s, l]) if s.ndim == 0 else jnp.stack([s, l])
    return apply_op(fn, x)


def multi_dot(x, name=None):
    return apply_op(lambda *xs: jnp.linalg.multi_dot(xs), *x)


def lu(x, pivot=True, get_infos=False, name=None):
    import jax.scipy.linalg as jsl
    def fn(a):
        lu_, piv = jsl.lu_factor(a)
        return lu_, piv.astype(jnp.int32) + 1  # paddle pivots are 1-based
    lu_, piv = apply_op(fn, x)
    if get_infos:
        from .creation import zeros
        return lu_, piv, zeros([1], dtype="int32")
    return lu_, piv


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    def fn(lu_, piv):
        m = lu_.shape[-2]
        L = jnp.tril(lu_, -1) + jnp.eye(m, lu_.shape[-1], dtype=lu_.dtype)
        L = L[..., :, :m]
        U = jnp.triu(lu_)[..., :m, :]
        piv0 = piv - 1
        perm = jnp.arange(m)
        def body(i, p):
            a, b = p[i], p[piv0[i]]
            p = p.at[i].set(b)
            return p.at[piv0[i]].set(a)
        for i in range(m):
            perm = body(i, perm)
        P = jnp.eye(m, dtype=lu_.dtype)[perm].T
        return P, L, U
    return apply_op(fn, x, y)


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = fweights.value if isinstance(fweights, Tensor) else fweights
    aw = aweights.value if isinstance(aweights, Tensor) else aweights
    return apply_op(lambda a: jnp.cov(a, rowvar=rowvar,
                                      ddof=1 if ddof else 0,
                                      fweights=fw, aweights=aw), x)


def histogram(input, bins=100, min=0, max=0, name=None):
    a = input.numpy()
    lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
    h, _ = np.histogram(a, bins=bins, range=(lo, hi))
    return Tensor(h.astype(np.int64))


def bincount(x, weights=None, minlength=0, name=None):
    w = weights.numpy() if isinstance(weights, Tensor) else weights
    return Tensor(np.bincount(x.numpy(), weights=w, minlength=minlength))
