"""Pin tests to the CPU backend with 8 virtual devices so distributed
(mesh/sharding) tests run without real multi-chip hardware (SURVEY.md §4).

jax may already be imported by the interpreter's sitecustomize (TPU tunnel
registration), so setting env vars alone is not enough — we also flip the
jax config before any backend initializes (first device use wins)."""
import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", "tests must run on the CPU backend"
assert jax.device_count() == 8, "expected 8 virtual CPU devices"

# persistent compilation cache: the suite is compile-bound (single-core
# hosts spend >80% of wall time in XLA), so cache compiled executables
# across runs — repeat runs drop from ~8min to well under the 5min
# SURVEY §4 CI budget.
_cache_dir = os.environ.get("PADDLE_TPU_TEST_CACHE",
                            os.path.expanduser("~/.cache/paddle_tpu_xla"))
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
