"""Fused softmax + cross-entropy Pallas TPU kernel (forward + custom VJP).

Replaces the reference's fused softmax_with_cross_entropy CUDA kernel
(paddle/fluid/operators/softmax_with_cross_entropy_op.cu) for the hard-label
case. The [N, V] logits are streamed through VMEM in vocab blocks with an
online logsumexp, so neither the softmax probabilities nor the log-probs are
ever materialized in HBM — for a GPT-sized vocab (V ~ 50k) this halves the
loss-path HBM traffic versus the XLA log_softmax+gather composition.

Forward emits per-row `loss = lse - logits[label]` plus the `lse` residual;
backward is a single fused pass `dlogits = (softmax - onehot) * dloss`.

Row-wise scalars (labels, loss, lse, dloss) are carried as [N, 1] arrays:
trailing-unit blocks satisfy the TPU (8, 128) tiling rule, which 1D
partial blocks do not.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import I0, NEG_INF  # noqa: F401


def _fwd_kernel(x_ref, lab_ref, loss_ref, lse_ref, m_ref, l_ref,
                picked_ref, *, block_v):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, jnp.float32(NEG_INF))
        l_ref[:] = jnp.zeros_like(l_ref)
        picked_ref[:] = jnp.zeros_like(picked_ref)

    s = x_ref[:].astype(jnp.float32)                    # [bn, bv]
    lab = lab_ref[:]                                    # [bn, 1] i32
    bn, bv = s.shape
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)

    m_prev = m_ref[:]                                   # [bn, 1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    l_ref[:] = (l_ref[:] * jnp.exp(m_prev - m_new) +
                jnp.sum(jnp.exp(s - m_new), axis=1, keepdims=True))
    m_ref[:] = m_new
    picked_ref[:] += jnp.sum(
        jnp.where(cols == lab, s, jnp.float32(0.0)), axis=1, keepdims=True)

    @pl.when(j == nv - 1)
    def _fin():
        lse = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], jnp.float32(1e-30)))
        loss_ref[:] = lse - picked_ref[:]
        lse_ref[:] = lse


def _bwd_kernel(x_ref, lab_ref, lse_ref, dloss_ref, dx_ref, *, block_v):
    j = pl.program_id(1)
    s = x_ref[:].astype(jnp.float32)                    # [bn, bv]
    lab = lab_ref[:]                                    # [bn, 1]
    bn, bv = s.shape
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    p = jnp.exp(s - lse_ref[:])                         # softmax block
    onehot = (cols == lab).astype(jnp.float32)
    dx_ref[:] = ((p - onehot) * dloss_ref[:]).astype(dx_ref.dtype)


def _choose_block(n, cap, align):
    """Largest divisor of n that is <= cap and a multiple of `align`.
    Returns 0 (unsupported) when no aligned divisor exists — unaligned
    blocks violate the TPU (8, 128) tiling rule and fail Mosaic lowering."""
    if n <= cap:
        return n if n % align == 0 else 0
    best = 0
    b = align
    while b <= cap:
        if n % b == 0:
            best = b
        b += align
    return best


def supported(n, v):
    return (_choose_block(n, 1024, 8) > 0 and
            _choose_block(v, 4096, 128) > 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_xent(x2d, lab2d, interpret):
    loss, _ = _fwd_impl(x2d, lab2d, interpret)
    return loss


def _fwd_impl(x2d, lab2d, interpret):
    N, V = x2d.shape
    bn = _choose_block(N, 1024, 8)
    bv = _choose_block(V, 4096, 128)
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_v=bv),
        grid=(N // bn, V // bv),
        in_specs=[
            pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, I0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, 1), lambda i, j: (i, I0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
            jax.ShapeDtypeStruct((N, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
            pltpu.VMEM((bn, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x2d, lab2d)
    return loss, lse


def _fwd(x2d, lab2d, interpret):
    loss, lse = _fwd_impl(x2d, lab2d, interpret)
    return loss, (x2d, lab2d, lse)


def _bwd(interpret, res, dloss):
    x2d, lab2d, lse = res
    N, V = x2d.shape
    bn = _choose_block(N, 1024, 8)
    bv = _choose_block(V, 4096, 128)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, block_v=bv),
        grid=(N // bn, V // bv),
        in_specs=[
            pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, I0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, I0)),
            pl.BlockSpec((bn, 1), lambda i, j: (i, I0)),
        ],
        out_specs=pl.BlockSpec((bn, bv), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((N, V), x2d.dtype),
        interpret=interpret,
    )(x2d, lab2d, lse, dloss.astype(jnp.float32))
    return dx, None


_softmax_xent.defvjp(_fwd, _bwd)


def softmax_xent_arrays(logits, labels, interpret=None):
    """Per-row cross-entropy `lse(logits) - logits[label]`.

    logits: [..., V]; labels: int [...] (no trailing unit dim).
    Returns f32 loss of shape `labels.shape`. Rows whose label lies
    outside [0, V) get `loss = lse` and a pure-softmax gradient, which
    the caller masks out (ignore_index handling stays outside).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    V = logits.shape[-1]
    lead = logits.shape[:-1]
    x2d = logits.reshape(-1, V)
    lab2d = labels.reshape(-1, 1).astype(jnp.int32)
    loss = _softmax_xent(x2d, lab2d, interpret)
    return loss.reshape(lead)
