"""Fused softmax-cross-entropy Pallas kernel (interpret mode on CPU).

Mirrors the reference's softmax_with_cross_entropy op tests
(python/paddle/fluid/tests/unittests/test_softmax_with_cross_entropy_op.py):
forward vs a numpy/XLA logsumexp formula, gradient vs jax.grad of the
reference composition, ignore_index masking at the functional layer.
"""
import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.pallas.softmax_xent import (softmax_xent_arrays,
                                                supported, _choose_block)


def _ref_loss(x, lab):
    return (jax.nn.logsumexp(x.astype(jnp.float32), axis=-1) -
            jnp.take_along_axis(x.astype(jnp.float32),
                                lab[..., None].astype(jnp.int64),
                                -1)[..., 0])


class TestSoftmaxXentKernel:
    def test_forward_matches_logsumexp(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(64, 512) * 3, jnp.float32)
        lab = jnp.asarray(rng.randint(0, 512, 64), jnp.int32)
        loss = softmax_xent_arrays(x, lab, interpret=True)
        np.testing.assert_allclose(np.asarray(loss),
                                   np.asarray(_ref_loss(x, lab)),
                                   rtol=1e-5, atol=1e-5)

    def test_forward_3d_batch(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(4, 16, 256), jnp.float32)
        lab = jnp.asarray(rng.randint(0, 256, (4, 16)), jnp.int32)
        loss = softmax_xent_arrays(x, lab, interpret=True)
        assert loss.shape == (4, 16)
        np.testing.assert_allclose(np.asarray(loss),
                                   np.asarray(_ref_loss(x, lab)),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_matches_reference(self):
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(32, 384), jnp.float32)
        lab = jnp.asarray(rng.randint(0, 384, 32), jnp.int32)
        g_kernel = jax.grad(
            lambda x: jnp.mean(softmax_xent_arrays(x, lab,
                                                   interpret=True)))(x)
        g_ref = jax.grad(lambda x: jnp.mean(_ref_loss(x, lab)))(x)
        np.testing.assert_allclose(np.asarray(g_kernel), np.asarray(g_ref),
                                   rtol=1e-5, atol=1e-6)

    def test_out_of_range_label_is_pure_lse(self):
        # label -1 never matches a column: loss = lse, grad = softmax
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(8, 128), jnp.float32)
        lab = jnp.full((8,), -1, jnp.int32)
        loss = softmax_xent_arrays(x, lab, interpret=True)
        np.testing.assert_allclose(
            np.asarray(loss), np.asarray(jax.nn.logsumexp(x, axis=-1)),
            rtol=1e-5, atol=1e-5)

    def test_block_chooser(self):
        assert _choose_block(50304, 4096, 128) > 0
        assert 50304 % _choose_block(50304, 4096, 128) == 0
        assert _choose_block(8192, 4096, 128) == 4096
        # unaligned sizes are rejected (Mosaic (8,128) tiling rule) and
        # the caller falls back to the XLA composition
        assert _choose_block(1000, 4096, 128) == 0
        assert _choose_block(1024, 4096, 128) == 1024  # aligned, fits
        assert not supported(8192, 1000)
        assert supported(8192, 50304)

    def test_bf16_logits(self):
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.randn(16, 256), jnp.bfloat16)
        lab = jnp.asarray(rng.randint(0, 256, 16), jnp.int32)
        loss = softmax_xent_arrays(x, lab, interpret=True)
        np.testing.assert_allclose(np.asarray(loss),
                                   np.asarray(_ref_loss(x, lab)),
                                   rtol=1e-2, atol=1e-2)
