"""paddle.distributed.fleet facade.
Parity: python/paddle/distributed/fleet/__init__.py + base/fleet_base.py.

fleet.init(strategy) builds the hybrid mesh; distributed_model /
distributed_optimizer return wrappers whose jit path is the
HybridTrainStep SPMD program (hybrid_train.py).
"""
import sys as _sys

from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup
from .base.role_maker import (Role, PaddleCloudRoleMaker,
                              UserDefinedRoleMaker)
from .base.util_factory import UtilBase
from .data_generator import (MultiSlotDataGenerator,
                             MultiSlotStringDataGenerator)
from .hybrid_train import HybridTrainStep, default_param_rules
# reference path parity: paddle.distributed.fleet.meta_parallel is the
# same package as paddle.distributed.meta_parallel here. Alias the WHOLE
# subtree in sys.modules (importing a deep path under the alias alone
# would re-run modules with fleet-relative names and break their
# relative imports), so `from paddle.distributed.fleet.meta_parallel
# .parallel_layers import ColumnParallelLinear` works.
from .. import meta_parallel
import importlib as _importlib
import pkgutil as _pkgutil

_real = "paddle_tpu.distributed.meta_parallel"
for _m in _pkgutil.walk_packages(meta_parallel.__path__, _real + "."):
    try:
        _importlib.import_module(_m.name)
    except Exception as _e:  # a broken leaf shouldn't break `import fleet`,
        # but vanishing silently makes the later ModuleNotFoundError
        # undiagnosable — say which module failed and why
        import warnings as _warnings
        _warnings.warn(f"fleet: meta_parallel submodule {_m.name} failed "
                       f"to import and will be missing from the alias "
                       f"tree: {_e!r}")
for _name in [n for n in _sys.modules if n.startswith(_real)]:
    _sys.modules[_name.replace(_real, __name__ + ".meta_parallel", 1)] = \
        _sys.modules[_name]
from .utils.recompute import (recompute, recompute_sequential,
                              recompute_hybrid)

_state = {"strategy": None, "hcg": None, "initialized": False,
          "role_maker": None}

__all__ = ["init", "Fleet", "DistributedStrategy", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "HybridTrainStep", "worker_index", "worker_num", "is_worker",
           "barrier_worker", "recompute", "utils", "UtilBase", "Role",
           "PaddleCloudRoleMaker", "UserDefinedRoleMaker",
           "MultiSlotDataGenerator", "MultiSlotStringDataGenerator",
           "elastic_controller"]


def elastic_controller(train_step, ckpt_dir, **kwargs):
    """Fault-tolerance wiring for a fleet train loop: an
    `ElasticController` (distributed/elastic.py) over the hybrid step —
    verified resume from the newest committed checkpoint, async
    snapshot-then-write saves on a step cadence, and a watchdog that
    dumps a debug bundle before SIGTERM. See docs/FAULT_TOLERANCE.md.

        step = fleet.build_train_step(model, loss_fn, opt)
        ctl = fleet.elastic_controller(step, "ckpts", save_every_steps=500)
        start = ctl.maybe_resume()
        ctl.start_watchdog()
    """
    from ..elastic import ElasticController
    return ElasticController(train_step, ckpt_dir, **kwargs)


def init(role_maker=None, is_collective=True, strategy=None):
    if strategy is None:
        strategy = DistributedStrategy()
    _state["strategy"] = strategy
    hc = strategy.hybrid_configs
    topo = CommunicateTopology(
        ("data", "sharding", "pipe", "model", "sep", "expert"),
        (hc.get("dp_degree", 1), hc.get("sharding_degree", 1),
         hc.get("pp_degree", 1), hc.get("mp_degree", 1),
         hc.get("sep_degree", 1), hc.get("ep_degree", 1)))
    _state["hcg"] = HybridCommunicateGroup(topo)
    _state["role_maker"] = role_maker
    if role_maker is not None:
        util._role_maker = role_maker
    _state["initialized"] = True
    return None


def is_initialized():
    return _state["initialized"]


def get_hybrid_communicate_group():
    if _state["hcg"] is None:
        init()
    return _state["hcg"]


def get_strategy():
    return _state["strategy"]


def fleet_mesh():
    return get_hybrid_communicate_group().mesh


class _PipelineStepAdapter:
    """Gives a PipelineParallel engine the HybridTrainStep call shape
    (step(x, y) -> loss Tensor) so fleet users drive pp and non-pp
    training identically."""

    def __init__(self, engine):
        self.engine = engine
        self.optimizer = engine.optimizer

    def __call__(self, x, y):
        return self.engine.train_batch(x, y)

    def forward(self, x):
        return self.engine.forward(x)


class _DistributedModel:
    """Wrapper returned by fleet.distributed_model: behaves like the layer
    in eager mode; exposes .train_step_builder() for the SPMD path."""

    def __init__(self, layer):
        self._layer = layer

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._layer, name)

    @property
    def wrapped(self):
        return self._layer


def distributed_model(model):
    return _DistributedModel(model)


def distributed_optimizer(optimizer, strategy=None):
    optimizer._fleet = True
    return optimizer


def build_train_step(model, loss_fn, optimizer, recompute=None,
                     accumulate_steps=None, param_dtype=None,
                     sharding_stage=None):
    """Assemble the hybrid-parallel jitted train step from fleet state.

    sharding_stage resolution order: explicit arg > ShardingStage2/3
    wrapper markers on the model/optimizer > strategy.sharding_configs
    ["stage"] > 1."""
    strat = _state["strategy"] or DistributedStrategy()
    for flag in ("dgc", "asp"):
        if getattr(strat, flag, False):
            # refuse rather than silently ignore: a no-op strategy flag
            # corrupts experiments. Scope rationale (SURVEY.md §3): dgc is
            # a gradient-compression hack for bandwidth-starved GPU
            # clusters — on TPU the dp psum rides ICI and XLA already
            # overlaps it with compute; asp (2:4 structured sparsity) targets
            # NVIDIA sparse tensor cores, which the MXU does not have.
            raise NotImplementedError(
                f"DistributedStrategy.{flag} is out of scope on TPU (see "
                f"SURVEY.md §3); unset it or use supported strategies "
                f"(amp/recompute/sharding/localsgd/gradient_merge/"
                f"lars/lamb)")
    if strat.localsgd:
        # same honesty policy as dgc/asp: composing localsgd with other
        # strategy mechanisms is not implemented — refuse rather than
        # silently run a step that ignores them
        combo = [f for f in ("amp", "recompute", "sharding", "pipeline",
                             "tensor_parallel", "gradient_merge", "lamb",
                             "lars") if getattr(strat, f, False)]
        if combo:
            raise NotImplementedError(
                f"DistributedStrategy.localsgd cannot be combined with "
                f"{combo} in paddle_tpu — run localsgd alone (pure dp)")
        from .localsgd import LocalSGDTrainStep
        hcg_ = get_hybrid_communicate_group()
        cfg = strat.localsgd_configs
        return LocalSGDTrainStep(model if not isinstance(
            model, _DistributedModel) else model.wrapped,
            loss_fn, optimizer, hcg_.mesh,
            k_steps=cfg.get("k_steps", 4),
            begin_step=cfg.get("begin_step", 1))
    if strat.lamb:
        from ...optimizer import Adam, AdamW, Lamb
        if isinstance(optimizer, Adam) and not isinstance(optimizer, Lamb):
            cfg = strat.lamb_configs
            optimizer = Lamb(
                learning_rate=optimizer._learning_rate,
                lamb_weight_decay=cfg.get("lamb_weight_decay", 0.01),
                beta1=optimizer._beta1, beta2=optimizer._beta2,
                epsilon=optimizer._epsilon,
                parameters=optimizer._parameters,
                grad_clip=optimizer._grad_clip)
    if strat.lars:
        from ...optimizer import Momentum, LarsMomentum
        if isinstance(optimizer, Momentum) and \
                not isinstance(optimizer, LarsMomentum):
            cfg = strat.lars_configs
            optimizer = LarsMomentum(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                lars_coeff=cfg.get("lars_coeff", 0.001),
                lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
                parameters=optimizer._parameters,
                grad_clip=optimizer._grad_clip,
                epsilon=cfg.get("epsilon", 1e-9))
    hcg = get_hybrid_communicate_group()
    if sharding_stage is None:
        sharding_stage = getattr(model, "_sharding_stage", None) \
            or getattr(optimizer, "_sharding_stage", None) \
            or (strat.sharding_configs.get("stage", 1)
                if strat.sharding else 1)
    if isinstance(model, _DistributedModel):
        model = model.wrapped
    # unwrap ShardingStage2/3 shells down to the real layer/optimizer
    model = getattr(model, "_layer", model)
    optimizer = getattr(optimizer, "_optim", optimizer)

    # pipeline parallelism routes through the PipelineParallel engine
    # (the reference's fleet.distributed_model does the same wrap for
    # PipelineLayer models — meta_parallel/__init__.py)
    from ..meta_parallel import PipelineLayer, PipelineParallel
    pp_deg = strat.hybrid_configs.get("pp_degree", 1)
    if isinstance(model, PipelineLayer):
        if hcg.mesh.shape.get("pp", 1) != model.num_stages:
            raise ValueError(
                f"PipelineLayer has {model.num_stages} stages but the "
                f"mesh 'pp' axis is {hcg.mesh.shape.get('pp', 1)} — set "
                f"hybrid_configs['pp_degree'] = num_stages")
        sched = strat.pipeline_configs.get("schedule_mode", "1F1B")
        n_micro = strat.pipeline_configs.get("accumulate_steps", 1)
        return _PipelineStepAdapter(PipelineParallel(
            model, optimizer, hcg.mesh, n_micro=max(n_micro, 1),
            loss_fn=loss_fn, schedule=sched))
    if pp_deg > 1:
        raise ValueError(
            f"pp_degree={pp_deg} requires the model to be a "
            f"PipelineLayer (wrap your stack in LayerDesc/SharedLayerDesc)"
            f" — a plain Layer cannot be stage-partitioned")
    if recompute is None:
        recompute = strat.recompute
    if strat.amp and param_dtype is None:
        # strategy.amp maps to mixed-precision compute: parameters cast
        # to bf16 (fp16 when use_bf16=False) inside the jitted step; on
        # TPU bf16 keeps fp32 range so no loss scaling is needed (the
        # reference's GradScaler path is an fp16 artifact)
        ac = strat.amp_configs
        param_dtype = "bfloat16" if ac.get("use_bf16", True) \
            else "float16"
    if accumulate_steps is None:
        accumulate_steps = strat.pipeline_configs.get("accumulate_steps", 1) \
            if strat.pipeline else \
            strat.gradient_merge_configs.get("k_steps", 1) \
            if strat.gradient_merge else 1
    return HybridTrainStep(model, loss_fn, optimizer, hcg.mesh,
                           recompute=recompute,
                           accumulate_steps=accumulate_steps,
                           param_dtype=param_dtype,
                           sharding_stage=sharding_stage)


def worker_index():
    import jax
    return jax.process_index()


def worker_num():
    import jax
    return jax.process_count()


def is_worker():
    return True


def is_server():
    return False


def barrier_worker():
    from ..env import barrier
    barrier()


class utils:  # namespace parity: fleet.utils.recompute
    recompute = staticmethod(recompute)
    recompute_sequential = staticmethod(recompute_sequential)
    recompute_hybrid = staticmethod(recompute_hybrid)


util = UtilBase()


class Fleet:
    """Object-style facade over this module (ref: base/fleet_base.py —
    there `fleet` is a singleton instance of Fleet; here the module IS
    the singleton, and Fleet instances delegate to it)."""

    def __init__(self):
        self.util = util

    def init(self, role_maker=None, is_collective=True, strategy=None):
        init(role_maker, is_collective, strategy)
        return self

    def is_initialized(self):
        return is_initialized()

    def distributed_model(self, model):
        return distributed_model(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    def worker_index(self):
        return worker_index()

    def worker_num(self):
        return worker_num()

    def is_worker(self):
        return is_worker()

    def is_server(self):
        return is_server()

    def is_first_worker(self):
        return worker_index() == 0

    def barrier_worker(self):
        barrier_worker()
