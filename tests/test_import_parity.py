"""Reference import-path parity: every `from paddle.X.Y import Z` form a
migrating user relies on must resolve as a real module path here."""
import importlib

import pytest


@pytest.mark.parametrize("path,names", [
    ("paddle_tpu.incubate.nn",
     ["FusedMultiHeadAttention", "FusedFeedForward", "MoELayer"]),
    ("paddle_tpu.incubate.optimizer", ["LookAhead", "ModelAverage"]),
    ("paddle_tpu.device.cuda",
     ["synchronize", "device_count", "max_memory_allocated", "Stream",
      "Event"]),
    ("paddle_tpu.distributed.fleet.meta_parallel",
     ["PipelineLayer", "PipelineParallel"]),
    ("paddle_tpu.distributed.fleet.meta_parallel.parallel_layers",
     ["ColumnParallelLinear", "RowParallelLinear",
      "VocabParallelEmbedding"]),
    ("paddle_tpu.distributed.fleet.meta_parallel.sharding", []),
    ("paddle_tpu.nn.functional", ["relu", "cross_entropy"]),
    ("paddle_tpu.optimizer.lr", ["LRScheduler", "NoamDecay"]),
    ("paddle_tpu.vision.transforms", ["Compose", "Resize"]),
    ("paddle_tpu.static.nn", ["fc", "cond", "while_loop"]),
])
def test_module_path_and_names(path, names):
    mod = importlib.import_module(path)
    for n in names:
        assert hasattr(mod, n), f"{path}.{n} missing"


def test_fleet_alias_is_same_package():
    import paddle_tpu.distributed.meta_parallel as real
    import paddle_tpu.distributed.fleet.meta_parallel as aliased
    assert aliased is real
