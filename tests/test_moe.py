"""Expert-parallel MoE layer (beyond-parity; GShard-style dense
dispatch): routing numerics vs a per-token oracle, training, and expert
sharding over the 'ep' mesh axis through fleet."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.incubate as incubate
from paddle_tpu import optimizer as opt
from paddle_tpu.distributed import fleet

pytestmark = [pytest.mark.slow, pytest.mark.heavy]  # multi-minute: out of tier-1 and the quick gate


def _dense_oracle_top1(x2d, moe):
    """Route each token to its argmax expert, no capacity drops."""
    gw = moe.gate_weight.numpy()
    w1, b1 = moe.w1.numpy(), moe.b1.numpy()
    w2, b2 = moe.w2.numpy(), moe.b2.numpy()
    logits = x2d @ gw
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    idx = logits.argmax(-1)
    out = np.zeros_like(x2d)
    from scipy.special import erf  # gelu oracle

    def gelu(a):
        return 0.5 * a * (1 + erf(a / np.sqrt(2.0)))

    for n in range(len(x2d)):
        e = idx[n]
        h = gelu(x2d[n] @ w1[e] + b1[e])
        out[n] = (h @ w2[e] + b2[e]) * 1.0  # top-1: combine weight = 1
    return out


class TestMoE:
    def test_top1_matches_oracle(self):
        paddle.seed(0)
        moe = incubate.nn.MoELayer(d_model=8, d_hidden=16, num_experts=4,
                                   top_k=1, capacity_factor=8.0)
        rng = np.random.RandomState(0)
        x = rng.randn(2, 6, 8).astype(np.float32)
        y = moe(paddle.to_tensor(x))
        ref = _dense_oracle_top1(x.reshape(-1, 8), moe).reshape(2, 6, 8)
        np.testing.assert_allclose(y.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_top2_runs_and_aux_loss(self):
        paddle.seed(0)
        moe = incubate.nn.MoELayer(d_model=8, d_hidden=16, num_experts=4,
                                   top_k=2)
        x = paddle.randn([2, 8, 8])
        y = moe(x)
        assert y.shape == [2, 8, 8]
        aux = float(moe.aux_loss().item())
        # perfectly balanced routing gives aux = 1; anything sane is O(1)
        assert 0.5 < aux < 4.0, aux

    def test_capacity_drops_tokens(self):
        """With capacity 1 slot per expert most tokens drop to zero
        output — the dense dispatch must mask them, not corrupt others."""
        paddle.seed(0)
        moe = incubate.nn.MoELayer(d_model=4, d_hidden=8, num_experts=2,
                                   top_k=1, capacity_factor=0.01)
        x = paddle.randn([1, 8, 4])
        assert moe.capacity(8) == 1
        y = moe(x)
        zero_rows = np.sum(np.abs(y.numpy().reshape(-1, 4)).sum(-1) < 1e-7)
        assert zero_rows >= 6  # 8 tokens, 2 experts x 1 slot

    def test_trains_with_aux_loss(self):
        paddle.seed(0)
        moe = incubate.nn.MoELayer(d_model=8, d_hidden=16, num_experts=4,
                                   top_k=2)
        head = nn.Linear(8, 2)
        o = opt.Adam(learning_rate=5e-3,
                     parameters=moe.parameters() + head.parameters())
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(4, 6, 8).astype(np.float32))
        t = paddle.to_tensor(rng.randint(0, 2, (4,)).astype(np.int64))
        ce = nn.CrossEntropyLoss()
        l0 = None
        for _ in range(12):
            logits = head(moe(x).mean(axis=1))
            loss = ce(logits, t) + 0.01 * moe.aux_loss()
            loss.backward()
            o.step()
            o.clear_grad()
            l0 = l0 or float(loss.item())
        assert float(loss.item()) < l0
        assert moe.gate_weight.grad is None  # cleared

    @pytest.mark.heavy
    def test_expert_parallel_through_fleet(self):
        """ep_degree=4: expert stacks shard over 'ep'; loss matches the
        replicated (ep=1) run."""
        class MoENet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.embed = nn.Embedding(64, 16)
                self.moe = incubate.nn.MoELayer(16, 32, num_experts=4,
                                                top_k=2)
                self.head = nn.Linear(16, 64)

            def forward(self, ids):
                return self.head(self.moe(self.embed(ids)))

        def run(ep):
            strategy = fleet.DistributedStrategy()
            strategy.hybrid_configs["dp_degree"] = 2
            strategy.hybrid_configs["ep_degree"] = ep
            fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(0)
            m = MoENet()
            o = opt.SGD(learning_rate=0.01, parameters=m.parameters())

            def loss_fn(out, y):
                return nn.functional.cross_entropy(
                    out.reshape([-1, 64]), y.reshape([-1]))

            step = fleet.build_train_step(m, loss_fn, o)
            if ep > 1:
                assert "ep" in str(step.params["moe.w1"].sharding.spec)
            ids = paddle.to_tensor(np.random.RandomState(0).randint(
                0, 64, size=(8, 8)))
            return [step(ids, ids).item() for _ in range(2)]

        base = run(1)
        par = run(4)
        np.testing.assert_allclose(base, par, rtol=1e-4, atol=1e-5)


class TestAuxLossInJittedStep:
    """The load-balancing loss must be added INSIDE TrainStep/fleet's
    compiled program (loss_fn can't reach it), and aux_loss() must fail
    loudly rather than hand back a leaked tracer afterwards."""

    def _net(self):
        class MoENet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.embed = nn.Embedding(64, 16)
                self.moe = incubate.nn.MoELayer(16, 32, num_experts=4,
                                                top_k=2,
                                                aux_loss_weight=0.5)
                self.head = nn.Linear(16, 64)

            def forward(self, ids):
                return self.head(self.moe(self.embed(ids)))
        paddle.seed(0)
        return MoENet()

    def test_trainstep_loss_includes_aux(self):
        from paddle_tpu.jit import TrainStep
        m = self._net()

        def loss_fn(out, y):
            return nn.functional.cross_entropy(
                out.reshape([-1, 64]), y.reshape([-1]))

        o = opt.SGD(learning_rate=0.0, parameters=m.parameters())
        step = TrainStep(m, loss_fn, o)
        ids = paddle.to_tensor(np.random.RandomState(0).randint(
            0, 64, size=(4, 8)))
        jitted_loss = float(step(ids, ids).item())

        logits = m(ids)  # eager forward with the same (lr=0) params
        task = float(loss_fn(logits, ids).item())
        aux = float(m.moe.aux_loss().item())
        np.testing.assert_allclose(jitted_loss, task + 0.5 * aux,
                                   rtol=1e-5)

    def test_aux_accessor_refuses_leaked_tracer(self):
        import pytest
        from paddle_tpu.jit import TrainStep
        m = self._net()

        def loss_fn(out, y):
            return nn.functional.cross_entropy(
                out.reshape([-1, 64]), y.reshape([-1]))

        o = opt.SGD(learning_rate=0.0, parameters=m.parameters())
        step = TrainStep(m, loss_fn, o)
        ids = paddle.to_tensor(np.random.RandomState(0).randint(
            0, 64, size=(4, 8)))
        step(ids, ids)
        with pytest.raises(RuntimeError, match="jitted step"):
            m.moe.aux_loss()


class TestGPTMoE:
    """GPT with MoE blocks (gpt_moe): eager training, and dp x ep fleet
    training with the aux loss folded in automatically."""

    def _cfg(self):
        from paddle_tpu.models.gpt import GPTConfig
        return GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                         num_heads=4, max_position_embeddings=32,
                         dropout=0.0, num_experts=4, moe_every=2)

    def test_moe_blocks_placed(self):
        from paddle_tpu.models.gpt import GPTForCausalLM
        from paddle_tpu.incubate.moe import MoELayer
        paddle.seed(0)
        m = GPTForCausalLM(self._cfg())
        kinds = [type(b.mlp).__name__ for b in m.gpt.h]
        assert kinds == ["GPTMLP", "MoELayer", "GPTMLP", "MoELayer"]

    def test_moe_with_unrolled_remat_trains(self):
        # scan_remat on an unrolled MoE stack: dense blocks get
        # jax.checkpoint, MoE blocks run unwrapped (their aux-loss side
        # channel cannot cross a checkpoint trace)
        import numpy as np
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models.gpt import GPTForCausalLM
        from paddle_tpu import optimizer as opt
        import paddle_tpu.nn as nn
        cfg = self._cfg()
        cfg.scan_remat = "names"
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())

        def loss_fn(lg, y):
            V = lg.shape[-1]
            return nn.functional.cross_entropy(
                lg.reshape([-1, V]), y.reshape([-1]))

        step = TrainStep(m, loss_fn, o)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(
            rng.randint(0, 128, (2, 16)).astype(np.int32))
        l0 = float(step(ids, ids).item())
        for _ in range(8):
            l = step(ids, ids)
        assert float(l.item()) < l0

    def test_trains_through_fleet_dp_ep(self):
        from paddle_tpu.models.gpt import GPTForCausalLM
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["dp_degree"] = 2
        strategy.hybrid_configs["ep_degree"] = 4
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = GPTForCausalLM(self._cfg())
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())

        def loss_fn(out, y):
            return nn.functional.cross_entropy(
                out.reshape([-1, 128]), y.reshape([-1]))

        step = fleet.build_train_step(m, loss_fn, o)
        assert "ep" in str(step.params["gpt.h.1.mlp.w1"].sharding.spec)
        ids = paddle.to_tensor(np.random.RandomState(0).randint(
            0, 128, size=(8, 16)))
        l0 = step(ids, ids).item()
        for _ in range(3):
            l = step(ids, ids).item()
        assert np.isfinite(l) and l < l0
