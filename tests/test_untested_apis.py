"""Coverage for previously-untested public APIs, mostly vs torch-cpu
oracles: interpolate, grid_sample, affine_grid, Unfold/Fold,
pixel_shuffle, MaxUnPool2D, temporal_shift, SpectralNorm, hapi
callbacks, profiler."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick commit gate no


def _t(a):
    import torch
    return torch.tensor(np.asarray(a))


class TestInterpolate:
    def test_bilinear_matches_torch(self):
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        x = rng.rand(2, 3, 8, 8).astype(np.float32)
        for align in (False, True):
            got = F.interpolate(paddle.to_tensor(x), size=[16, 16],
                                mode="bilinear",
                                align_corners=align).numpy()
            want = tF.interpolate(_t(x), size=(16, 16), mode="bilinear",
                                  align_corners=align).numpy()
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_nearest_and_scale_factor(self):
        import torch.nn.functional as tF
        rng = np.random.RandomState(1)
        x = rng.rand(1, 2, 5, 5).astype(np.float32)
        got = F.interpolate(paddle.to_tensor(x), scale_factor=2,
                            mode="nearest").numpy()
        want = tF.interpolate(_t(x), scale_factor=2,
                              mode="nearest").numpy()
        np.testing.assert_allclose(got, want)


class TestGridSample:
    def test_bilinear_zeros_matches_torch(self):
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        x = rng.rand(2, 3, 6, 7).astype(np.float32)
        grid = (rng.rand(2, 5, 4, 2).astype(np.float32) * 2 - 1)
        for align in (True, False):
            got = F.grid_sample(paddle.to_tensor(x),
                                paddle.to_tensor(grid),
                                align_corners=align).numpy()
            want = tF.grid_sample(_t(x), _t(grid), mode="bilinear",
                                  padding_mode="zeros",
                                  align_corners=align).numpy()
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_affine_grid_matches_torch(self):
        import torch.nn.functional as tF
        theta = np.array([[[1.0, 0.2, 0.1], [0.0, 0.9, -0.3]]],
                         np.float32)
        for align in (True, False):
            got = F.affine_grid(paddle.to_tensor(theta),
                                [1, 3, 4, 5],
                                align_corners=align).numpy()
            want = tF.affine_grid(_t(theta), (1, 3, 4, 5),
                                  align_corners=align).numpy()
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestUnfoldFold:
    def test_unfold_matches_torch(self):
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        x = rng.rand(2, 3, 8, 8).astype(np.float32)
        got = nn.Unfold(kernel_sizes=3, strides=2,
                        paddings=1)(paddle.to_tensor(x)).numpy()
        want = tF.unfold(_t(x), kernel_size=3, stride=2,
                         padding=1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_fold_roundtrip(self):
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        cols = rng.rand(1, 3 * 2 * 2, 9).astype(np.float32)
        got = nn.Fold(output_sizes=[4, 4], kernel_sizes=2,
                      strides=1)(paddle.to_tensor(cols)).numpy()
        want = tF.fold(_t(cols), output_size=(4, 4), kernel_size=2,
                       stride=1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)


class TestPixelOps:
    def test_pixel_shuffle_matches_torch(self):
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        x = rng.rand(2, 8, 3, 3).astype(np.float32)
        got = F.pixel_shuffle(paddle.to_tensor(x), 2).numpy()
        want = tF.pixel_shuffle(_t(x), 2).numpy()
        np.testing.assert_allclose(got, want)

    def test_max_unpool2d_inverts_pool(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        x = rng.rand(1, 2, 6, 6).astype(np.float32)
        pooled, idx = F.max_pool2d(paddle.to_tensor(x), 2,
                                   return_mask=True)
        got = nn.MaxUnPool2D(kernel_size=2)(pooled, idx).numpy()
        tp, ti = tF.max_pool2d(_t(x), 2, return_indices=True)
        want = tF.max_unpool2d(tp, ti, 2).numpy()
        np.testing.assert_allclose(got, want)

    def test_temporal_shift_semantics(self):
        # [N*T, C, H, W]: first quarter channels shift -1 in time,
        # second quarter +1, rest untouched (TSM)
        N, T, C, H, W = 1, 4, 8, 2, 2
        x = np.arange(N * T * C * H * W, dtype=np.float32).reshape(
            N * T, C, H, W)
        out = F.temporal_shift(paddle.to_tensor(x), seg_num=T,
                               shift_ratio=0.25).numpy()
        xr = x.reshape(N, T, C, H, W)
        want = np.zeros_like(xr)
        fold = C // 4
        want[:, :-1, :fold] = xr[:, 1:, :fold]       # shift left
        want[:, 1:, fold:2 * fold] = xr[:, :-1, fold:2 * fold]
        want[:, :, 2 * fold:] = xr[:, :, 2 * fold:]
        np.testing.assert_allclose(out, want.reshape(N * T, C, H, W))


class TestSpectralNorm:
    def test_output_has_unit_spectral_norm(self):
        paddle.seed(0)
        sn = nn.SpectralNorm([8, 6], dim=0, power_iters=20)
        w = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 6).astype(np.float32) * 3)
        out = sn(w)
        sigma = np.linalg.svd(out.numpy(), compute_uv=False)[0]
        assert abs(sigma - 1.0) < 0.05, sigma


class TestHapiCallbacks:
    def _model_and_data(self):
        from paddle_tpu.hapi.model import Model
        from paddle_tpu.io import Dataset, DataLoader
        from paddle_tpu import optimizer as opt
        from paddle_tpu.metric import Accuracy

        class DS(Dataset):
            def __init__(self, n=32):
                rng = np.random.RandomState(0)
                self.x = rng.rand(n, 4).astype(np.float32)
                self.y = rng.randint(0, 2, n).astype(np.int64)

            def __len__(self):
                return len(self.x)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        m = Model(net)
        m.prepare(opt.Adam(learning_rate=1e-2,
                           parameters=net.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
        return m, DataLoader(DS(), batch_size=8)

    def test_early_stopping_halts(self):
        from paddle_tpu.hapi.callbacks import EarlyStopping
        m, loader = self._model_and_data()
        es = EarlyStopping(monitor="loss", patience=0, min_delta=1e9,
                           mode="min")  # impossible delta: stop asap
        m.fit(loader, loader, epochs=10, callbacks=[es], verbose=0)
        assert es.stopped_epoch is not None and es.stopped_epoch < 9

    def test_model_checkpoint_writes(self):
        from paddle_tpu.hapi.callbacks import ModelCheckpoint
        m, loader = self._model_and_data()
        d = tempfile.mkdtemp()
        m.fit(loader, epochs=1,
              callbacks=[ModelCheckpoint(save_freq=1, save_dir=d)],
              verbose=0)
        found = []
        for root, _, files in os.walk(d):
            found += files
        assert found, "checkpoint wrote nothing"


class TestProfilerSmoke:
    def test_profiler_records(self):
        import paddle_tpu.profiler as profiler
        d = tempfile.mkdtemp()
        try:
            with profiler.Profiler(
                    targets=[profiler.ProfilerTarget.CPU],
                    on_trace_ready=profiler.export_chrome_tracing(d)):
                x = paddle.randn([32, 32])
                (x @ x).numpy()
        except Exception as e:
            pytest.skip(f"profiler backend unavailable here: {e}")
