"""Semi-auto parallel annotations. Parity:
python/paddle/distributed/auto_parallel/ (shard_tensor / shard_op +
planner). TPU-native: these ARE jax's native GSPMD annotations —
shard_tensor places/constrains an array with a NamedSharding and XLA's
partitioner (the production auto-parallel planner) propagates shardings
through the whole program.
"""
import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ...framework.core import Tensor, apply_op
from ..env import get_mesh

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "Planner", "plan"]


class ProcessMesh:
    """Parity: auto_parallel/process_mesh.py."""

    def __init__(self, mesh=None, dim_names=None, shape=None,
                 process_ids=None):
        if mesh is not None and hasattr(mesh, "devices"):
            self._mesh = mesh
        else:
            shape = shape or (np.asarray(mesh).shape if mesh is not None
                              else (jax.device_count(),))
            dim_names = dim_names or [f"d{i}" for i in range(len(shape))]
            devs = np.array(jax.devices()[:int(np.prod(shape))]
                            ).reshape(shape)
            self._mesh = Mesh(devs, tuple(dim_names))

    @property
    def mesh(self):
        return self._mesh

    @property
    def shape(self):
        return tuple(self._mesh.devices.shape)

    @property
    def dim_names(self):
        return tuple(self._mesh.axis_names)


def _to_spec(dist_attr, ndim):
    if dist_attr is None:
        return PartitionSpec()
    if isinstance(dist_attr, PartitionSpec):
        return dist_attr
    if isinstance(dist_attr, dict):
        dims = dist_attr.get("dims_mapping",
                             dist_attr.get("sharding_specs"))
    else:
        dims = dist_attr
    return PartitionSpec(*[d if isinstance(d, str) and d else None
                           for d in list(dims)[:ndim]])


def shard_tensor(x, process_mesh=None, shard_spec=None, dist_attr=None):
    mesh = process_mesh.mesh if isinstance(process_mesh, ProcessMesh) \
        else (process_mesh or get_mesh())
    spec = _to_spec(shard_spec if shard_spec is not None else dist_attr,
                    x.ndim if hasattr(x, "ndim") else 0)
    sharding = NamedSharding(mesh, spec)
    arr = x.value if isinstance(x, Tensor) else x
    if isinstance(arr, jax.core.Tracer):
        out = jax.lax.with_sharding_constraint(arr, sharding)
        return Tensor(out) if isinstance(x, Tensor) else out
    placed = jax.device_put(arr, sharding)
    if isinstance(x, Tensor):
        x._bind(Tensor(placed)._slot)
        return x
    return placed


def shard_op(op_fn, process_mesh=None, in_shard_specs=None,
             out_shard_specs=None, **kwargs):
    """Parity: auto_parallel/interface.py:shard_op — constrain an op's
    inputs and outputs to dist specs; GSPMD partitions the op body."""
    mesh = process_mesh.mesh if isinstance(process_mesh, ProcessMesh) \
        else (process_mesh or get_mesh())
    pm = ProcessMesh(mesh)

    def wrapped(*args):
        if in_shard_specs is not None:
            in_specs = in_shard_specs if isinstance(in_shard_specs, list) \
                else [in_shard_specs]
            args = tuple(
                shard_tensor(a, pm, s) if s is not None else a
                for a, s in zip(args, list(in_specs)
                                + [None] * (len(args) - len(in_specs))))
        out = op_fn(*args)
        if out_shard_specs is not None:
            specs = out_shard_specs if isinstance(out_shard_specs, list) \
                else [out_shard_specs]
            outs = out if isinstance(out, (list, tuple)) else [out]
            new = []
            for o, s in zip(outs, specs):
                new.append(shard_tensor(o, pm, s))
            return new if isinstance(out, (list, tuple)) else new[0]
        return out
    return wrapped


class Planner:
    """Sharding planner. Parity: auto_parallel/planner.py (PlanSpace +
    MCMC search over per-op dims_mappings). TPU-native: XLA's GSPMD
    propagation IS the search — given input/param annotations it assigns
    a sharding to every intermediate while minimizing resharding. plan()
    compiles the function and returns the concrete shardings XLA chose
    for inputs and outputs (inspectable, and reusable as constraints)."""

    def __init__(self, process_mesh=None):
        mesh = process_mesh.mesh if isinstance(process_mesh, ProcessMesh) \
            else (process_mesh or get_mesh())
        self.mesh = mesh

    def plan(self, fn, *example_args, in_specs=None, search=False,
             max_candidates=32):
        """Compile `fn` under sharding annotations. With search=True (and
        no explicit in_specs) this is a MEASURED chooser, honoring the
        reference planner's intent (auto_parallel/planner.py PlanSpace
        search + cost_model.py): enumerate candidate input PartitionSpecs,
        compile each, rank by XLA's own cost_analysis, keep the cheapest."""
        arrays = [a.value if isinstance(a, Tensor) else jnp_asarray(a)
                  for a in example_args]
        if in_specs is None and search:
            return self._search(fn, arrays, max_candidates)
        if in_specs is not None:
            shardings = tuple(
                NamedSharding(self.mesh, _to_spec(s, a.ndim))
                for s, a in zip(in_specs, arrays))
            jitted = jax.jit(fn, in_shardings=shardings)
        else:
            jitted = jax.jit(fn)
        compiled = jitted.lower(*arrays).compile()
        return PlanResult(compiled)

    # -- measured search ------------------------------------------------
    def _arg_candidates(self, arr):
        """Per-argument spec shortlist: replicated, plus each usable mesh
        axis on each divisible array dim."""
        cands = [PartitionSpec()]
        for ax, deg in self.mesh.shape.items():
            if deg <= 1:
                continue
            for d in range(arr.ndim):
                if arr.shape[d] % deg == 0 and arr.shape[d] >= deg:
                    spec = [None] * arr.ndim
                    spec[d] = ax
                    cands.append(PartitionSpec(*spec))
        return cands

    @staticmethod
    def _cost_of(compiled):
        """Scalar rank from XLA's analytical model: per-device flops plus
        bytes accessed (the HBM roofline terms). Missing analysis ranks
        worst so an un-analyzable candidate never wins silently."""
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return float(ca.get("flops", 0.0)) + \
                float(ca.get("bytes accessed", 0.0))
        except Exception:
            return float("inf")

    def _search(self, fn, arrays, max_candidates):
        import itertools
        per_arg = [self._arg_candidates(a) for a in arrays]
        # fair sampling under the budget: plain product varies the LAST
        # arg fastest, so truncating it would never shard the first args.
        # Guarantee coverage of (a) fully replicated, (b) every one-arg
        # sharding for EVERY arg, then fill the rest from the product.
        combos, seen = [], set()

        def add(c):
            if c not in seen:
                seen.add(c)
                combos.append(c)

        add(tuple(PartitionSpec() for _ in per_arg))
        for i, cands in enumerate(per_arg):
            for s in cands[1:]:
                add(tuple(s if j == i else PartitionSpec()
                          for j in range(len(per_arg))))
        for c in itertools.product(*per_arg):
            if len(combos) >= max_candidates:
                break
            add(c)
        total = 1
        for cands in per_arg:
            total *= len(cands)
        truncated = total > len(combos)
        rep = tuple(PartitionSpec() for _ in arrays)
        report = []
        best = None
        rep_compiled = None  # kept so an all-inf fallback needs no recompile
        for specs in combos[:max_candidates]:
            try:
                shardings = tuple(NamedSharding(self.mesh, s)
                                  for s in specs)
                compiled = jax.jit(fn, in_shardings=shardings) \
                    .lower(*arrays).compile()
            except Exception:
                continue  # invalid combination for this fn
            if specs == rep:
                rep_compiled = compiled
            cost = self._cost_of(compiled)
            report.append((specs, cost))
            if best is None or cost < best[1]:
                best = (specs, cost, compiled)
        if best is None:
            raise RuntimeError("auto_parallel search: no candidate "
                               "sharding compiled successfully")
        if best[1] == float("inf") and rep_compiled is not None \
                and best[0] != rep:
            # cost_analysis unavailable everywhere: a "measured" winner
            # would be arbitrary — prefer the fully-replicated plan, loudly
            import warnings
            warnings.warn(
                "auto_parallel search: XLA cost_analysis unavailable for "
                "every candidate; preferring the fully-replicated plan")
            best = (rep, float("inf"), rep_compiled)
        result = PlanResult(best[2])
        result.chosen_specs = best[0]
        result.search_report = sorted(report, key=lambda t: t[1])
        result.search_truncated = truncated  # caller can raise the budget
        return result


class PlanResult:
    def __init__(self, compiled):
        self.compiled = compiled

    @property
    def input_shardings(self):
        return self.compiled.input_shardings

    @property
    def output_shardings(self):
        return self.compiled.output_shardings

    def cost(self):
        """Analytical cost report from XLA (flops/bytes when available),
        the role of the reference's cost_model.py."""
        try:
            ca = self.compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return dict(ca)
        except Exception:
            return {}

    def __call__(self, *args):
        arrays = [a.value if isinstance(a, Tensor) else jnp_asarray(a)
                  for a in args]
        return self.compiled(*arrays)


def jnp_asarray(a):
    import jax.numpy as jnp
    return jnp.asarray(a)


def plan(fn, *example_args, process_mesh=None, in_specs=None):
    return Planner(process_mesh).plan(fn, *example_args,
                                      in_specs=in_specs)
