"""1F1B pipeline schedule: numerics vs GPipe, depth-bounded activation
memory, and SharedLayerDesc tied embedding/head (ref
fleet/meta_parallel/pipeline_parallel.py:81,170, pp_layers.py:49)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.distributed.env import build_mesh
from paddle_tpu.distributed.meta_parallel import (PipelineLayer,
                                                  PipelineParallel,
                                                  LayerDesc,
                                                  SharedLayerDesc)


def _make(schedule, n_micro=4, lr=0.02, seed=0):
    paddle.seed(seed)
    mesh = build_mesh(dp=1, pp=4, mp=1, devices=jax.devices()[:4])
    pipe = PipelineLayer(
        [LayerDesc(nn.Linear, 16, 16) for _ in range(8)],
        num_stages=4, loss_fn=lambda o, y: ((o - y) ** 2).mean())
    o = opt.SGD(learning_rate=lr, parameters=pipe.parameters())
    return PipelineParallel(pipe, o, mesh, n_micro=n_micro,
                            schedule=schedule)


class Test1F1B:
    def test_loss_and_updates_match_gpipe(self):
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        a = _make("gpipe")
        b = _make("1f1b")
        la = a.train_batch(x, y).item()
        lb = b.train_batch(x, y).item()
        assert abs(la - lb) < 1e-5, (la, lb)
        for k in a.stacked:
            np.testing.assert_allclose(np.asarray(a.stacked[k]),
                                       np.asarray(b.stacked[k]),
                                       rtol=1e-4, atol=1e-5)
        # and training actually converges
        for _ in range(10):
            l = b.train_batch(x, y).item()
        assert l < lb

    @pytest.mark.heavy
    def test_activation_memory_below_gpipe(self):
        """With n_micro >> n_stages, 1F1B's ring buffer (depth-bounded)
        must beat GPipe-via-AD (which saves residuals for every tick)."""
        n_micro = 16

        def temp_bytes(engine):
            xa = jnp.zeros((n_micro * 4, 16), jnp.float32)
            ya = jnp.zeros((n_micro * 4, 16), jnp.float32)
            lowered = jax.jit(engine._train_step_fn).lower(
                engine.stacked, engine.edge, engine.opt_state,
                engine.edge_opt_state, jnp.float32(0.01), 1, xa, ya)
            return lowered.compile().memory_analysis().temp_size_in_bytes

        g = temp_bytes(_make("gpipe", n_micro=n_micro))
        f = temp_bytes(_make("1f1b", n_micro=n_micro))
        assert f < g, f"1F1B temp {f} not below GPipe temp {g}"

    @pytest.mark.heavy
    def test_shared_embedding_tied_gradients(self):
        """GPT-style tied embedding: SharedLayerDesc at both ends — one
        weight leaf, gradient sums both uses, loss decreases."""
        paddle.seed(0)
        V, H = 32, 16
        mesh = build_mesh(dp=1, pp=2, mp=1, devices=jax.devices()[:2])

        def head(layer, x):  # logits = h @ E^T
            return paddle.matmul(x, layer.weight, transpose_y=True)

        pipe = PipelineLayer(
            [SharedLayerDesc("embed", nn.Embedding, None, "weight", V, H)]
            + [LayerDesc(nn.Linear, H, H) for _ in range(4)]
            + [SharedLayerDesc("embed", nn.Embedding, head, "weight",
                               V, H)],
            num_stages=2,
            loss_fn=lambda o, y: nn.functional.cross_entropy(
                o.reshape([-1, V]), y.reshape([-1])))
        o = opt.SGD(learning_rate=0.1, parameters=pipe.parameters())
        pp = PipelineParallel(pipe, o, mesh, n_micro=2, schedule="1f1b")

        # ONE tied leaf shared by embed + head
        assert [k for k in pp.edge] == ["embed.weight"], list(pp.edge)
        w0 = np.asarray(pp.edge["embed.weight"]).copy()

        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, V, (4, 8)).astype(np.int64))
        l0 = pp.train_batch(ids, ids).item()
        assert np.isfinite(l0)
        w1 = np.asarray(pp.edge["embed.weight"])
        assert np.abs(w1 - w0).sum() > 0, "tied weight did not update"
        for _ in range(15):
            l = pp.train_batch(ids, ids).item()
        assert l < l0, (l0, l)

    @pytest.mark.heavy

    def test_shared_embedding_gpipe_parity(self):
        """Same tied-edge model must also work on the GPipe schedule and
        produce the same first-step loss as 1F1B."""
        def build(schedule):
            paddle.seed(0)
            V, H = 32, 16
            mesh = build_mesh(dp=1, pp=2, mp=1, devices=jax.devices()[:2])

            def head(layer, x):
                return paddle.matmul(x, layer.weight, transpose_y=True)

            pipe = PipelineLayer(
                [SharedLayerDesc("embed", nn.Embedding, None, "weight",
                                 V, H)]
                + [LayerDesc(nn.Linear, H, H) for _ in range(4)]
                + [SharedLayerDesc("embed", nn.Embedding, head, "weight",
                                   V, H)],
                num_stages=2,
                loss_fn=lambda o, y: nn.functional.cross_entropy(
                    o.reshape([-1, 32]), y.reshape([-1])))
            o = opt.SGD(learning_rate=0.1, parameters=pipe.parameters())
            return PipelineParallel(pipe, o, mesh, n_micro=2,
                                    schedule=schedule)

        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 32, (4, 8)).astype(np.int64))
        la = build("gpipe").train_batch(ids, ids).item()
        lb = build("1f1b").train_batch(ids, ids).item()
        assert abs(la - lb) < 1e-5, (la, lb)

    def test_bad_schedule_rejected(self):
        with pytest.raises(ValueError):
            _make("interleaved-2f2b")


class TestInterleaved:
    """Virtual-stage interleaved schedule (Megatron-style; ref
    'interleaved'/virtual pp in fleet pipeline_parallel.py)."""

    def _build(self, schedule, n_virtual=1, lr=0.02):
        paddle.seed(0)
        mesh = build_mesh(dp=1, pp=2, mp=1, devices=jax.devices()[:2])
        pipe = PipelineLayer(
            [LayerDesc(nn.Linear, 16, 16) for _ in range(8)],
            num_stages=2, loss_fn=lambda o, y: ((o - y) ** 2).mean())
        o = opt.SGD(learning_rate=lr, parameters=pipe.parameters())
        return PipelineParallel(pipe, o, mesh, n_micro=4,
                                schedule=schedule, n_virtual=n_virtual), \
            pipe

    @pytest.mark.heavy
    def test_matches_gpipe_and_single_device(self):
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randn(8, 16).astype(np.float32))
        inter, pipe = self._build("interleaved", n_virtual=2)
        # forward parity vs the single-device full stack
        np.testing.assert_allclose(inter.forward(x).numpy(),
                                   pipe(x).numpy(), rtol=1e-4, atol=1e-5)
        gp, _ = self._build("gpipe")
        li = inter.train_batch(x, y).item()
        lg = gp.train_batch(x, y).item()
        assert abs(li - lg) < 1e-5, (li, lg)
        for _ in range(8):
            l = inter.train_batch(x, y).item()
        assert l < li

    def test_micro_must_divide_stages(self):
        paddle.seed(0)
        mesh = build_mesh(dp=1, pp=2, mp=1, devices=jax.devices()[:2])
        pipe = PipelineLayer(
            [LayerDesc(nn.Linear, 8, 8) for _ in range(4)],
            num_stages=2, loss_fn=lambda o, y: ((o - y) ** 2).mean())
        o = opt.SGD(learning_rate=0.01, parameters=pipe.parameters())
        eng = PipelineParallel(pipe, o, mesh, n_micro=3,
                               schedule="interleaved", n_virtual=2)
        x = paddle.randn([6, 8])
        with pytest.raises(ValueError, match="divisible"):
            eng.train_batch(x, x)

    def test_trunk_must_divide_chunks(self):
        paddle.seed(0)
        mesh = build_mesh(dp=1, pp=2, mp=1, devices=jax.devices()[:2])
        pipe = PipelineLayer(
            [LayerDesc(nn.Linear, 8, 8) for _ in range(6)],
            num_stages=2, loss_fn=lambda o, y: ((o - y) ** 2).mean())
        o = opt.SGD(learning_rate=0.01, parameters=pipe.parameters())
        with pytest.raises(ValueError, match="uniform stages"):
            PipelineParallel(pipe, o, mesh, n_micro=4,
                             schedule="interleaved", n_virtual=2)
