#!/usr/bin/env python
"""Static lint: no host synchronization in the designated hot-loop code
— THIN SHIM over the paddlelint hot-sync pass (tools/lint/hot_sync.py).

The region table, sync patterns, `# hot-sync-ok: <why>` allowlist and
check_source/check_repo semantics live in the framework pass now (PR
"paddlelint": docs/STATIC_ANALYSIS.md has the pass catalog and the
folded-in region table). This CLI keeps its historical contract
byte-for-byte — same stdout, same exit codes — so existing callers
(tests/test_async_pipeline.py and friends, CI scripts) run unchanged:

Usage: python tools/check_no_hot_sync.py [REPO_ROOT]
Exit 0 clean, 1 violations.

Prefer `python tools/paddlelint.py --select hot-sync` for new
callers: same verdicts, plus the kind:"lint" findings JSONL and the
suppression/baseline accounting.
"""
import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from lint.hot_sync import (  # noqa: F401,E402  (the public surface)
    ALLOW_MARKER, HOT_REGIONS, PATTERNS, check_repo, check_source)


def main(argv):
    repo = argv[0] if argv else os.path.dirname(_TOOLS)
    errors = check_repo(repo)
    for err in errors:
        print(err)
    if errors:
        print(f"FAIL: {len(errors)} hot-loop sync violation(s)")
        return 1
    print(f"OK: {len(HOT_REGIONS)} hot file(s) clean")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
