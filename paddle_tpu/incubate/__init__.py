"""paddle.incubate. Parity: python/paddle/incubate/__init__.py (subset:
the pieces the training stack uses — fused ops route to Pallas/XLA)."""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op

# fresh randomness per sampler call, reseedable via numpy's global seed
import numpy as _np
_khop_rng = _np.random.default_rng()

__all__ = ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
           "graph_send_recv", "graph_khop_sampler", "segment_sum",
           "segment_mean", "segment_max", "segment_min", "optimizer", "nn",
           "LookAhead", "ModelAverage"]


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """K-hop neighbourhood sampling over a CSC graph.
    Parity: python/paddle/incubate/operators/graph_khop_sampler.py.
    Host-side (numpy) sampling — graph walks are data-dependent/ragged and
    belong on CPU; the sampled dense subgraph then feeds TPU compute."""
    import numpy as np
    rowv = np.asarray(row.numpy() if isinstance(row, Tensor) else row)
    colv = np.asarray(colptr.numpy() if isinstance(colptr, Tensor)
                      else colptr)
    nodes = np.asarray(input_nodes.numpy() if isinstance(input_nodes, Tensor)
                       else input_nodes).reshape(-1)
    eids = np.asarray(sorted_eids.numpy() if isinstance(sorted_eids, Tensor)
                      else sorted_eids) if sorted_eids is not None else None
    rng = _khop_rng
    edge_src, edge_dst, edge_ids = [], [], []
    frontier = nodes
    seen = {int(n): i for i, n in enumerate(nodes)}
    order = list(nodes)
    for k in sample_sizes:
        nxt = []
        for dst in frontier:
            s, e = int(colv[dst]), int(colv[dst + 1])
            neigh = rowv[s:e]
            ids = np.arange(s, e)
            if k >= 0 and len(neigh) > k:
                pick = rng.choice(len(neigh), size=k, replace=False)
                neigh, ids = neigh[pick], ids[pick]
            for u, ei in zip(neigh, ids):
                u = int(u)
                if u not in seen:
                    seen[u] = len(order)
                    order.append(u)
                edge_src.append(u)
                edge_dst.append(int(dst))
                edge_ids.append(int(eids[ei]) if eids is not None else int(ei))
            nxt.extend(int(u) for u in neigh)
        frontier = np.unique(np.asarray(nxt, dtype=rowv.dtype)) \
            if nxt else np.array([], dtype=rowv.dtype)
    reindex = {n: i for i, n in enumerate(order)}
    src_l = jnp.asarray([reindex[u] for u in edge_src], jnp.int64)
    dst_l = jnp.asarray([reindex[v] for v in edge_dst], jnp.int64)
    out_nodes = jnp.asarray(order, jnp.int64)
    # positions of the seed input_nodes in the sampled-subgraph index
    # space (they seed `order`, so this is their reindexed location)
    reindex_x = jnp.asarray([reindex[int(n)] for n in nodes], jnp.int64)
    outs = (Tensor(src_l), Tensor(dst_l), Tensor(out_nodes),
            Tensor(reindex_x))
    if return_eids:
        return outs + (Tensor(jnp.asarray(edge_ids, jnp.int64)),)
    return outs


def softmax_mask_fuse(x, mask, name=None):
    return apply_op(
        lambda a, m: jax.nn.softmax(a + m.astype(a.dtype), -1), x, mask)


def softmax_mask_fuse_upper_triangle(x):
    def fn(a):
        T = a.shape[-1]
        causal = jnp.tril(jnp.ones((a.shape[-2], T), bool), k=T - a.shape[-2])
        return jax.nn.softmax(jnp.where(causal, a, -1e30), -1)
    return apply_op(fn, x)


def _segment(op, init):
    def seg(data, segment_ids, name=None):
        def fn(d, ids):
            n = int(jnp.max(ids)) + 1 if not isinstance(
                ids, jax.core.Tracer) else d.shape[0]
            out = jnp.full((n,) + d.shape[1:], init, d.dtype)
            if op == "sum" or op == "mean":
                out = jnp.zeros((n,) + d.shape[1:], d.dtype).at[ids].add(d)
                if op == "mean":
                    cnt = jnp.zeros((n,), d.dtype).at[ids].add(1.0)
                    out = out / jnp.maximum(cnt, 1.0).reshape(
                        (-1,) + (1,) * (d.ndim - 1))
                return out
            if op == "max":
                return out.at[ids].max(d)
            return out.at[ids].min(d)
        return apply_op(fn, data, segment_ids)
    return seg


segment_sum = _segment("sum", 0.0)
segment_mean = _segment("mean", 0.0)
segment_max = _segment("max", -jnp.inf)
segment_min = _segment("min", jnp.inf)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    def fn(a, src, dst):
        gathered = a[src]
        n = a.shape[0] if out_size is None else out_size
        if pool_type in ("sum", "mean"):
            out = jnp.zeros((n,) + a.shape[1:], a.dtype).at[dst].add(
                gathered)
            if pool_type == "mean":
                cnt = jnp.zeros((n,), a.dtype).at[dst].add(1.0)
                out = out / jnp.maximum(cnt, 1.0).reshape(
                    (-1,) + (1,) * (a.ndim - 1))
            return out
        if pool_type == "max":
            return jnp.full((n,) + a.shape[1:], -jnp.inf,
                            a.dtype).at[dst].max(gathered)
        return jnp.full((n,) + a.shape[1:], jnp.inf,
                        a.dtype).at[dst].min(gathered)
    return apply_op(fn, x, src_index, dst_index)


from . import optimizer  # noqa: E402  (real submodule)


def _fused_layers():
    """paddle.incubate.nn fused transformer layers. Parity:
    python/paddle/incubate/nn/layer/fused_transformer.py. On TPU the
    'fusion' is flash attention (Pallas) + Pallas layer_norm + XLA
    elementwise fusion — same single-layer semantics: attention/FFN with
    the residual add and layer norm folded into the layer."""
    from .. import nn as _nn
    from ..nn import functional as _F

    class FusedMultiHeadAttention(_nn.Layer):
        def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                     attn_dropout_rate=0.5, kdim=None, vdim=None,
                     normalize_before=False, need_weights=False,
                     weight_attr=None, bias_attr=None, epsilon=1e-5,
                     name=None):
            super().__init__()
            assert not need_weights, "need_weights not supported"
            self.embed_dim = embed_dim
            self.num_heads = num_heads
            self.normalize_before = normalize_before
            self.qkv_proj = _nn.Linear(embed_dim, 3 * embed_dim,
                                       weight_attr=weight_attr,
                                       bias_attr=bias_attr)
            self.out_proj = _nn.Linear(embed_dim, embed_dim,
                                       weight_attr=weight_attr,
                                       bias_attr=bias_attr)
            self.ln = _nn.LayerNorm(embed_dim, epsilon=epsilon)
            self.attn_dropout = _nn.Dropout(attn_dropout_rate)
            self.dropout = _nn.Dropout(dropout_rate)

        def forward(self, query, key=None, value=None, attn_mask=None,
                    cache=None):
            """cache: optional (k_hist, v_hist) in [B, T, H, D] for
            incremental decode; returns (out, (k, v)) when given, like
            the reference's Cache path."""
            residual = query
            x = self.ln(query) if self.normalize_before else query
            B, T, E = x.shape
            H = self.num_heads
            qkv = self.qkv_proj(x).reshape([B, T, 3, H, E // H])
            q, k, v = qkv.unbind(axis=2)
            if cache is not None:
                from ..tensor.manipulation import concat
                k = concat([cache[0], k], axis=1)
                v = concat([cache[1], v], axis=1)
            out = _F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                dropout_p=self.attn_dropout.p if self.training else 0.0)
            out = self.out_proj(out.reshape([B, T, E]))
            out = residual + self.dropout(out)
            if not self.normalize_before:
                out = self.ln(out)
            return out if cache is None else (out, (k, v))

        def set_state_dict(self, state_dict, use_structured_name=True):
            """Accepts our native layout OR the reference fused-op layout
            (incubate/nn/layer/fused_transformer.py): qkv_weight
            [3, H, hd, E], qkv_bias [3, H, hd], linear_weight/bias,
            pre_ln_scale/bias or ln_scale/bias — converted into the
            qkv_proj/out_proj/ln sublayers."""
            import numpy as _np
            sd = {k: (v.numpy() if hasattr(v, "numpy") else _np.asarray(v))
                  for k, v in state_dict.items()}
            if "qkv_weight" in sd:
                E = self.embed_dim
                conv = {}
                qkv_w = sd.pop("qkv_weight")          # [3, H, hd, E]
                conv["qkv_proj.weight"] = _np.transpose(
                    qkv_w.reshape(3 * E, E))          # -> [E, 3E] (in,out)
                if "qkv_bias" in sd:
                    conv["qkv_proj.bias"] = sd.pop("qkv_bias").reshape(-1)
                if "linear_weight" in sd:
                    conv["out_proj.weight"] = sd.pop("linear_weight")
                if "linear_bias" in sd:
                    conv["out_proj.bias"] = sd.pop("linear_bias")
                lnk = ("pre_ln_scale", "pre_ln_bias") \
                    if self.normalize_before else ("ln_scale", "ln_bias")
                if lnk[0] in sd:
                    conv["ln.weight"] = sd.pop(lnk[0])
                if lnk[1] in sd:
                    conv["ln.bias"] = sd.pop(lnk[1])
                sd = conv
            return _nn.Layer.set_state_dict(self, sd, use_structured_name)

    class FusedFeedForward(_nn.Layer):
        def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                     epsilon=1e-5, activation="relu",
                     act_dropout_rate=None, normalize_before=False,
                     linear1_weight_attr=None, linear1_bias_attr=None,
                     linear2_weight_attr=None, linear2_bias_attr=None,
                     ln1_scale_attr=None, ln1_bias_attr=None,
                     ln2_scale_attr=None, ln2_bias_attr=None, name=None):
            super().__init__()
            self.normalize_before = normalize_before
            self.linear1 = _nn.Linear(d_model, dim_feedforward,
                                      weight_attr=linear1_weight_attr,
                                      bias_attr=linear1_bias_attr)
            self.linear2 = _nn.Linear(dim_feedforward, d_model,
                                     weight_attr=linear2_weight_attr,
                                     bias_attr=linear2_bias_attr)
            self.ln = _nn.LayerNorm(d_model, epsilon=epsilon)
            self.dropout = _nn.Dropout(dropout_rate)
            self.act_dropout = _nn.Dropout(
                dropout_rate if act_dropout_rate is None
                else act_dropout_rate)
            self.activation = getattr(_F, activation)

        def forward(self, src, cache=None):
            residual = src
            x = self.ln(src) if self.normalize_before else src
            x = self.act_dropout(self.activation(self.linear1(x)))
            x = self.dropout(self.linear2(x))
            out = residual + x
            if not self.normalize_before:
                out = self.ln(out)
            return out

        def set_state_dict(self, state_dict, use_structured_name=True):
            """Accepts the reference fused-op layout (linear1_weight,
            linear2_weight, ln1_scale/ln2_scale...) besides ours."""
            import numpy as _np
            sd = {k: (v.numpy() if hasattr(v, "numpy") else _np.asarray(v))
                  for k, v in state_dict.items()}
            if "linear1_weight" in sd:
                conv = {"linear1.weight": sd.pop("linear1_weight"),
                        "linear2.weight": sd.pop("linear2_weight")}
                if "linear1_bias" in sd:
                    conv["linear1.bias"] = sd.pop("linear1_bias")
                if "linear2_bias" in sd:
                    conv["linear2.bias"] = sd.pop("linear2_bias")
                lnk = ("ln1_scale", "ln1_bias") if self.normalize_before \
                    else ("ln2_scale", "ln2_bias")
                if lnk[0] in sd:
                    conv["ln.weight"] = sd.pop(lnk[0])
                if lnk[1] in sd:
                    conv["ln.bias"] = sd.pop(lnk[1])
                sd = conv
            return _nn.Layer.set_state_dict(self, sd, use_structured_name)

    return FusedMultiHeadAttention, FusedFeedForward


_FusedMultiHeadAttention, _FusedFeedForward = _fused_layers()

from .moe import MoELayer as _MoELayer  # noqa: E402

# real submodule (paddle parity: `from paddle.incubate.nn import
# FusedMultiHeadAttention` must work) — imported last so nn.py can read
# the classes above off this partially-initialized package
from . import nn  # noqa: E402


LookAhead = optimizer.LookAhead
ModelAverage = optimizer.ModelAverage
