"""bench.py harness robustness (round-5): the headline JSON line must
survive the driver killing the process at any point after measurement
(BENCH_r04.json recorded rc=124 with zero output; the contract now is
tee-on-measure). Runs the real bench.py CPU smoke path in a subprocess.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick commit gate no

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _bench_env(tmp_path, hold=None):
    env = dict(os.environ)
    env.update({
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONUNBUFFERED": "1",
        "BENCH_1P3B": "0",
        # a private cache dir: the test must not warm/poison the repo one
        "BENCH_XLA_CACHE": str(tmp_path / "xla_cache"),
        "BENCH_TOTAL_BUDGET": "150",
    })
    env.pop("XLA_FLAGS", None)  # no 8-device split for the bench child
    if hold is not None:
        env["BENCH_HOLD_AFTER_PRINT"] = str(hold)
    return env


def test_headline_survives_midrun_kill(tmp_path):
    """Kill -9 the whole bench process group the instant the headline
    line appears on stdout; the line must already be complete and
    parseable — exactly what the driver's `tail` would keep."""
    proc = subprocess.Popen(
        [sys.executable, "-u", BENCH], env=_bench_env(tmp_path, hold=60),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        start_new_session=True)
    headline = None
    deadline = time.time() + 150
    try:
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            if line.startswith("{"):
                headline = line.strip()
                break
        # the driver's kill: whole process group, no grace
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    finally:
        proc.wait()
    assert headline, "no headline line before the kill"
    parsed = json.loads(headline)
    assert parsed["metric"] == "gpt_medium_train_tokens_per_sec_per_chip"
    assert parsed["value"] > 0
    assert parsed["unit"] == "tokens/s/chip"


@pytest.mark.heavy
def test_bench_persistent_cache_records_state(tmp_path):
    """A completed run must leave the compile-state marker that drives
    warm-cache attempt ordering, and end with a merged final line."""
    env = _bench_env(tmp_path)
    out = subprocess.run(
        [sys.executable, "-u", BENCH], env=env, timeout=170,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    assert out.returncode == 0
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    assert lines, out.stdout
    final = json.loads(lines[-1])
    assert final["value"] > 0
    assert "gpt_1p3b_tokens_per_sec" in final  # merged shape
    state_path = tmp_path / "xla_cache" / "bench_state.json"
    assert state_path.exists()
    state = json.loads(state_path.read_text())
    assert any(k.startswith("headline") for k in state)
