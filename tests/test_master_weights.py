"""multi_precision f32 master weights in the jit/tree path: sub-bf16-ulp
updates must accumulate in the master instead of rounding away."""
import numpy as np
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.jit import TrainStep


def _train(multi_precision, steps=30):
    paddle.seed(0)
    m = nn.Linear(4, 1, bias_attr=False)
    # weights near 256: bf16 ulp there is 2.0, far above any single update
    m.weight.set_value(jnp.full((4, 1), 256.0, jnp.float32))
    m.bfloat16()
    o = opt.SGD(learning_rate=0.05, parameters=m.parameters(),
                multi_precision=multi_precision)
    step = TrainStep(m, lambda out, y: nn.functional.mse_loss(out, y),
                     o, donate=False)
    x = paddle.to_tensor(np.ones((2, 4), np.float32)).astype("bfloat16")
    y = paddle.to_tensor(np.zeros((2, 1), np.float32)).astype("bfloat16")
    for _ in range(steps):
        step(x, y)
    return step


def test_master_accumulates_sub_ulp_updates():
    st = _train(multi_precision=True)
    # param dtype unchanged, master exists and has drifted from 256
    w = st.params["weight"]
    assert w.dtype == jnp.bfloat16
    leaf = st.opt_state["weight"]
    assert isinstance(leaf, dict) and "master" in leaf
    master = np.asarray(leaf["master"])
    assert master.dtype == np.float32
    assert np.all(master < 256.0)  # gradient pushed it down
    # and the shadow param tracks the master's rounded value
    np.testing.assert_allclose(
        np.asarray(w.astype(jnp.float32)),
        master.astype(np.float32), atol=1.01)


def test_without_master_updates_may_round_away():
    st = _train(multi_precision=False, steps=1)
    leaf = st.opt_state["weight"]
    assert not isinstance(leaf, dict)  # plain state, no master


def test_master_weights_sgd_converges_lower():
    stm = _train(multi_precision=True, steps=60)
    stp = _train(multi_precision=False, steps=60)
    wm = np.asarray(stm.opt_state["weight"]["master"])
    wp = np.asarray(stp.params["weight"].astype(jnp.float32))
    # both move, but the master path must have made at least as much
    # progress toward 0 (it never loses sub-ulp updates)
    assert wm.mean() <= wp.mean() + 1e-3


def test_master_weights_adamw_moments_and_master():
    paddle.seed(0)
    m = nn.Linear(4, 1, bias_attr=False)
    m.weight.set_value(jnp.full((4, 1), 256.0, jnp.float32))
    m.bfloat16()
    o = opt.AdamW(learning_rate=0.5, parameters=m.parameters(),
                  multi_precision=True)
    step = TrainStep(m, lambda out, y: nn.functional.mse_loss(out, y),
                     o, donate=False)
    x = paddle.to_tensor(np.ones((2, 4), np.float32)).astype("bfloat16")
    y = paddle.to_tensor(np.zeros((2, 1), np.float32)).astype("bfloat16")
    for _ in range(10):
        step(x, y)
    leaf = step.opt_state["weight"]
    assert isinstance(leaf, dict)
    m1, v1 = leaf["state"]
    assert m1.dtype == jnp.float32 and v1.dtype == jnp.float32
    assert float(np.abs(np.asarray(m1)).max()) > 0  # moments advanced
    master = np.asarray(leaf["master"])
    assert np.all(master < 256.0)
    assert step.params["weight"].dtype == jnp.bfloat16
