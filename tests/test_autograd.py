"""Tape autograd semantics (SURVEY.md §2.2)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


def leaf(a):
    return paddle.to_tensor(np.asarray(a, dtype=np.float32),
                            stop_gradient=False)


class TestBackward:
    def test_simple_chain(self):
        x = leaf([1.0, 2.0, 3.0])
        y = (x * x + 2 * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy() + 2)

    def test_branching(self):
        x = leaf([2.0])
        a = x * 3
        b = x * 4
        (a + b).backward()
        np.testing.assert_allclose(x.grad.numpy(), [7.0])

    def test_matmul_grad(self):
        rng = np.random.RandomState(0)
        a_np = rng.rand(3, 4).astype(np.float32)
        b_np = rng.rand(4, 2).astype(np.float32)
        a, b = leaf(a_np), leaf(b_np)
        paddle.matmul(a, b).sum().backward()
        np.testing.assert_allclose(a.grad.numpy(),
                                   np.ones((3, 2)) @ b_np.T, rtol=1e-5)
        np.testing.assert_allclose(b.grad.numpy(),
                                   a_np.T @ np.ones((3, 2)), rtol=1e-5)

    def test_stop_gradient(self):
        x = leaf([1.0])
        y = paddle.to_tensor([2.0])  # stop_gradient=True
        z = x * y
        z.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])
        assert y.grad is None

    def test_grad_accumulation(self):
        x = leaf([1.0])
        (x * 2).backward()
        (x * 3).backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0])
        x.clear_grad()
        assert x.grad is None

    def test_detach(self):
        x = leaf([3.0])
        d = x.detach()
        assert d.stop_gradient
        y = x * x + d
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])

    def test_no_grad(self):
        x = leaf([1.0])
        with paddle.no_grad():
            y = x * 5
        assert y.stop_gradient
        z = x * 2
        assert not z.stop_gradient

    def test_non_scalar_backward_implicit_ones(self):
        """Reference semantics (varbase_patch_methods.py backward): ANY
        shape backpropagates with an implicit all-ones cotangent — the
        adamw docstring example calls out.backward() on a [10,10]."""
        x = leaf([1.0, 2.0])
        y = x * 2
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])
        x.clear_grad()
        y2 = x * 2
        y2.backward(grad_tensor=paddle.to_tensor([1.0, 10.0]))
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 20.0])

    def test_int_inputs_no_record(self):
        i = paddle.to_tensor(np.array([0, 1]), stop_gradient=False)
        out = i + 1
        assert out.stop_gradient  # integer path records nothing


class TestGradAPI:
    def test_grad_basic(self):
        x = leaf([1.0, 2.0])
        y = (x ** 2).sum()
        (gx,) = paddle.grad(y, [x])
        np.testing.assert_allclose(gx.numpy(), 2 * x.numpy())
        assert x.grad is None  # paddle.grad does not populate .grad

    def test_grad_unused(self):
        x, z = leaf([1.0]), leaf([1.0])
        y = x * 2
        with pytest.raises(ValueError):
            paddle.grad(y, [z])
        gx, gz = paddle.grad(x * 2, [x, z], allow_unused=True)
        assert gz is None


class TestPyLayer:
    def test_custom_forward_backward(self):
        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, a):
                ctx.save_for_backward(a)
                return a * a * a

            @staticmethod
            def backward(ctx, g):
                (a,) = ctx.saved_tensor()  # method, per reference py_layer.py:88
                return g * 3 * a * a

        x = leaf([2.0])
        Cube.apply(x).backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0])


class TestFunctional:
    def test_vjp(self):
        from paddle_tpu.autograd import vjp
        x = leaf([1.0, 2.0])
        out, g = vjp(lambda a: (a * a).sum(), x)
        np.testing.assert_allclose(g.numpy(), [2.0, 4.0])

    def test_jacobian(self):
        from paddle_tpu.autograd import jacobian
        x = leaf([1.0, 2.0])
        J = jacobian(lambda a: a * a, x)
        np.testing.assert_allclose(J.numpy(), np.diag([2.0, 4.0]))

    def test_hessian(self):
        from paddle_tpu.autograd import hessian
        x = leaf([1.0, 2.0])
        H = hessian(lambda a: (a ** 3).sum(), x)
        np.testing.assert_allclose(H.numpy(), np.diag([6.0, 12.0]))


class TestGradHooks:
    """Tensor.register_hook must actually fire during backward and a
    non-None return must replace the upstream gradient (ref
    varbase_patch_methods.py:330)."""

    def test_leaf_hook_observes_grad(self):
        x = leaf([1.0, 2.0])
        seen = []
        x.register_hook(lambda g: seen.append(g.numpy().copy()))
        (x * 3).sum().backward()
        assert len(seen) == 1
        np.testing.assert_allclose(seen[0], [3.0, 3.0])
        np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])

    def test_hook_replaces_grad_upstream(self):
        x = leaf([1.0, 2.0])
        y = x * 2
        y.register_hook(lambda g: g * 10)
        y.sum().backward()
        # d(sum)/dy = 1 -> hook makes it 10 -> dx = 20
        np.testing.assert_allclose(x.grad.numpy(), [20.0, 20.0])

    def test_hook_remove(self):
        x = leaf([1.0])
        calls = []
        h = x.register_hook(lambda g: calls.append(1))
        (x * 2).backward()
        assert h.remove() is True
        (x * 2).backward()
        assert len(calls) == 1

    def test_hook_on_stop_gradient_raises(self):
        t = paddle.to_tensor([1.0])  # stop_gradient=True
        with pytest.raises(RuntimeError):
            t.register_hook(lambda g: g)


class TestDoubleGrad:
    def test_create_graph_second_order(self):
        # y = x^3 -> dy/dx = 3x^2 -> d2y/dx2 = 6x
        x = leaf([2.0])
        y = (x * x * x).sum()
        (gx,) = paddle.grad(y, [x], create_graph=True)
        np.testing.assert_allclose(gx.numpy(), [12.0])
        (ggx,) = paddle.grad(gx.sum(), [x])
        np.testing.assert_allclose(ggx.numpy(), [12.0])

    def test_create_graph_mixed_expression(self):
        # loss = sum(grad^2) where grad = dy/dx, y = sum(x^2) -> grad=2x,
        # loss = 4 x^2 -> dloss/dx = 8x
        x = leaf([1.0, 3.0])
        y = (x * x).sum()
        (gx,) = paddle.grad(y, [x], create_graph=True)
        loss = (gx * gx).sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0, 24.0])

    @pytest.mark.heavy

    def test_wgan_gp_style_penalty(self):
        """Gradient penalty: grads of an interpolation point flow back
        into discriminator weights (the WGAN-GP training pattern)."""
        import paddle_tpu.nn as nn
        paddle.seed(0)
        disc = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
        x = leaf(np.random.RandomState(0).randn(3, 4).astype(np.float32))
        out = disc(x).sum()
        (gx,) = paddle.grad(out, [x], create_graph=True)
        gp = ((gx.square().sum(axis=1).sqrt() - 1.0) ** 2).mean()
        gp.backward()
        w = disc[0].weight
        assert w.grad is not None
        assert float(np.abs(w.grad.numpy()).sum()) > 0
