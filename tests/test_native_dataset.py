"""Native MultiSlot dataset engine (runtime_core.cpp ms_*).

Mirrors the reference's data_feed tests
(python/paddle/fluid/tests/unittests/test_dataset.py): parse, shuffle,
batch, ragged slots, python-fallback parity.
"""
import numpy as np
import pytest

import paddle_tpu.distributed as dist
from paddle_tpu.runtime import get_lib

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick commit gate no


def _write(tmp_path, lines, name="part-0"):
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_native_engine_loads(tmp_path):
    if get_lib() is None:
        pytest.skip("native runtime unavailable")
    path = _write(tmp_path, ["2 10 20 1 0.5", "2 30 40 1 1.5",
                             "2 50 60 1 2.5"])
    ds = dist.InMemoryDataset()
    ds.init(batch_size=2, thread_num=2, use_var=["ids", "score"])
    ds.set_filelist([path])
    ds.load_into_memory()
    assert ds._native is not None, "expected the native parse path"
    assert ds.get_memory_data_size() == 3
    batches = list(ds)
    assert batches[0]["ids"].shape == (2, 2)
    assert batches[0]["ids"].dtype == np.int64
    assert batches[0]["score"].dtype == np.float32
    np.testing.assert_allclose(batches[0]["score"].ravel(), [0.5, 1.5])
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_native_ragged_and_shuffle(tmp_path):
    if get_lib() is None:
        pytest.skip("native runtime unavailable")
    rng = np.random.RandomState(0)
    lines, recs = [], []
    for _ in range(200):
        n = rng.randint(1, 6)
        ids = rng.randint(0, 100, n)
        lines.append(f"{n} " + " ".join(map(str, ids)) + " 1 1")
        recs.append(ids)
    path = _write(tmp_path, lines)
    ds = dist.InMemoryDataset()
    ds.init(batch_size=200, thread_num=4, use_var=["ids", "label"])
    ds.set_filelist([path])
    ds.load_into_memory()
    assert ds._native is not None
    ds.local_shuffle()
    (batch,) = list(ds)
    got = batch["ids"]
    assert isinstance(got, list) and len(got) == 200
    # shuffle preserves the multiset of records
    key = lambda arrs: sorted(tuple(a.tolist()) for a in arrs)
    assert key(got) == key(recs)


def test_malformed_line_rejected_not_merged(tmp_path):
    """A line missing a slot must fail loudly (reference CheckFile
    semantics), never be silently merged with the next line."""
    if get_lib() is None:
        pytest.skip("native runtime unavailable")
    path = _write(tmp_path, ["1 5", "1 6"])  # both lines missing slot b
    ds = dist.InMemoryDataset()
    ds.init(batch_size=2, use_var=["a", "b"])
    ds.set_filelist([path])
    with pytest.raises(Exception):
        ds.load_into_memory()  # native rejects -> python fallback raises


def test_python_fallback_matches_native(tmp_path):
    if get_lib() is None:
        pytest.skip("native runtime unavailable")
    path = _write(tmp_path, ["3 1 2 3 1 7", "3 4 5 6 1 8"])

    def load(force_python):
        ds = dist.InMemoryDataset()
        ds.init(batch_size=2, use_var=["a", "b"])
        ds.set_filelist([path])
        if force_python:
            ds._pipe_command = "cat"  # pipe path stays pure-python
        ds.load_into_memory()
        return list(ds)[0]

    native, py = load(False), load(True)
    np.testing.assert_array_equal(native["a"], py["a"])
    np.testing.assert_array_equal(native["b"], py["b"])
