"""Framework model zoo for the BASELINE.json configs (GPT / BERT-ERNIE)."""
from .gpt import (GPTConfig, GPTModel, GPTForCausalLM, gpt_tiny, gpt_small,
                  gpt_medium, gpt_1p3b, gpt_6p7b, gpt_moe)
from .bert import (BertConfig, BertModel, BertForMaskedLM,
                   BertForSequenceClassification, ErnieModel,
                   ErnieForSequenceClassification, bert_base, ernie_base)
from .seq2seq import Seq2SeqConfig, Seq2SeqTransformer
