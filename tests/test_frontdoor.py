"""The serving front door: multi-engine router, prefill/decode
disaggregation over the shared page pool, and real on-device sampling.

Covers the PR's acceptance criteria end to end on CPU:

- on-device seeded sampling (`sample_token_rows` / `SamplingParams`):
  temperature 0 bit-exact vs the argmax path, seeded reproducibility,
  distributional parity vs a numpy reference softmax sampler over many
  draws, retrace stability across admit/evict
- the chain handoff (`PagedKVCache.export_chain`/`adopt_chain`):
  page IDENTITY and refcounts asserted across the move, zero copies,
  claims-ledger continuity, release path
- the disaggregated pair: a chain prefilled on engine A and decoded on
  engine B is token-for-token equal to a single-engine run (greedy AND
  seeded-sampled), with draw counts proving no page was copied
- `ServingRouter` placement: load-aware dispatch, sticky prefix
  affinity, fast-fail when the whole fleet is saturated, fleet
  load_report aggregation (shared pools deduplicated)
- `kind:"route"` record schema (accept + reject) and the obs_report
  `== routing ==` section
"""
import json
import os
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import (GPTForCausalLM, GPTConfig,
                                   sample_token_rows, sampling_key_data)
from paddle_tpu.ops.paged_attention import PagedKVCache
from paddle_tpu.inference import (GenerationEngine, ServingRouter,
                                  SamplingParams, QueueFullError)
from paddle_tpu.profiler import monitor

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick gate no

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import check_metrics_schema as cms  # noqa: E402


def _tiny_lm(seed=0):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


# ONE module-level model: every engine in this file shares weights AND
# the per-model ragged-executable cache, so cross-topology equality is
# meaningful and the suite compiles each signature once
MODEL = _tiny_lm()


def _ref_greedy(m, prompt, max_new):
    """Oracle: single-sequence LEGACY paged decode, one request alone."""
    cache = m.make_paged_cache(n_pages=64, page_size=4)
    cache.add_sequence("s")
    logits = m.paged_decode_step(
        cache, ["s"], paddle.to_tensor(prompt[None].astype(np.int64)))
    toks = [int(np.asarray(logits.value)[0].argmax())]
    while len(toks) < max_new:
        logits = m.paged_decode_step(
            cache, ["s"],
            paddle.to_tensor(np.array([[toks[-1]]], np.int64)))
        toks.append(int(np.asarray(logits.value)[0].argmax()))
    return toks


# -- the sampler ---------------------------------------------------------

def _np_reference_probs(logits, temp, top_k, top_p):
    """Reference softmax sampler probabilities (numpy, float64): the
    same temperature -> top-k -> nucleus -> softmax pipeline the
    on-device sampler implements."""
    arr = logits.astype(np.float64) / max(temp, 1e-6)
    V = arr.size
    if top_k:
        kth = np.sort(arr)[::-1][min(int(top_k), V) - 1]
        arr = np.where(arr < kth, -1e30, arr)
    if top_p is not None and top_p < 1.0:
        srt = np.sort(arr)[::-1]
        e = np.exp(srt - srt.max())
        p = e / e.sum()
        before = np.cumsum(p) - p
        keep = before < top_p
        thresh = srt[keep].min() if keep.any() else -np.inf
        arr = np.where(arr >= thresh, arr, -1e30)
    e = np.exp(arr - arr.max())
    return e / e.sum()


class TestSamplerMath:
    def test_temperature_zero_is_bitwise_argmax(self):
        rng = np.random.RandomState(0)
        last = jnp.asarray(rng.randn(5, 32).astype(np.float32))
        toks = sample_token_rows(
            last, jnp.zeros((5,), jnp.float32),
            jnp.zeros((5,), jnp.int32), jnp.ones((5,), jnp.float32),
            jnp.zeros((5, 2), jnp.uint32),
            jnp.arange(5, dtype=jnp.int32))
        np.testing.assert_array_equal(
            np.asarray(toks), np.asarray(jnp.argmax(last, axis=-1)))

    def test_mixed_greedy_and_sampled_rows_one_call(self):
        """One fixed-shape call serves a greedy row and a sampled row:
        the greedy row is bit-exact argmax regardless of neighbors."""
        rng = np.random.RandomState(1)
        last = jnp.asarray(rng.randn(2, 32).astype(np.float32))
        toks = sample_token_rows(
            last, jnp.asarray(np.array([0.0, 1.0], np.float32)),
            jnp.asarray(np.array([0, 8], np.int32)),
            jnp.asarray(np.array([1.0, 0.9], np.float32)),
            jnp.asarray(np.stack([sampling_key_data(3)] * 2)),
            jnp.asarray(np.array([0, 0], np.int32)))
        assert int(toks[0]) == int(jnp.argmax(last[0]))

    def test_deterministic_per_key_and_position(self):
        rng = np.random.RandomState(2)
        last = jnp.asarray(rng.randn(1, 32).astype(np.float32))
        args = (jnp.ones((1,), jnp.float32), jnp.zeros((1,), jnp.int32),
                jnp.ones((1,), jnp.float32))

        def draw(seed, pos):
            return int(sample_token_rows(
                last, *args,
                jnp.asarray(sampling_key_data(seed)[None]),
                jnp.asarray(np.array([pos], np.int32)))[0])

        assert draw(7, 3) == draw(7, 3)
        draws = {draw(7, p) for p in range(40)}
        assert len(draws) > 1  # position folds into the key

    @pytest.mark.parametrize("temp,top_k,top_p", [
        (1.0, None, None),     # plain temperature sampling
        (0.8, 8, None),        # top-k
        (1.2, None, 0.85),     # nucleus
        (0.9, 12, 0.9),        # both filters
    ])
    def test_distributional_parity_vs_numpy_reference(self, temp,
                                                      top_k, top_p):
        """Empirical frequencies over many seeded draws match the
        reference numpy softmax sampler's probabilities (TV distance;
        the draws use distinct fold positions — exactly how the serving
        step derives per-token keys)."""
        V, N = 32, 4000
        rng = np.random.RandomState(5)
        row = rng.randn(V).astype(np.float32) * 2.0
        last = jnp.asarray(np.tile(row, (N, 1)))
        toks = np.asarray(jax.jit(sample_token_rows)(
            last,
            jnp.full((N,), temp, jnp.float32),
            jnp.full((N,), top_k or 0, jnp.int32),
            jnp.full((N,), 1.0 if top_p is None else top_p,
                     jnp.float32),
            jnp.asarray(np.tile(sampling_key_data(11), (N, 1))),
            jnp.arange(N, dtype=jnp.int32)))
        ref = _np_reference_probs(row, temp, top_k, top_p)
        emp = np.bincount(toks, minlength=V) / N
        # support must agree exactly: a filtered-out token sampled even
        # once means the masking diverged
        assert set(np.nonzero(emp)[0]) <= set(np.nonzero(ref > 0)[0])
        tv = 0.5 * np.abs(emp - ref).sum()
        assert tv < 0.07, (tv, emp, ref)


class TestSamplingEngine:
    def test_default_and_explicit_temp0_match_argmax_oracle(self):
        m = MODEL
        rng = np.random.RandomState(3)
        p = rng.randint(0, 64, (5,))
        ref = _ref_greedy(m, p, 4)
        eng = GenerationEngine(m, n_pages=64, page_size=4, max_batch=2,
                               max_new_tokens=8)
        try:
            h_default = eng.submit(p, max_new_tokens=4)
            h_explicit = eng.submit(
                p, max_new_tokens=4,
                sampling=SamplingParams(temperature=0.0))
            assert h_default.result(300).tolist() == ref
            assert h_explicit.result(300).tolist() == ref
        finally:
            eng.shutdown()

    def test_seeded_sampling_reproducible_and_seed_sensitive(self):
        m = MODEL
        rng = np.random.RandomState(4)
        p = rng.randint(0, 64, (6,))
        sp = dict(temperature=0.9, top_k=32, seed=13)

        def run_once():
            eng = GenerationEngine(m, n_pages=64, page_size=4,
                                   max_batch=2, max_new_tokens=8)
            try:
                return eng.submit(
                    p, max_new_tokens=6,
                    sampling=SamplingParams(**sp)).result(300).tolist()
            finally:
                eng.shutdown()

        a, b = run_once(), run_once()
        assert a == b  # same seed, fresh engine: identical text
        eng = GenerationEngine(m, n_pages=64, page_size=4, max_batch=2,
                               max_new_tokens=8)
        try:
            outs = {tuple(eng.submit(
                p, max_new_tokens=6,
                sampling=SamplingParams(temperature=0.9, top_k=32,
                                        seed=s)).result(300).tolist())
                for s in range(8)}
        finally:
            eng.shutdown()
        assert len(outs) > 1  # different seeds actually vary

    def test_retrace_stable_across_admit_evict_and_sampling_mix(self):
        """Mixing greedy and sampled requests (and admit/evict churn)
        dispatches the SAME executables: the sampling config rides in
        [B]-shaped arrays, never the compiled signature."""
        m = MODEL
        rng = np.random.RandomState(6)
        prompts = [rng.randint(0, 64, (n,)) for n in (5, 3, 6, 4)]
        eng = GenerationEngine(m, n_pages=64, page_size=4, max_batch=2,
                               max_new_tokens=6)
        try:
            # warm phase: greedy traffic compiles the signature set
            for h in [eng.submit(p, max_new_tokens=4)
                      for p in prompts[:2]]:
                h.result(300)
            before = getattr(m, "_ragged_traces", 0)
            handles = [
                eng.submit(prompts[0], max_new_tokens=4,
                           sampling=SamplingParams(temperature=0.8,
                                                   seed=1)),
                eng.submit(prompts[1], max_new_tokens=4),
                eng.submit(prompts[2][:5], max_new_tokens=4,
                           sampling=SamplingParams(temperature=1.1,
                                                   top_p=0.9, seed=2)),
                eng.submit(prompts[3][:3], max_new_tokens=4),
            ]
            for h in handles:
                h.result(300)
            assert getattr(m, "_ragged_traces", 0) == before
        finally:
            eng.shutdown()

    def test_legacy_bucketed_path_rejects_sampling(self):
        eng = GenerationEngine(MODEL, n_pages=64, page_size=4,
                               max_batch=2, ragged=False)
        try:
            with pytest.raises(ValueError, match="greedy-only"):
                eng.submit(np.array([1, 2, 3]),
                           sampling=SamplingParams(temperature=0.7))
        finally:
            eng.shutdown()

    def test_sampling_params_validation(self):
        with pytest.raises(ValueError):
            SamplingParams(temperature=-0.1)
        with pytest.raises(ValueError):
            SamplingParams(top_k=0.5)
        with pytest.raises(ValueError):
            SamplingParams(top_p=0.0)
        with pytest.raises(TypeError):
            GenerationEngine(MODEL, n_pages=16, page_size=4) \
                .submit(np.array([1]), sampling="greedy")


# -- the chain handoff (cache level) ------------------------------------

class TestChainHandoff:
    def test_export_adopt_preserves_page_identity_and_refcounts(self):
        m = MODEL
        cache = m.make_paged_cache(n_pages=32, page_size=4)
        cache.add_sequence("a")
        prompt = np.array([1, 2, 3, 4, 5, 6], np.int64)
        m.paged_ragged_step(cache, [("a", prompt)])
        cache.set_claim("a", 4)
        pages_before = list(cache._tables["a"])
        ref_before = dict(cache._ref)
        stats_before = cache.pool_stats()
        drawn = cache.pages_drawn("a")
        claims_before = cache.outstanding_claims()

        chain = cache.export_chain("a")
        # limbo: the sequence is gone, but every page keeps its hold
        # and the claim still counts
        assert "a" not in cache._tables
        assert dict(cache._ref) == ref_before
        assert cache.outstanding_claims() == claims_before

        assert cache.adopt_chain("b", chain) == prompt.size
        assert list(cache._tables["b"]) == pages_before  # IDENTITY
        assert dict(cache._ref) == ref_before
        assert cache.pages_drawn("b") == drawn
        assert cache.outstanding_claims() == claims_before
        stats_after = cache.pool_stats()
        # zero copies, zero extra draws across the whole move
        assert stats_after["cow_copies"] == stats_before["cow_copies"]
        assert stats_after["pages_drawn"] == stats_before["pages_drawn"]
        # a consumed handle cannot be adopted twice
        with pytest.raises(ValueError):
            cache.adopt_chain("c", chain)

    def test_decode_after_adopt_token_for_token(self):
        """Prefill under one sid, hand off, decode under another —
        equal to the uninterrupted single-sequence run."""
        m = MODEL
        rng = np.random.RandomState(7)
        prompt = rng.randint(0, 64, (7,))
        ref = _ref_greedy(m, prompt, 5)

        cache = m.make_paged_cache(n_pages=32, page_size=4)
        cache.add_sequence("pre")
        _, nxt = m.paged_ragged_step(cache, [("pre", prompt)])
        toks = [int(np.asarray(nxt)[0])]
        chain = cache.export_chain("pre")
        cache.adopt_chain("dec", chain)
        while len(toks) < 5:
            _, nxt = m.paged_ragged_step(cache, [("dec", [toks[-1]])])
            toks.append(int(np.asarray(nxt)[0]))
        assert toks == ref

    def test_release_chain_frees_pages_and_claim(self):
        m = MODEL
        cache = m.make_paged_cache(n_pages=16, page_size=4)
        cache.add_sequence("a")
        m.paged_ragged_step(cache, [("a", [1, 2, 3, 4, 5])])
        cache.set_claim("a", 3)
        free_before_prefill = cache.n_free_pages()
        chain = cache.export_chain("a")
        cache.release_chain(chain)
        assert cache.outstanding_claims() == 0
        assert cache.n_free_pages() == free_before_prefill + 2
        cache.release_chain(chain)  # idempotent

    def test_cross_pool_adopt_refused(self):
        m = MODEL
        c1 = m.make_paged_cache(n_pages=16, page_size=4)
        c2 = m.make_paged_cache(n_pages=16, page_size=4)
        c1.add_sequence("a")
        m.paged_ragged_step(c1, [("a", [1, 2, 3])])
        chain = c1.export_chain("a")
        with pytest.raises(ValueError, match="THIS pool"):
            c2.adopt_chain("b", chain)
        c1.release_chain(chain)


# -- the disaggregated router -------------------------------------------

def _metrics_val(name):
    m = monitor.get_metric(name)
    return int(m.value) if m else 0


class TestDisaggregatedRouter:
    def test_handoff_equals_single_engine_with_page_accounting(self):
        """The acceptance run: chains prefilled on engine A decode on
        engine B token-for-token equal to a single-engine run; the
        adoption spy sees every chain's pages alive in the shared pool
        at handoff, and the pool draws exactly as many pages as the
        single-engine run — no copy anywhere on the path."""
        m = MODEL
        rng = np.random.RandomState(8)
        prompts = [rng.randint(0, 64, (n,)) for n in (9, 4, 6)]

        single = GenerationEngine(m, n_pages=64, page_size=4,
                                  max_batch=3, max_new_tokens=8,
                                  prefix_cache=False,
                                  name="fd_single")
        try:
            refs = [h.result(300).tolist() for h in
                    [single.submit(p, max_new_tokens=5)
                     for p in prompts]]
            single_drawn = single.cache.pool_stats()["pages_drawn"]
        finally:
            single.shutdown()

        cache = m.make_paged_cache(64, 4)
        pre = GenerationEngine(m, cache=cache, max_batch=3,
                               max_new_tokens=8, prefix_cache=False,
                               name="fd_pre")
        dec = GenerationEngine(m, cache=cache, max_batch=3,
                               max_new_tokens=8, prefix_cache=False,
                               name="fd_dec")
        router = ServingRouter([pre, dec],
                               roles=("prefill", "decode"),
                               name="fd_router")
        seen = []
        orig_adopt = dec.adopt

        def spy(handle, chain, **kw):
            # at handoff: page identity + liveness in the SHARED pool
            assert all(cache._ref.get(pg, 0) >= 1
                       for pg in chain.pages)
            seen.append((list(chain.pages), int(chain.length)))
            return orig_adopt(handle=handle, chain=chain, **kw)

        dec.adopt = spy
        h0 = _metrics_val("serve.route_handoffs")
        try:
            outs = [h.result(300).tolist() for h in
                    [router.submit(p, max_new_tokens=5,
                                   deadline_ms=120_000)
                     for p in prompts]]
        finally:
            router.shutdown()
        assert outs == refs  # token-for-token across the handoff
        assert len(seen) == len(prompts)
        for (pages, length), p in zip(
                sorted(seen, key=lambda t: -t[1]),
                sorted(prompts, key=lambda p: -p.size)):
            assert length == p.size
            assert len(pages) == -(-p.size // 4)  # ceil(tokens/page)
        stats = cache.pool_stats()
        assert stats["cow_copies"] == 0
        # the shared pool drew exactly what the single engine drew:
        # the handoff moved ids, it never copied a page
        assert stats["pages_drawn"] == single_drawn
        assert _metrics_val("serve.route_handoffs") - h0 \
            == len(prompts)

    def test_sampled_request_equal_across_topologies(self):
        """Seeded sampling survives disaggregation: the per-token key
        is fold_in(seed, position), so engine A prefilling and engine
        B decoding produce the same text as one engine doing both."""
        m = MODEL
        rng = np.random.RandomState(9)
        p = rng.randint(0, 64, (6,))
        sp = lambda: SamplingParams(temperature=0.95, top_k=24, seed=21)

        single = GenerationEngine(m, n_pages=64, page_size=4,
                                  max_batch=2, max_new_tokens=8)
        try:
            ref = single.submit(p, max_new_tokens=6,
                                sampling=sp()).result(300).tolist()
        finally:
            single.shutdown()

        router = ServingRouter.disaggregated(
            m, n_pages=64, page_size=4, max_batch=2,
            max_new_tokens=8, name="fd_samp")
        try:
            got = router.submit(p, max_new_tokens=6,
                                sampling=sp()).result(300).tolist()
        finally:
            router.shutdown()
        assert got == ref

    def test_streaming_first_token_from_prefill_engine(self):
        """TTFT comes from the prefill engine: the first token streams
        before the decode engine produces the rest."""
        m = MODEL
        router = ServingRouter.disaggregated(
            m, n_pages=64, page_size=4, max_batch=2,
            max_new_tokens=8, name="fd_stream")
        try:
            h = router.submit(np.arange(1, 6), max_new_tokens=4,
                              deadline_ms=120_000)
            toks = list(h.tokens())
            assert len(toks) == 4
            assert h.result(10).tolist() == toks
        finally:
            router.shutdown()


# -- router placement ----------------------------------------------------

class TestRouterPlacement:
    def test_prefix_affinity_routes_to_warm_engine(self):
        m = MODEL
        rng = np.random.RandomState(10)
        system = rng.randint(0, 64, (8,))
        eng_a = GenerationEngine(m, n_pages=64, page_size=4,
                                 max_batch=2, max_new_tokens=8,
                                 name="fd_aff_a")
        eng_b = GenerationEngine(m, n_pages=64, page_size=4,
                                 max_batch=2, max_new_tokens=8,
                                 name="fd_aff_b")
        router = ServingRouter([eng_a, eng_b], name="fd_aff")
        try:
            # seed engine A's registry: a completed request registers
            # its prompt's pages at eviction
            eng_a.submit(system, max_new_tokens=2).result(300)
            time.sleep(0.1)
            prompt = np.concatenate([system, rng.randint(0, 64, (3,))])
            placed = []
            for _ in range(4):
                h = router.submit(prompt, max_new_tokens=2,
                                  deadline_ms=120_000)
                h.result(300)
                placed.append(h.trace.engine)
            # sticky: every request lands on the engine holding the
            # registered prefix pages
            assert placed == ["fd_aff_a"] * 4
            assert router.load_report()["routing"][
                "prefix_affinity"] >= 4
        finally:
            router.shutdown()

    def test_fast_fail_when_fleet_saturated(self):
        m = MODEL
        engines = [GenerationEngine(m, n_pages=64, page_size=4,
                                    max_batch=1, max_queue=1,
                                    max_new_tokens=64,
                                    name=f"fd_sat_{i}")
                   for i in range(2)]
        router = ServingRouter(engines, name="fd_sat")
        rej0 = _metrics_val("serve.route_rejected")
        try:
            # saturate: 1 active + 1 queued per engine (long decodes);
            # wait for the first submit to ADMIT before queueing the
            # second, or the engine's own fast-fail rejects the setup
            held = []
            for eng in engines:
                held.append(eng.submit(np.arange(1, 5),
                                       max_new_tokens=60))
                deadline = time.time() + 30
                while eng.load_report().get("active", 0) < 1:
                    assert time.time() < deadline, "admission stuck"
                    time.sleep(0.01)
                held.append(eng.submit(np.arange(1, 5),
                                       max_new_tokens=60))
            with pytest.raises(QueueFullError, match="saturated"):
                router.submit(np.arange(1, 4), max_new_tokens=2)
            assert _metrics_val("serve.route_rejected") == rej0 + 1
            for h in held:
                h.future.cancel()
        finally:
            router.shutdown(wait=False)

    def test_load_balance_spreads_across_engines(self):
        m = MODEL
        engines = [GenerationEngine(m, n_pages=64, page_size=4,
                                    max_batch=1, max_new_tokens=16,
                                    prefix_cache=False,
                                    name=f"fd_lb_{i}")
                   for i in range(2)]
        router = ServingRouter(engines, name="fd_lb")
        try:
            handles = [router.submit(np.arange(1, 6),
                                     max_new_tokens=8,
                                     deadline_ms=120_000)
                       for _ in range(4)]
            for h in handles:
                h.result(300)
            used = {h.trace.engine for h in handles}
            assert len(used) == 2  # queue-depth scoring spreads load
        finally:
            router.shutdown()

    def test_fleet_load_report_dedups_shared_pool(self):
        m = MODEL
        router = ServingRouter.disaggregated(
            m, n_pages=64, page_size=4, max_batch=2, name="fd_rep")
        try:
            rep = router.load_report()
            assert rep["fleet"]["n_engines"] == 2
            assert rep["fleet"]["n_pools"] == 1  # ONE shared pool
            assert set(rep["engines"]) == {"fd_rep_prefill",
                                           "fd_rep_decode"}
            assert rep["roles"]["fd_rep_prefill"] == "prefill"
            single_pool = rep["engines"]["fd_rep_prefill"][
                "admittable_pages"]
            assert rep["fleet"]["admittable_pages"] == single_pool
        finally:
            router.shutdown()

    def test_router_validation(self):
        m = MODEL
        with pytest.raises(ValueError, match="at least one"):
            ServingRouter([])
        eng = GenerationEngine(m, n_pages=16, page_size=4,
                               name="fd_val")
        try:
            with pytest.raises(ValueError, match="submit-capable"):
                ServingRouter([eng], roles=("decode",))
            with pytest.raises(ValueError, match="sharing its page"):
                other = GenerationEngine(m, n_pages=16, page_size=4,
                                         name="fd_val2")
                try:
                    ServingRouter([eng, other],
                                  roles=("prefill", "decode"))
                finally:
                    other.shutdown()
        finally:
            eng.shutdown()

    def test_non_ragged_decode_mate_refused(self):
        """Only the ragged scheduler drains adopted chains: a legacy
        bucketed engine must be refused as the decode mate (and by
        adopt() directly) instead of parking the chain forever."""
        m = MODEL
        cache = m.make_paged_cache(16, 4)
        pre = GenerationEngine(m, cache=cache, max_batch=2,
                               name="fd_nr_pre")
        dec = GenerationEngine(m, cache=cache, max_batch=2,
                               ragged=False, name="fd_nr_dec")
        try:
            with pytest.raises(ValueError, match="ragged"):
                ServingRouter([pre, dec], roles=("prefill", "decode"))
            with pytest.raises(ValueError, match="ragged"):
                dec.adopt(handle=None, chain=None, last_token=0,
                          generated=[])
        finally:
            pre.shutdown()
            dec.shutdown()

    def test_slo_classes(self):
        m = MODEL
        router = ServingRouter.disaggregated(
            m, n_pages=16, page_size=4, name="fd_slo")
        try:
            assert router.slo_class(500) == "interactive"
            assert router.slo_class(60_000) == "standard"
            assert router.slo_class(600_000) == "batch"
            assert router.slo_class(None) == "batch"
        finally:
            router.shutdown()

    def test_identity_stamped_before_scheduler_visibility(self):
        """request_id / router / slo_class land inside engine submit,
        BEFORE the enqueue makes the request visible to the scheduler
        thread — a post-submit stamp races a fast prefill, which can
        stream/export/finish the instant it is queued, producing
        journeys with router=None and records missing the class."""
        eng = GenerationEngine(MODEL, n_pages=16, page_size=4,
                               max_batch=1, max_new_tokens=4,
                               name="fd_stamp_eng")
        try:
            h = eng.submit(np.arange(1, 5), max_new_tokens=2,
                           deadline_ms=60_000,
                           slo_class="standard", router="fd_stamp")
            # stamped by submit itself — no router post-processing ran
            assert h.request_id == h.trace.request_id
            assert h.router == "fd_stamp"
            assert h.trace.slo_class == "standard"
            h.result(300)
        finally:
            eng.shutdown()


# -- schema + report -----------------------------------------------------

def _route_rec(**over):
    rec = {"ts": 1.0, "rank": 0, "kind": "route", "router": "r",
           "engine": "e1", "fleet": ["e1", "e2"],
           "outcome": "dispatched", "slo_class": "interactive",
           "queue_depth": 0}
    rec.update(over)
    return rec


class TestRouteSchema:
    def test_accepts_real_records(self, tmp_path):
        good = [
            _route_rec(),
            _route_rec(outcome="rejected", queue_depth=7),
            _route_rec(outcome="handoff", engine="e2",
                       from_engine="e1", pages_moved=3,
                       chain_tokens=9, page_size=4,
                       request_id="e1-r0"),
            _route_rec(prefix_affinity=True, prefix_match_pages=2,
                       deadline_ms=5000.0),
            # a recurrent handoff moves ONE state blob, zero pages
            _route_rec(outcome="handoff", engine="e2",
                       from_engine="e1", pages_moved=0,
                       chain_tokens=9, page_size=4,
                       cache_strategy="recurrent", state_bytes=4096,
                       request_id="e1-r1"),
            # a hybrid handoff moves pages AND the SSM half's blob
            _route_rec(outcome="handoff", engine="e2",
                       from_engine="e1", pages_moved=3,
                       chain_tokens=9, page_size=4,
                       cache_strategy="hybrid", state_bytes=4096,
                       request_id="e1-r2"),
        ]
        for rec in good:
            assert cms.validate_line(json.dumps(rec)) == []

    @pytest.mark.parametrize("bad,needle", [
        (_route_rec(outcome="routed"), "outcome"),
        (_route_rec(engine="ghost"), "not in fleet"),
        (_route_rec(fleet=[]), "fleet"),
        (_route_rec(queue_depth=-1), "queue_depth"),
        (_route_rec(outcome="handoff", engine="e2", from_engine="e2",
                    pages_moved=1, chain_tokens=4, page_size=4),
         "itself"),
        (_route_rec(outcome="handoff", engine="e2", from_engine="e1",
                    pages_moved=5, chain_tokens=9, page_size=4),
         "reconcile"),
        (_route_rec(outcome="handoff", engine="e2", from_engine="e1"),
         "pages_moved"),
        (_route_rec(prefix_affinity="yes"), "prefix_affinity"),
        (_route_rec(deadline_ms=-5), "deadline_ms"),
        (_route_rec(cache_strategy="magnetic"), "cache_strategy"),
        # recurrent: pages crossing the wire means the strategy lied
        (_route_rec(outcome="handoff", engine="e2", from_engine="e1",
                    pages_moved=3, chain_tokens=9, page_size=4,
                    cache_strategy="recurrent", state_bytes=4096),
         "state blob"),
        # recurrent: a zero-byte blob carried nothing
        (_route_rec(outcome="handoff", engine="e2", from_engine="e1",
                    pages_moved=0, chain_tokens=9, page_size=4,
                    cache_strategy="recurrent", state_bytes=0),
         "state_bytes"),
        # hybrid still reconciles its page half
        (_route_rec(outcome="handoff", engine="e2", from_engine="e1",
                    pages_moved=5, chain_tokens=9, page_size=4,
                    cache_strategy="hybrid", state_bytes=4096),
         "reconcile"),
    ])
    def test_rejects_bad_records(self, bad, needle):
        errs = cms.validate_line(json.dumps(bad))
        assert errs and any(needle in e for e in errs), (errs, needle)

    def test_live_records_validate_and_render(self, tmp_path,
                                              monkeypatch):
        """A real disaggregated run's JSONL passes the schema lint and
        obs_report renders the routing section from it."""
        mfile = tmp_path / "metrics.jsonl"
        # monitor.metrics_file() reads the env on every export
        monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
        router = ServingRouter.disaggregated(
            MODEL, n_pages=64, page_size=4, max_batch=2,
            max_new_tokens=8, name="fd_live")
        try:
            router.submit(np.arange(1, 7), max_new_tokens=3,
                          deadline_ms=120_000).result(300)
        finally:
            router.shutdown()
        lines = [json.loads(l) for l in
                 mfile.read_text().splitlines() if l.strip()]
        routes = [r for r in lines if r.get("kind") == "route"]
        outcomes = {r["outcome"] for r in routes}
        assert {"dispatched", "handoff"} <= outcomes
        # ONE class per request across its records: the handoff stamps
        # the submit-time deadline's class (120s -> standard), never a
        # reclassification from the time remaining at prefill exit
        assert {r["slo_class"] for r in routes} == {"standard"}
        errs = [e for r in routes
                for e in cms.validate_line(json.dumps(r))]
        assert errs == []
        sys.path.insert(0, os.path.join(REPO, "tools"))
        import obs_report
        text = obs_report.render(lines)
        assert "== routing ==" in text
        assert "handoff fd_live_prefill -> fd_live_decode" in text
