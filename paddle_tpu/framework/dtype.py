"""Dtype system.

Parity: python/paddle/framework/dtype.py (reference). Paddle exposes dtype
singletons (paddle.float32, ...) and string aliases; we map them onto numpy
dtypes, which JAX consumes directly. float64/int64 are available but note
that on TPU f64 is emulated; the default compute dtype is float32 with
bfloat16 as the AMP-preferred type (TPU MXU-native).
"""
import numpy as np
import jax.numpy as jnp
import ml_dtypes

__all__ = [
    "dtype", "float16", "bfloat16", "float32", "float64", "int8", "int16",
    "int32", "int64", "uint8", "bool_", "complex64", "complex128",
    "set_default_dtype", "get_default_dtype", "convert_dtype", "iinfo", "finfo",
]


class dtype:
    """A paddle-style dtype handle wrapping a numpy dtype."""

    _registry = {}

    def __init__(self, name, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        dtype._registry[name] = self
        dtype._registry[self.np_dtype] = self

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __eq__(self, other):
        try:
            return self.np_dtype == convert_dtype(other)
        except (TypeError, ValueError):
            return NotImplemented

    def __hash__(self):
        return hash(self.np_dtype)

    @property
    def is_floating_point(self):
        return jnp.issubdtype(self.np_dtype, np.floating)


float16 = dtype("float16", np.float16)
bfloat16 = dtype("bfloat16", ml_dtypes.bfloat16)
float32 = dtype("float32", np.float32)
float64 = dtype("float64", np.float64)
int8 = dtype("int8", np.int8)
int16 = dtype("int16", np.int16)
int32 = dtype("int32", np.int32)
int64 = dtype("int64", np.int64)
uint8 = dtype("uint8", np.uint8)
bool_ = dtype("bool", np.bool_)
complex64 = dtype("complex64", np.complex64)
complex128 = dtype("complex128", np.complex128)

_STR_ALIASES = {
    "float16": np.float16, "fp16": np.float16, "half": np.float16,
    "bfloat16": ml_dtypes.bfloat16, "bf16": ml_dtypes.bfloat16,
    "float32": np.float32, "fp32": np.float32, "float": np.float32,
    "float64": np.float64, "fp64": np.float64, "double": np.float64,
    "int8": np.int8, "int16": np.int16, "int32": np.int32, "int64": np.int64,
    "uint8": np.uint8, "bool": np.bool_,
    "complex64": np.complex64, "complex128": np.complex128,
}

_default_dtype = np.dtype(np.float32)


def convert_dtype(d):
    """Normalize any dtype spec (paddle dtype, str, numpy, jnp) to np.dtype."""
    if d is None:
        return None
    if isinstance(d, dtype):
        return d.np_dtype
    if isinstance(d, str):
        if d in _STR_ALIASES:
            return np.dtype(_STR_ALIASES[d])
        raise ValueError(f"unsupported dtype string: {d!r}")
    return np.dtype(d)


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if not (jnp.issubdtype(d, np.floating)):
        raise TypeError("default dtype must be floating point")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype


def iinfo(d):
    return np.iinfo(convert_dtype(d))


def finfo(d):
    return jnp.finfo(convert_dtype(d))
