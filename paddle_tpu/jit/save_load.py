"""paddle.jit.save / load.

Parity: python/paddle/fluid/dygraph/jit.py:save + io.py:TranslatedLayer.
TPU-native format: instead of a ProgramDesc proto + LoDTensor params
(`__model__` + `*.pdiparams`), we serialize the traced computation as
portable StableHLO bytes via jax.export plus a pickled numpy state dict:

    <path>.pdmodel   — serialized StableHLO (jax.export.Exported bytes)
    <path>.pdiparams — pickled {name: ndarray} state
    <path>.meta      — input specs / structure

The exported artifact is exactly what Paddle Inference loads (see
paddle_tpu/inference), and runs on any PjRt backend.
"""
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..framework.core import Tensor, no_grad
from .api import StaticFunction, functional_call, state_arrays

__all__ = ["save", "load", "TranslatedLayer", "InputSpec"]


class InputSpec:
    """Parity: python/paddle/static/input.py:InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return (f"InputSpec(shape={list(self.shape)}, "
                f"dtype={self.dtype}, name={self.name})")

    @classmethod
    def from_tensor(cls, tensor, name=None):
        """Describe an existing Tensor (static/input.py from_tensor)."""
        return cls(tuple(tensor.shape), str(np.dtype(tensor.dtype)),
                   name or getattr(tensor, "name", None))

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size):
        """Insert batch_size in front of shape, in place."""
        if isinstance(batch_size, (list, tuple)):
            if len(batch_size) != 1:
                raise ValueError(
                    f"Length of batch_size: {batch_size} shall be 1, "
                    f"but received {len(batch_size)}.")
            batch_size = batch_size[0]
        self.shape = (int(batch_size),) + self.shape
        return self

    def unbatch(self):
        """Drop the leading dim of shape, in place."""
        if not self.shape:
            raise ValueError(
                "Not support to unbatch a InputSpec when len(shape) == 0.")
        self.shape = self.shape[1:]
        return self

    _sym_counter = [0]

    def to_shape_dtype(self):
        from ..framework.dtype import convert_dtype
        dims = []
        for s in self.shape:
            if s is None or s == -1:
                # dynamic axis → jax.export symbolic dimension, so the
                # serialized StableHLO stays batch-polymorphic
                InputSpec._sym_counter[0] += 1
                dims.append(f"_pd_b{InputSpec._sym_counter[0]}")
            else:
                dims.append(str(int(s)))
        if any(d.startswith("_pd_b") for d in dims):
            shape = jax_export.symbolic_shape(",".join(dims))
        else:
            shape = tuple(int(d) for d in dims)
        return jax.ShapeDtypeStruct(shape, convert_dtype(self.dtype))


def _save_function(sf, path, input_spec):
    """Save a @to_static-decorated plain FUNCTION (reference
    dygraph/jit.py 'example 2: save function'). RNG ops inside would bake
    a fixed key — saved functions are deterministic transforms."""
    from ..framework.core import no_grad
    from ..framework.random import rng_scope
    from .dy2static import convert_to_static
    fn = convert_to_static(sf._obj if isinstance(sf, StaticFunction)
                           else sf)
    if input_spec is None:
        if isinstance(sf, StaticFunction) and sf._input_spec:
            input_spec = list(sf._input_spec)
        elif isinstance(sf, StaticFunction) and sf._cache:
            input_spec = [
                InputSpec([None] + list(shape)[1:] if len(shape) >= 1
                          else [], dtype)
                for shape, dtype in list(sf._cache)[-1]]
        else:
            raise ValueError(
                "jit.save on a function requires input_spec (or at least "
                "one prior call to record shapes)")
    specs = [s.to_shape_dtype() if isinstance(s, InputSpec)
             else jax.ShapeDtypeStruct(tuple(s.shape), s.value.dtype)
             for s in input_spec]
    fixed_key = jax.random.PRNGKey(0)

    def pure(*xs):
        with no_grad(), rng_scope(fixed_key):
            out = fn(*[Tensor(x) for x in xs])
        return jax.tree.map(
            lambda t: t.value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    exported = jax_export.export(jax.jit(pure))(*specs)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({"params": {}, "buffers": {}}, f, protocol=4)
    meta = {"kind": "function",
            "input_specs": [(tuple(str(dd) for dd in s.shape),
                             str(s.dtype)) for s in specs]}
    with open(path + ".meta", "wb") as f:
        pickle.dump(meta, f, protocol=4)


def save(layer, path, input_spec=None, **configs):
    from ..nn.layer.layers import Layer
    if isinstance(layer, StaticFunction):
        if not layer._is_layer:
            return _save_function(layer, path, input_spec)
        layer = layer.wrapped
    if callable(layer) and not isinstance(layer, Layer) and \
            hasattr(layer, "__code__"):
        return _save_function(layer, path, input_spec)
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer (or converted Layer)")
    if input_spec is None:
        # reference behavior (dygraph/jit.py example 1): a layer whose
        # forward was @to_static-decorated can be saved without specs —
        # infer from the decorator's input_spec or the signatures its
        # compiled cache recorded during training
        fwd = type(layer).forward
        sf = layer.__dict__.get("_jit_static_forward")
        if isinstance(fwd, StaticFunction) and fwd._input_spec:
            input_spec = list(fwd._input_spec)
        elif sf is not None and sf._cache:
            last_sig = list(sf._cache)[-1]
            input_spec = [
                InputSpec([None] + list(shape)[1:] if len(shape) >= 1
                          else [], dtype)
                for shape, dtype in last_sig]
        else:
            raise ValueError(
                "jit.save requires input_spec on first save (or a "
                "@to_static forward that has been called at least once)")

    params, buffers = state_arrays(layer)
    specs = [s.to_shape_dtype() if isinstance(s, InputSpec)
             else jax.ShapeDtypeStruct(tuple(s.shape),
                                       s.value.dtype) for s in input_spec]

    def pure(params, buffers, *xs):
        return functional_call(layer, params, buffers, xs, training=False,
                               convert=True)

    exported = jax_export.export(jax.jit(pure))(
        jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                     params),
        jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                     buffers),
        *specs)
    # vjp_order=1: the serialized StableHLO carries its VJP, so jit.load
    # supports fine-tune training (reference TranslatedLayer train mode,
    # fluid/dygraph/jit.py 'example 3: load & fine-tune')
    blob = exported.serialize(vjp_order=1)

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    state = {"params": {k: np.asarray(v) for k, v in params.items()},
             "buffers": {k: np.asarray(v) for k, v in buffers.items()}}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    meta = {"input_specs": [(tuple(str(d) for d in s.shape), str(s.dtype))
                            for s in specs]}
    with open(path + ".meta", "wb") as f:
        pickle.dump(meta, f, protocol=4)


class TranslatedLayer:
    """A loaded computation, callable like the original Layer. When the
    artifact was saved with a VJP (the default), it also FINE-TUNES: the
    call runs as a taped op over its live Parameters, so loss.backward()
    + optimizer.step() train it (reference TranslatedLayer semantics,
    fluid/dygraph/jit.py 'example 3: load & fine-tune')."""

    def __init__(self, exported, params, buffers, meta):
        from ..framework.core import Parameter
        self._exported = exported
        self._param_names = list(params)
        self._param_t = {k: Parameter(jnp.asarray(v), name=k)
                         for k, v in params.items()}
        self._buffers = {k: jnp.asarray(v) for k, v in buffers.items()}
        self._meta = meta
        self._call = jax.jit(exported.call)
        self._training = False

    def __call__(self, *args):
        from ..framework.core import apply_op, is_grad_enabled
        if self._meta.get("kind") == "function":
            arrays = [a.value if isinstance(a, Tensor) else jnp.asarray(a)
                      for a in args]
            return jax.tree.map(Tensor, self._call(*arrays))
        named = [(k, self._param_t[k]) for k in self._param_names]
        # tape only in train mode: train() is the gate that checked
        # has_vjp(), and eval-mode inference must not retain autograd
        # graphs per call
        if self._training and is_grad_enabled() and any(
                not p.stop_gradient for _, p in named):
            n = len(named)

            def fn(*flat, _names=tuple(self._param_names), _n=n,
                   _c=self._call, _b=self._buffers):
                pd = dict(zip(_names, flat[:_n]))
                return _c(pd, _b, *flat[_n:])

            tensor_args = [a if isinstance(a, Tensor) else Tensor(a)
                           for a in args]
            return apply_op(fn, *[p for _, p in named], *tensor_args)
        arrays = [a.value if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
        out = self._call({k: p.value for k, p in named}, self._buffers,
                         *arrays)
        return jax.tree.map(Tensor, out)

    forward = __call__

    def eval(self):
        self._training = False
        return self

    def train(self):
        if not self._exported.has_vjp():
            raise RuntimeError(
                "this artifact was serialized without a VJP "
                "(vjp_order=0) — re-save it to fine-tune")
        self._training = True
        return self

    def parameters(self):
        return [self._param_t[k] for k in self._param_names]

    def named_parameters(self):
        return [(k, self._param_t[k]) for k in self._param_names]

    def state_dict(self):
        out = {k: Tensor(p.value) for k, p in self._param_t.items()}
        out.update({k: Tensor(v) for k, v in self._buffers.items()})
        return out

    def clear_gradients(self):
        for p in self._param_t.values():
            p.clear_grad()


def load(path, params_path=None, **configs):
    """Load a saved artifact. `params_path` overrides the default
    `<path>.pdiparams` sibling — the inference Config(model_path,
    params_path) pair maps straight onto it (reference AnalysisConfig
    keeps the program and the weights as two independent files)."""
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(bytearray(f.read()))
    with open(params_path or (path + ".pdiparams"), "rb") as f:
        state = pickle.load(f)
    meta = {}
    if os.path.exists(path + ".meta"):
        with open(path + ".meta", "rb") as f:
            meta = pickle.load(f)
    return TranslatedLayer(exported, state["params"], state["buffers"],
                           meta)
