"""paddle.device.cuda shim mapping onto the TPU runtime.
Parity: python/paddle/device/cuda/__init__.py — importable as a real
submodule so `from paddle.device.cuda import synchronize` works."""
from . import Stream, Event  # noqa: F401
from . import synchronize as _synchronize, _default_device

__all__ = ["Stream", "Event", "device_count", "synchronize",
           "max_memory_allocated", "memory_allocated", "empty_cache"]


def device_count():
    return 0


def synchronize(device=None):
    _synchronize()


def max_memory_allocated(device=None):
    d = _default_device()
    if hasattr(d, "memory_stats"):
        return d.memory_stats().get("peak_bytes_in_use", 0)
    return 0


def memory_allocated(device=None):
    d = _default_device()
    if hasattr(d, "memory_stats"):
        return d.memory_stats().get("bytes_in_use", 0)
    return 0


def empty_cache():
    pass
