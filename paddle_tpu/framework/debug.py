"""Debugging aids. Parity: paddle/fluid/framework/details/nan_inf_utils*
(check_nan_inf debug mode) + FLAGS_check_nan_inf.

TPU-native: eager mode checks each op output on the host; under jit use
enable_jit_nan_checks() which flips jax's debug_nans (XLA-level check that
re-runs the failing computation op-by-op to localize the NaN).
"""
import os

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["set_nan_inf_check", "check_numerics", "enable_jit_nan_checks",
           "TensorStats"]

_nan_check_enabled = [
    os.environ.get("FLAGS_check_nan_inf", "0") in ("1", "true")]


def set_nan_inf_check(enabled):
    _nan_check_enabled[0] = bool(enabled)


def nan_check_enabled():
    return _nan_check_enabled[0]


def check_numerics(arr, op_name="op"):
    if isinstance(arr, jax.core.Tracer):
        return arr
    if jnp.issubdtype(arr.dtype, jnp.floating) and \
            bool(jnp.any(~jnp.isfinite(arr))):
        n_nan = int(jnp.sum(jnp.isnan(arr)))
        n_inf = int(jnp.sum(jnp.isinf(arr)))
        raise FloatingPointError(
            f"NaN/Inf detected in output of '{op_name}': "
            f"{n_nan} NaNs, {n_inf} Infs, shape {arr.shape}")
    return arr


def enable_jit_nan_checks(enabled=True):
    jax.config.update("jax_debug_nans", bool(enabled))


class TensorStats:
    """Summarize a tensor for debugging (min/max/mean/nan counts)."""

    def __init__(self, t, name=""):
        arr = np.asarray(t.value if hasattr(t, "value") else t)
        self.name = name
        self.shape = arr.shape
        self.dtype = arr.dtype
        if np.issubdtype(arr.dtype, np.floating) and arr.size:
            self.min = float(np.nanmin(arr))
            self.max = float(np.nanmax(arr))
            self.mean = float(np.nanmean(arr))
            self.n_nan = int(np.isnan(arr).sum())
            self.n_inf = int(np.isinf(arr).sum())
        else:
            self.min = self.max = self.mean = None
            self.n_nan = self.n_inf = 0

    def __repr__(self):
        return (f"TensorStats({self.name} shape={self.shape} "
                f"dtype={self.dtype} min={self.min} max={self.max} "
                f"mean={self.mean} nan={self.n_nan} inf={self.n_inf})")
