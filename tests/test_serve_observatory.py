"""Serving observatory (ISSUE 11): per-request lifecycle ledger, KV
page-pool telemetry, SLO/goodput accounting, and the forensic surfaces
built on them.

Proof points:
- every request submitted to either engine lands EXACTLY ONE
  schema-valid `kind:"request"` record whose token counts reconcile
  with the engine's aggregate counters;
- outcome coverage: completed / rejected / expired (including the new
  GenerationEngine deadline_ms) / cancelled / error;
- `PagedKVCache.pool_stats()` refcount/CoW/reclaim accounting matches
  known sharing scenarios, and the engine loop emits periodic
  `kind:"kvcache"` snapshots + serve.kv_* gauges;
- goodput vs wasted token split; `load_report()` sanity under
  admit/evict; Histogram.snapshot() p50/p99;
- Perfetto "serving requests" lanes + kv counter tracks pass the trace
  lint, and merged per-rank traces stay rank-safe;
- debug bundles carry requests_tail.jsonl + serve_state.json;
- the hot-sync fence covers the new observatory call sites, and the
  observatory's steady-state overhead stays within noise (calibrated
  best-of-3, the PR 5 container pattern).
"""
import importlib.util
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference import serving
from paddle_tpu.inference.serving import (
    InferenceEngine, GenerationEngine, QueueFullError, DeadlineExceeded)
from paddle_tpu.ops.paged_attention import PagedKVCache
from paddle_tpu.profiler import (flight_recorder, monitor,
                                 serve_observatory as sobs, statistic,
                                 trace_export)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    statistic.reset_statistics()
    monitor.reset_metrics()
    sobs.reset()
    yield


def _load_tool(name):
    path = os.path.join(REPO, "tools", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mlp(din=8, dout=4, seed=0):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(din, 16), nn.Tanh(),
                         nn.Linear(16, dout))


def _x(n=1, d=8, seed=0):
    return np.random.RandomState(seed).randn(n, d).astype(np.float32)


def _tiny_lm(seed=0):
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _request_records(path):
    with open(path) as f:
        return [json.loads(l) for l in f
                if l.strip() and json.loads(l).get("kind") == "request"]


# -- Histogram.snapshot percentiles (satellite) -------------------------

def test_histogram_snapshot_carries_percentiles():
    h = monitor.histogram("obs.lat")
    for v in range(1, 101):
        h.observe(v / 100.0)
    snap = h.snapshot()
    assert snap["p50"] == pytest.approx(h.percentile(50))
    assert snap["p99"] == pytest.approx(h.percentile(99))
    assert snap["p50"] == pytest.approx(0.5, abs=0.02)
    assert snap["p99"] == pytest.approx(0.99, abs=0.02)
    # empty histogram: zeros, not a crash
    assert monitor.histogram("obs.empty").snapshot()["p99"] == 0.0
    # and metrics_snapshot serializes them
    assert monitor.metrics_snapshot()["obs.lat"]["p99"] > 0


# -- InferenceEngine request ledger -------------------------------------

def test_inference_request_records_complete_and_validate(
        tmp_path, monkeypatch):
    path = str(tmp_path / "m.jsonl")
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", path)
    eng = InferenceEngine(_mlp(), batch_sizes=(1, 2), name="obs_inf")
    try:
        eng(_x())
        eng(_x(2))
    finally:
        eng.shutdown()
    recs = _request_records(path)
    assert len(recs) == 2  # exactly one record per submitted request
    assert all(r["engine"] == "obs_inf" for r in recs)
    assert all(r["outcome"] == "completed" for r in recs)
    assert [r["rows"] for r in recs] == [1, 2]
    for r in recs:
        assert 0 <= r["queue_s"] <= r["latency_s"]
        assert r["generated_tokens"] == 0  # inference: no decode
    # reconciles with the engine's aggregate counter
    assert monitor.get_metric("serve.requests").value == len(recs)
    cms = _load_tool("check_metrics_schema")
    assert cms.validate_file(path) == []


def test_rejected_queue_full_lands_request_record():
    eng = InferenceEngine(_mlp(), batch_sizes=(1,), max_queue=0,
                          name="obs_rej")
    try:
        with pytest.raises(QueueFullError):
            eng.submit(_x())
    finally:
        eng.shutdown()
    recs = [r for r in sobs.requests_tail() if r["engine"] == "obs_rej"]
    assert len(recs) == 1 and recs[0]["outcome"] == "rejected"
    assert recs[0]["generated_tokens"] == 0
    assert sobs.slo_report()["outcomes"]["rejected"] >= 1


def test_expired_and_cancelled_close_their_traces():
    eng = InferenceEngine(_mlp(), batch_sizes=(1,), name="obs_exp")
    try:
        eng.pause()
        dead = eng.submit(_x(), deadline_ms=1)
        gone = eng.submit(_x())
        assert gone.cancel()
        time.sleep(0.02)
        eng.resume()
        with pytest.raises(DeadlineExceeded):
            dead.result(timeout=30)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:  # traces close asynchronously
            outs = sorted(r["outcome"] for r in sobs.requests_tail()
                          if r["engine"] == "obs_exp")
            if outs == ["cancelled", "expired"]:
                break
            time.sleep(0.01)
        assert outs == ["cancelled", "expired"]
        exp = next(r for r in sobs.requests_tail()
                   if r["engine"] == "obs_exp"
                   and r["outcome"] == "expired")
        assert exp["deadline_s"] == pytest.approx(0.001)
        assert exp["deadline_met"] is False
    finally:
        eng.shutdown()


def test_error_outcome_carries_the_exception():
    def fn(x):
        if x.shape[-1] == 3:
            raise ValueError("bad feature dim")
        return x * 2

    eng = InferenceEngine(fn, batch_sizes=(1,), name="obs_err")
    try:
        with pytest.raises(ValueError, match="bad feature dim"):
            eng.submit(np.ones((1, 3), np.float32)).result(timeout=30)
    finally:
        eng.shutdown()
    rec = next(r for r in sobs.requests_tail()
               if r["engine"] == "obs_err")
    assert rec["outcome"] == "error"
    assert "bad feature dim" in rec["error"]


# -- PagedKVCache.pool_stats (the pool observatory) ---------------------

def test_pool_stats_sharing_cow_and_reclaim_accounting():
    cache = PagedKVCache(n_layers=1, n_pages=8, page_size=4, n_heads=1,
                         head_dim=4)
    s0 = cache.pool_stats()
    assert s0["free_pages"] == 7 and s0["held_pages"] == 0
    assert s0["free_pages"] + s0["held_pages"] == s0["n_pages"] - 1

    rng = np.random.RandomState(0)
    toks = list(range(8))
    cache.add_sequence("a")
    kv = rng.randn(8, 1, 4).astype(np.float32)
    cache.extend("a", 0, kv, kv)
    cache.advance("a", 8)
    cache.register_prefix("a", toks)
    st = cache.pool_stats()
    assert st["registered_pages"] == 2 and st["prefix_nodes"] == 2
    assert st["pages_drawn"] == 2  # cumulative draws so far
    assert st["shared_pages"] == 2  # seq + registry hold the same pages
    assert st["refcounts"] == {"2": 2}
    cache.free_sequence("a")
    st = cache.pool_stats()
    assert st["evictable_pages"] == 2 and st["refcounts"] == {"1": 2}

    # partial-tail acquire (6 of 8 tokens) then a write -> copy-on-write
    cache.add_sequence("b")
    assert cache.acquire_prefix("b", toks, max_tokens=6) == 6
    st = cache.pool_stats()
    assert st["shared_pages"] == 2  # registry + b
    cache.extend("b", 0, kv[:1], kv[:1])  # token 6 -> CoW of page 2
    st = cache.pool_stats()
    assert st["cow_copies"] == 1
    assert st["pages_drawn"] == 3  # the CoW copy was a draw
    cache.free_sequence("b")

    # drain the pool: LRU reclaim evicts the registered chain
    cache.add_sequence("c")
    big = rng.randn(28, 1, 4).astype(np.float32)
    cache.extend("c", 0, big, big)  # 7 pages: needs the registry's 2
    st = cache.pool_stats()
    assert st["lru_reclaims"] >= 2
    assert st["registered_pages"] == 0


# -- generation: the full lifecycle -------------------------------------

@pytest.mark.heavy
class TestGenerationObservatory:
    def test_request_records_token_accurate_and_kvcache_snapshots(
            self, tmp_path, monkeypatch):
        path = str(tmp_path / "m.jsonl")
        monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", path)
        eng = GenerationEngine(_tiny_lm(), n_pages=64, page_size=4,
                               max_batch=4, max_new_tokens=4,
                               name="obs_gen", kv_snapshot_every=1)
        try:
            rng = np.random.RandomState(0)
            prompts = [rng.randint(0, 64, (n,)) for n in (5, 3, 7)]
            handles = [eng.submit(p, deadline_ms=120_000)
                       for p in prompts]
            outs = [h.result(timeout=300) for h in handles]
        finally:
            eng.shutdown()
        recs = _request_records(path)
        assert len(recs) == 3  # exactly one per submitted request
        assert all(r["engine"] == "obs_gen" for r in recs)
        assert all(r["outcome"] == "completed" for r in recs)
        # token-accurate: per-request counts match the results, the sum
        # matches the engine's aggregate counters
        assert sorted(r["generated_tokens"] for r in recs) == \
            sorted(len(o) for o in outs)
        assert sorted(r["prompt_tokens"] for r in recs) == [3, 5, 7]
        total = sum(r["generated_tokens"] for r in recs)
        assert monitor.get_metric("serve.generated_tokens").value == total
        assert monitor.get_metric("serve.goodput_tokens").value == total
        assert monitor.get_metric("serve.wasted_tokens") is None
        for r in recs:
            assert r["prefill_chunks"] >= 1
            assert r["peak_pages_held"] >= 1
            assert r["deadline_met"] is True
            assert r["queue_s"] + r["prefill_s"] + r["decode_s"] <= \
                r["latency_s"] + 1e-3
        # the pool observatory snapshotted from the loop
        with open(path) as f:
            kvs = [json.loads(l) for l in f
                   if l.strip()
                   and json.loads(l).get("kind") == "kvcache"]
        assert kvs and all(k["engine"] == "obs_gen" for k in kvs)
        assert monitor.get_metric("serve.kv_peak_held_pages").value >= 1
        assert eng.kv_peak_occupancy() > 0
        # TPOT observed for completed multi-token requests
        assert monitor.get_metric("serve.tpot_s").count == 3
        cms = _load_tool("check_metrics_schema")
        assert cms.validate_file(path) == []

    def test_generation_deadline_expires_in_queue(self):
        eng = GenerationEngine(_tiny_lm(), n_pages=64, page_size=4,
                               max_batch=2, max_new_tokens=3,
                               name="obs_dl")
        try:
            h = eng.submit(np.array([1, 2, 3]), deadline_ms=0)
            with pytest.raises(DeadlineExceeded):
                h.result(timeout=60)
            assert monitor.get_metric("serve.expired").value == 1
            rec = next(r for r in sobs.requests_tail()
                       if r["engine"] == "obs_dl")
            assert rec["outcome"] == "expired"
            assert rec["generated_tokens"] == 0
            slo = sobs.slo_report()
            assert slo["deadline"]["requests"] == 1
            assert slo["deadline"]["met"] == 0
            assert slo["deadline"]["attainment"] == 0.0
            # the engine still serves after the expiry
            ok = eng.submit(np.array([4, 5]), deadline_ms=120_000)
            assert len(ok.result(timeout=300)) == 3
            assert sobs.slo_report()["deadline"]["attainment"] == 0.5
        finally:
            eng.shutdown()

    def test_saturated_engine_still_sheds_expired_head(self):
        # max_batch=1 and a long-running active request: the admission
        # loop hits its capacity gate every cycle, but an expired head
        # must be shed anyway — overload is exactly the regime
        # deadline-based shedding exists for
        eng = GenerationEngine(_tiny_lm(), n_pages=64, page_size=4,
                               max_batch=1, max_new_tokens=40,
                               name="obs_shed")
        try:
            busy = eng.submit(np.array([1, 2, 3]), max_new_tokens=40)
            next(busy.tokens())  # the engine is saturated now
            dead = eng.submit(np.array([4, 5]), deadline_ms=1)
            with pytest.raises(DeadlineExceeded):
                dead.result(timeout=60)
            rec = next(r for r in sobs.requests_tail()
                       if r["engine"] == "obs_shed"
                       and r["outcome"] == "expired")
            assert rec["generated_tokens"] == 0
            busy.future.cancel()
        finally:
            eng.shutdown()

    def test_goodput_vs_wasted_split_on_cancel(self):
        eng = GenerationEngine(_tiny_lm(), n_pages=64, page_size=4,
                               max_batch=1, max_new_tokens=40,
                               name="obs_waste")
        try:
            h = eng.submit(np.array([1, 2, 3]), max_new_tokens=40)
            next(h.tokens())  # at least one token generated
            assert h.future.cancel()
            h2 = eng.submit(np.array([4, 5]), max_new_tokens=2)
            assert len(h2.result(timeout=300)) == 2
            assert eng.drain(timeout=60)
        finally:
            eng.shutdown()
        recs = [r for r in sobs.requests_tail()
                if r["engine"] == "obs_waste"]
        assert sorted(r["outcome"] for r in recs) == \
            ["cancelled", "completed"]
        wasted = sum(r["generated_tokens"] for r in recs
                     if r["outcome"] != "completed")
        good = sum(r["generated_tokens"] for r in recs
                   if r["outcome"] == "completed")
        assert wasted >= 1 and good == 2
        assert monitor.get_metric("serve.wasted_tokens").value == wasted
        assert monitor.get_metric("serve.goodput_tokens").value == good

    def test_load_report_sanity_under_admit_evict(self):
        eng = GenerationEngine(_tiny_lm(), n_pages=16, page_size=4,
                               max_batch=2, max_new_tokens=6,
                               name="obs_load")
        try:
            usable = eng.cache.n_pages - 1
            rep0 = eng.load_report()
            assert rep0["active"] == 0 and rep0["queue_depth"] == 0
            assert rep0["free_pages"] == usable
            assert rep0["admittable_pages"] == usable
            rng = np.random.RandomState(1)
            hs = [eng.submit(rng.randint(0, 64, (5,))) for _ in range(3)]
            # while traffic is in flight the report stays consistent
            for _ in range(50):
                rep = eng.load_report()
                assert 0 <= rep["active"] <= rep["max_batch"]
                assert rep["slots_free"] == rep["max_batch"] - rep["active"]
                assert 0 <= rep["free_pages"] <= usable
                assert rep["admittable_pages"] <= \
                    rep["free_pages"] + rep["evictable_pages"]
                assert rep["admittable_tokens"] == \
                    rep["admittable_pages"] * eng.cache.page_size
                if any(not h.future.done() for h in hs):
                    time.sleep(0.01)
            for h in hs:
                h.result(timeout=300)
            assert eng.drain(timeout=300)
            rep = eng.load_report()
            assert rep["active"] == 0 and rep["reserved_pages"] == 0
            assert rep["ttft_p99_s"] >= rep["ttft_p50_s"] >= 0.0
            assert rep["kv_peak_occupancy"] > 0
            # debug-bundle snapshot path
            snap = eng.observatory_snapshot()
            assert snap["load_report"]["engine"] == "obs_load"
            assert snap["pool_stats"]["n_pages"] == 16
        finally:
            eng.shutdown()

    def test_prefix_hits_land_in_request_records(self):
        eng = GenerationEngine(_tiny_lm(), n_pages=64, page_size=4,
                               max_batch=2, max_new_tokens=2,
                               name="obs_pfx")
        try:
            prompt = np.arange(9) % 64
            eng.submit(prompt).result(timeout=300)  # registers at evict
            eng.submit(prompt).result(timeout=300)  # shares the prefix
        finally:
            eng.shutdown()
        # ring order == completion order == submit order (sequential)
        recs = [r for r in sobs.requests_tail()
                if r["engine"] == "obs_pfx"]
        assert recs[0]["prefix_hit_tokens"] == 0
        assert recs[1]["prefix_hit_tokens"] == 8  # two full pages
        assert recs[1]["prefix_hit_tokens"] <= recs[1]["prompt_tokens"]


# -- timeline + forensics ----------------------------------------------

def _ring_request(engine, rid, outcome, start_off, queue_s, prefill_s,
                  decode_s, rank=0):
    lat = queue_s + prefill_s + decode_s
    flight_recorder.record_record({
        "ts": time.time() + start_off + lat, "rank": rank,
        "kind": "request", "engine": engine, "request_id": rid,
        "outcome": outcome, "rows": 1, "prompt_tokens": 4,
        "prefix_hit_tokens": 0, "generated_tokens": 3,
        "prefill_chunks": 1, "peak_pages_held": 2,
        "queue_s": queue_s, "prefill_s": prefill_s,
        "decode_s": decode_s, "latency_s": lat})


def test_trace_export_serving_requests_track(tmp_path):
    flight_recorder.reset()
    # two OVERLAPPING lifetimes + one later one (lane reuse)
    _ring_request("g", "g-r0", "completed", 0.0, 0.1, 0.2, 0.7)
    _ring_request("g", "g-r1", "cancelled", 0.2, 0.3, 0.2, 0.5)
    _ring_request("g", "g-r2", "completed", 5.0, 0.1, 0.1, 0.1)
    flight_recorder.record_record({
        "ts": time.time(), "rank": 0, "kind": "kvcache", "engine": "g",
        "n_pages": 32, "free_pages": 30, "held_pages": 1,
        "shared_pages": 0, "registered_pages": 0, "evictable_pages": 0,
        "pages_drawn": 1, "cow_copies": 0, "lru_reclaims": 0})
    path = trace_export.write_chrome_trace(str(tmp_path / "t.json"))
    cms = _load_tool("check_metrics_schema")
    assert cms.validate_file(path) == []  # strict trace lint
    ev = json.load(open(path))["traceEvents"]
    lanes = {e["name"]: e["tid"] for e in ev
             if e.get("cat") == "request" and "[" in e["name"]}
    # overlapping requests on DIFFERENT lanes; the later one reuses 0
    assert lanes["g g-r0 [completed]"] != lanes["g g-r1 [cancelled]"]
    assert lanes["g g-r2 [completed]"] == trace_export.REQUEST_TID
    phases = [e["name"] for e in ev if e.get("cat") == "request"
              and e["tid"] == lanes["g g-r0 [completed]"]
              and "[" not in e["name"]]
    assert phases[:3] == ["queued", "prefill", "decode"]
    assert any(e.get("ph") == "M"
               and e["args"].get("name") == "serving requests"
               for e in ev)
    assert any(e["name"] == "kv.g.free_pages" for e in ev)


def test_merged_request_traces_stay_rank_safe(tmp_path):
    mt = _load_tool("merge_traces")
    cms = _load_tool("check_metrics_schema")
    paths = []
    for rank in (0, 1):
        flight_recorder.reset()
        _ring_request(f"g{rank}", f"g{rank}-r0", "completed", 0.0,
                      0.1, 0.1, 0.3, rank=rank)
        snap = flight_recorder.snapshot()
        p = str(tmp_path / f"rank{rank}.json")
        trace_export.write_chrome_trace(p, snap=snap, rank=rank)
        paths.append(p)
    out = str(tmp_path / "merged.json")
    assert mt.main(["-o", out] + paths) == 0
    assert cms.validate_file(out) == []
    ev = json.load(open(out))["traceEvents"]
    req = [e for e in ev if e.get("cat") == "request" and "[" in e["name"]]
    assert sorted(e["pid"] for e in req) == [0, 1]  # one per rank


def test_debug_bundle_carries_serving_state(tmp_path):
    eng = InferenceEngine(_mlp(), batch_sizes=(1,), name="obs_bundle")
    try:
        eng(_x())
        d = flight_recorder.dump("manual", base_dir=str(tmp_path))
        assert d is not None
        tail = os.path.join(d, "requests_tail.jsonl")
        assert os.path.exists(tail)
        cms = _load_tool("check_metrics_schema")
        assert cms.validate_file(tail) == []
        state = json.load(open(os.path.join(d, "serve_state.json")))
        assert state["engines"]["obs_bundle"]["load_report"][
            "engine"] == "obs_bundle"
        assert state["slo"]["outcomes"]["completed"] >= 1
    finally:
        eng.shutdown()


# -- schema + lint fences -----------------------------------------------

def test_request_and_kvcache_schema_accept_and_reject():
    cms = _load_tool("check_metrics_schema")
    ok_req = {"ts": 1.0, "rank": 0, "kind": "request", "engine": "g",
              "request_id": "g-r0", "outcome": "completed", "rows": 1,
              "prompt_tokens": 4, "prefix_hit_tokens": 4,
              "generated_tokens": 2, "prefill_chunks": 1,
              "peak_pages_held": 2, "queue_s": 0.1, "prefill_s": 0.1,
              "decode_s": 0.1, "latency_s": 0.3, "max_new_tokens": 2,
              "deadline_s": 1.0, "deadline_met": True}
    assert cms.validate_line(json.dumps(ok_req)) == []
    ok_kv = {"ts": 1.0, "rank": 0, "kind": "kvcache", "engine": "g",
             "n_pages": 8, "free_pages": 5, "held_pages": 2,
             "shared_pages": 1, "registered_pages": 1, "pages_drawn": 3,
             "cow_copies": 1, "lru_reclaims": 0, "evictable_pages": 1,
             "refcounts": {"1": 1, "2": 1}}
    assert cms.validate_line(json.dumps(ok_kv)) == []

    def bad(base, **kw):
        rec = dict(base)
        rec.update(kw)
        return cms.validate_line(json.dumps(rec))

    assert bad(ok_req, outcome="vanished")
    assert bad(ok_req, prefix_hit_tokens=9)      # > prompt_tokens
    assert bad(ok_req, outcome="expired")        # generated > 0
    assert bad(ok_req, generated_tokens=5)       # > max_new_tokens
    assert bad(ok_req, queue_s=5.0)              # phases > latency
    assert bad(ok_req, engine="")
    assert bad(ok_req, deadline_met="yes")
    assert bad(ok_kv, free_pages=9)              # free + held > n_pages
    assert bad(ok_kv, shared_pages=3)            # > held_pages
    assert bad(ok_kv, evictable_pages=2)         # > registered_pages
    assert bad(ok_kv, refcounts={"1": -1})

    # strategy-dispatched snapshots (inference/cache_strategy.py)
    ok_rec = {"ts": 1.0, "rank": 0, "kind": "kvcache", "engine": "s",
              "cache_strategy": "recurrent", "n_slots": 7,
              "free_slots": 6, "held_slots": 1, "sequences": 1,
              "slots_drawn": 2, "state_bytes": 4096,
              "state_bytes_total": 28672}
    assert cms.validate_line(json.dumps(ok_rec)) == []
    assert bad(ok_rec, cache_strategy="magnetic")
    assert bad(ok_rec, state_bytes=0)            # the blob IS the cache
    assert bad(ok_rec, free_slots=7)             # free + held > n_slots
    assert bad(ok_rec, held_pages=3)             # page gauge on recurrent
    ok_hyb = dict(ok_kv, cache_strategy="hybrid", n_slots=7,
                  free_slots=6, held_slots=1, state_bytes=4096,
                  state_bytes_total=28672)
    assert cms.validate_line(json.dumps(ok_hyb)) == []
    assert bad(ok_hyb, state_bytes=0)
    hyb_missing = {k: v for k, v in ok_hyb.items() if k != "n_slots"}
    assert cms.validate_line(json.dumps(hyb_missing))
    # engine is REQUIRED on serve records now
    assert cms.validate_line(json.dumps(
        {"ts": 1, "rank": 0, "kind": "serve", "requests": 1,
         "batch_size": 1, "bucket_batch": 1, "queue_depth": 0,
         "pad_tokens": 0, "latency_s": 0.1}))


def test_hot_sync_fence_covers_observatory_call_sites():
    tool = _load_tool("check_no_hot_sync")
    regions = tool.HOT_REGIONS
    assert regions["paddle_tpu/profiler/serve_observatory.py"] == ["*"]
    assert "PagedKVCache.pool_stats" in \
        regions["paddle_tpu/ops/paged_attention.py"]
    serving_regions = regions["paddle_tpu/inference/serving.py"]
    for name in ("GenerationEngine._note_kv_step",
                 "GenerationEngine.load_report",
                 "InferenceEngine._flush_expired",
                 "InferenceEngine.load_report"):
        assert name in serving_regions
    assert tool.main([REPO]) == 0
    # a planted device read in the observatory is caught
    errs = tool.check_source(
        "def finish(self):\n    return float(x.block_until_ready())\n",
        ["*"], "serve_observatory.py")
    assert errs


# -- overhead stays within noise (PR 5 pattern) -------------------------

class _NoopTrace:
    def admitted(self):
        pass

    def first_token(self):
        pass

    def note_prefix(self, n):
        pass

    def note_chunk(self):
        pass

    def note_token(self, pages_held=0):
        pass

    def finish(self, outcome, error=None):
        pass


class _NoopObservatory:
    @staticmethod
    def start_request(*a, **k):
        return _NoopTrace()

    @staticmethod
    def record_pool_stats(*a, **k):
        return None

    @staticmethod
    def register_engine(engine):
        pass


@pytest.mark.heavy
def test_observatory_overhead_within_noise(monkeypatch):
    """Per-request serving wall time with the observatory active stays
    within noise of a no-op observatory — calibrated, best-of-3 (the
    2-CPU container convention, tests/test_observability.py)."""
    eng = InferenceEngine(_mlp(), batch_sizes=(1,), name="obs_ovh")
    x = _x()
    try:
        eng.warm(x)
        for _ in range(3):
            eng(x)  # execution warmup

        def median_req_s():
            times = []
            for _ in range(30):
                t0 = time.perf_counter()
                eng(x)
                times.append(time.perf_counter() - t0)
            return sorted(times)[len(times) // 2]

        for _ in range(3):
            real = median_req_s()
            monkeypatch.setattr(serving, "_obs", _NoopObservatory)
            try:
                base = median_req_s()
            finally:
                monkeypatch.setattr(serving, "_obs", sobs)
            if real <= base * 1.5 + 0.002:
                return
    finally:
        eng.shutdown()
    raise AssertionError(
        f"serving observatory overhead out of noise after 3 rounds: "
        f"base={base * 1e3:.2f}ms observed={real * 1e3:.2f}ms")
