"""ERNIE/BERT-base masked-LM train-step benchmark (the reference's second
headline metric is ERNIE step time — BASELINE.json §5).

One fully-jitted TrainStep (fwd + MLM loss + grads + AdamW with f32
master weights), bf16, batch 32 x seq 128 — a pretraining-shaped step.
Prints step ms + sequences/s + tokens/s.

Measured on a v5e-class chip: 44.5 ms/step, ~720 sequences/s,
~92k tokens/s (117M params).
"""
import json
import sys
import time

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.jit import TrainStep
from paddle_tpu.models.bert import BertForMaskedLM, ernie_base, BertConfig


def main():
    import jax
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        batch, seq = 32, 128
        cfg = ernie_base()
        cfg.hidden_dropout = 0.0
        cfg.attention_dropout = 0.0
    else:
        batch, seq = 2, 16
        cfg = BertConfig(vocab_size=512, hidden_size=64, num_layers=2,
                         num_heads=4, intermediate_size=128,
                         max_position_embeddings=64, hidden_dropout=0.0,
                         attention_dropout=0.0)
    paddle.seed(0)
    model = BertForMaskedLM(cfg)
    if on_tpu:
        model.bfloat16()
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    o = opt.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                  multi_precision=on_tpu)

    def loss_fn(logits, labels):
        V = logits.shape[-1]
        return nn.functional.cross_entropy(
            logits.reshape([-1, V]), labels.reshape([-1]),
            ignore_index=-100)

    step = TrainStep(model, loss_fn, o)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    # MLM labels: predict the 15% masked positions, ignore the rest
    lab = np.asarray(ids.value).copy()
    lab[rng.rand(batch, seq) > 0.15] = -100
    labels = paddle.to_tensor(lab.astype(np.int32))

    for _ in range(3):
        loss = step(ids, labels)
    float(loss.item())
    iters = 30 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, labels)
    float(loss.item())
    dt = (time.perf_counter() - t0) / iters
    print(json.dumps({
        "model": "ernie_base_mlm", "n_params": n_params,
        "batch": batch, "seq": seq,
        "step_ms": round(dt * 1e3, 1),
        "sequences_per_sec": round(batch / dt, 1),
        "tokens_per_sec": round(batch * seq / dt, 1),
        "loss": round(float(loss.item()), 4)}), flush=True)


if __name__ == "__main__":
    main()
