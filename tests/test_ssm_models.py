"""SSM / hybrid model family (ISSUE 19): the O(1)-cache second model
family behind the pluggable cache-strategy interface.

Proof points:
- the Pallas selective-scan kernel is bit-compatible with its pure-jnp
  reference across ragged row assignments (pads included);
- full-sequence forward == chunked prefill + token-by-token decode
  at the logits level (the recurrent state carry is REAL, not an echo);
- decode memory is FLAT in sequence length: a 5-token and a 50-token
  sequence hold the same state bytes and zero KV pages (pure SSM),
  while the hybrid's SSM half stays flat as its page half grows;
- a disaggregated router handoff moves ONE fixed-size state blob (no
  pages) and decodes token-for-token equal to a single engine;
- speculative decoding refuses non-paged strategies loudly.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.cache_strategy import (
    RecurrentStateCache, strategy_of)
from paddle_tpu.inference.serving import GenerationEngine
from paddle_tpu.models.ssm import SSMConfig, SSMForCausalLM


# one model per (hybrid, seed, geometry): compiled executables cache
# on the model instance and the disk compile cache is off under tests,
# so sharing across this file's tests avoids repaying ~4-6s of
# compiles each (no test here asserts cold-compile behavior)
_MODELS = {}


def _tiny(hybrid=False, seed=0, vocab=64, max_pos=64):
    key = (hybrid, seed, vocab, max_pos)
    if key in _MODELS:
        return _MODELS[key]
    paddle.seed(seed)
    cfg = SSMConfig(vocab_size=vocab, hidden_size=32, num_layers=2,
                    d_state=8, d_conv=4, expand=2,
                    max_position_embeddings=max_pos,
                    attn_every=2 if hybrid else 0,
                    num_heads=4 if hybrid else 0)
    m = SSMForCausalLM(cfg)
    m.eval()
    _MODELS[key] = m
    return m


# -- kernel vs reference -------------------------------------------------

def test_ssm_scan_kernel_matches_reference():
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.ssm_scan import (
        ssm_scan, selective_scan_reference)
    rng = np.random.RandomState(0)
    T, D, N, R = 16, 8, 4, 3
    x = jnp.asarray(rng.randn(T, D).astype(np.float32))
    dt = jnp.asarray(np.abs(rng.randn(T, D)).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.randn(T, N).astype(np.float32))
    c = jnp.asarray(rng.randn(T, N).astype(np.float32))
    a = jnp.asarray(-np.abs(rng.randn(D, N)).astype(np.float32))
    h0 = jnp.asarray(rng.randn(R, D, N).astype(np.float32))
    # ragged: rows 1 and 2 interleaved, row 0 = pad slot with dt=0
    seq = jnp.asarray(
        np.array([1, 1, 2, 1, 2, 2, 1, 2] * 2, np.int32))
    dt = dt.at[12:].set(0.0)  # tail tokens neutralized like pads
    y_k, h_k = ssm_scan(x, dt, b, c, a, h0, seq)
    y_r, h_r = selective_scan_reference(x, dt, b, c, a, h0, seq)
    np.testing.assert_allclose(np.asarray(y_k), np.asarray(y_r),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_k), np.asarray(h_r),
                               rtol=1e-5, atol=1e-5)
    # zero-dt tokens left every row's state untouched after token 12
    y_k2, h_k2 = ssm_scan(x[:12], dt[:12], b[:12], c[:12], a, h0,
                          seq[:12])
    np.testing.assert_allclose(np.asarray(h_k), np.asarray(h_k2),
                               rtol=1e-5, atol=1e-5)


# -- full forward == prefill + decode ------------------------------------

@pytest.mark.parametrize("hybrid", [False, True],
                         ids=["recurrent", "hybrid"])
def test_forward_equals_prefill_plus_decode_logits(hybrid):
    m = _tiny(hybrid=hybrid)
    rng = np.random.RandomState(1)
    toks = rng.randint(0, 64, (1, 9)).astype(np.int64)

    full = m(paddle.to_tensor(toks)).numpy()  # [1, 9, V]

    cache = m.make_paged_cache(n_pages=16, page_size=4)
    assert strategy_of(cache) == ("hybrid" if hybrid else "recurrent")
    cache.add_sequence("s")
    # chunked prefill (5 + 3) then one decode token
    l1 = m.paged_decode_step(
        cache, ["s"], paddle.to_tensor(toks[:, :5])).numpy()
    l2 = m.paged_decode_step(
        cache, ["s"], paddle.to_tensor(toks[:, 5:8])).numpy()
    l3 = m.paged_decode_step(
        cache, ["s"], paddle.to_tensor(toks[:, 8:])).numpy()
    np.testing.assert_allclose(l1[0], full[0, 4], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(l2[0], full[0, 7], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(l3[0], full[0, 8], rtol=1e-4, atol=1e-5)
    m.clear_decode_cache()


# -- flat memory vs sequence length --------------------------------------

def test_recurrent_state_flat_in_sequence_length():
    m = _tiny()
    cache = m.make_paged_cache(n_pages=16, page_size=4)
    rng = np.random.RandomState(2)
    chains = {}
    for name, n in (("short", 5), ("long", 50)):
        cache.add_sequence(name)
        m.paged_decode_step(cache, [name], paddle.to_tensor(
            rng.randint(0, 64, (1, n)).astype(np.int64)))
        chains[name] = cache.export_chain(name)
    short, long_ = chains["short"], chains["long"]
    # O(1): the exported blob is the SAME size at 5 and at 50 tokens,
    # and no KV pages exist at any length
    assert short.state_bytes == long_.state_bytes > 0
    assert tuple(short.pages) == tuple(long_.pages) == ()
    assert long_.length == 50 and short.length == 5
    stats = cache.pool_stats()
    assert stats["cache_strategy"] == "recurrent"
    assert stats["state_bytes"] == short.state_bytes
    cache.release_chain(short)
    cache.release_chain(long_)
    m.clear_decode_cache()


def test_hybrid_pages_grow_but_state_half_stays_flat():
    m = _tiny(hybrid=True)
    cache = m.make_paged_cache(n_pages=32, page_size=4)
    rng = np.random.RandomState(3)
    chains = {}
    for name, n in (("short", 5), ("long", 50)):
        cache.add_sequence(name)
        m.paged_decode_step(cache, [name], paddle.to_tensor(
            rng.randint(0, 64, (1, n)).astype(np.int64)))
        chains[name] = cache.export_chain(name)
    short, long_ = chains["short"], chains["long"]
    assert len(long_.pages) > len(short.pages) >= 1  # KV half: O(T)
    assert short.state_bytes == long_.state_bytes > 0  # SSM half: O(1)
    cache.release_chain(short)
    cache.release_chain(long_)
    m.clear_decode_cache()


# -- engine: zero new executables at steady state ------------------------

@pytest.mark.heavy
def test_warm_engine_adds_zero_executables():
    from paddle_tpu.profiler import compile_observatory as cobs
    m = _tiny()
    eng = GenerationEngine(m, n_pages=8, page_size=4, max_batch=2,
                           max_new_tokens=4, name="ssm_steady")
    rng = np.random.RandomState(4)
    try:
        eng.submit(rng.randint(0, 64, (5,))).result(timeout=300)
        warm = set(cobs.ledger_signatures())
        for n in (3, 6, 4):  # varied lengths, same padded signature
            eng.submit(rng.randint(0, 64, (n,))).result(timeout=300)
        assert set(cobs.ledger_signatures()) == warm
        rep = eng.load_report()
        assert rep["cache_strategy"] == "recurrent"
        assert rep["state_bytes"] > 0
    finally:
        eng.shutdown()


# -- disaggregation: the handoff moves one blob --------------------------

@pytest.mark.heavy
def test_router_handoff_moves_one_state_blob_token_equal():
    from paddle_tpu.inference.frontdoor import ServingRouter
    m = _tiny()
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, 64, (n,)) for n in (7, 4)]

    single = GenerationEngine(m, n_pages=8, page_size=4, max_batch=2,
                              max_new_tokens=6, name="ssm_single")
    try:
        refs = [h.result(300).tolist() for h in
                [single.submit(p, max_new_tokens=4) for p in prompts]]
    finally:
        single.shutdown()

    cache = m.make_paged_cache(8, 4)
    pre = GenerationEngine(m, cache=cache, max_batch=2,
                           max_new_tokens=6, name="ssm_pre")
    dec = GenerationEngine(m, cache=cache, max_batch=2,
                           max_new_tokens=6, name="ssm_dec")
    router = ServingRouter([pre, dec], roles=("prefill", "decode"),
                           name="ssm_router")
    seen = []
    orig_adopt = dec.adopt

    def spy(handle, chain, **kw):
        # the handoff payload is ONE state blob: no pages, real bytes
        seen.append((getattr(chain, "strategy", "paged"),
                     tuple(chain.pages), int(chain.state_bytes),
                     int(chain.length)))
        return orig_adopt(handle=handle, chain=chain, **kw)

    dec.adopt = spy
    try:
        outs = [h.result(300).tolist() for h in
                [router.submit(p, max_new_tokens=4,
                               deadline_ms=120_000) for p in prompts]]
    finally:
        router.shutdown()
    assert outs == refs  # token-for-token across the handoff
    assert len(seen) == len(prompts)
    for strategy, pages, state_bytes, length in seen:
        assert strategy == "recurrent"
        assert pages == ()
        assert state_bytes == cache.state_bytes_per_slot() > 0
    assert sorted(length for _, _, _, length in seen) == \
        sorted(p.size for p in prompts)


# -- guardrails ----------------------------------------------------------

def test_speculative_requires_paged_strategy():
    from paddle_tpu.inference.speculative import SpeculativeConfig
    m = _tiny()
    with pytest.raises(ValueError, match="paged cache strategy"):
        GenerationEngine(
            m, n_pages=8, page_size=4,
            speculative=SpeculativeConfig(draft_model=_tiny(seed=7)))


def test_recurrent_cache_rejects_rollback():
    cache = RecurrentStateCache(n_layers=2, n_slots=4, d_inner=8,
                                d_state=4, d_conv=4)
    cache.add_sequence("s")
    cache.advance("s", 3)
    with pytest.raises(RuntimeError, match="not rewindable"):
        cache.rollback("s", 2)
