"""MultiSlot datasets. Parity:
python/paddle/distributed/fleet/dataset/dataset.py (InMemoryDataset,
QueueDataset).

The reference backs these with C++ data feeds for parameter-server
training (paddle/fluid/framework/data_feed.cc MultiSlotInMemoryDataFeed).
The TPU build keeps the user-facing API (init / set_filelist /
load_into_memory / local_shuffle / batch iteration); InMemoryDataset
parses and shuffles in the native runtime (runtime_core.cpp ms_* engine:
multithreaded from_chars parsing into per-slot CSR arrays) with a
pure-Python MultiSlot reader as fallback. Batches are numpy arrays ready
for ``jax.device_put`` — PS-specific pieces (global_shuffle over
trainers, pipe commands as subprocess filters) degrade gracefully to
their local equivalents.
"""
import ctypes
import random
import subprocess

import numpy as np

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset"]


def _parse_multislot_line(line, slot_names):
    """'<n> v1..vn <m> u1..um' -> {slot: np.array}, slots in order."""
    toks = line.split()
    out = {}
    i = 0
    for name in slot_names:
        n = int(toks[i])
        vals = toks[i + 1:i + 1 + n]
        i += 1 + n
        try:
            arr = np.asarray([int(v) for v in vals], dtype=np.int64)
        except ValueError:
            arr = np.asarray([float(v) for v in vals], dtype=np.float32)
        out[name] = arr
    return out


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._filelist = []
        self._use_var = []
        self._pipe_command = None
        self._input_type = 0

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name="", fs_ugi="",
             **kwargs):
        self._batch_size = batch_size
        self._thread_num = thread_num
        self._use_var = use_var or []
        self._pipe_command = pipe_command
        self._input_type = input_type
        return self

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def _slot_names(self):
        names = []
        for v in self._use_var:
            names.append(getattr(v, "name", v if isinstance(v, str)
                                 else str(v)))
        return names

    def _read_lines(self, fname):
        if self._pipe_command:
            proc = subprocess.run(
                f"cat {fname} | {self._pipe_command}", shell=True,
                capture_output=True, text=True, check=True)
            return proc.stdout.splitlines()
        with open(fname) as f:
            return [ln.rstrip("\n") for ln in f if ln.strip()]

    def _iter_samples(self):
        names = self._slot_names()
        for fname in self._filelist:
            for line in self._read_lines(fname):
                yield _parse_multislot_line(line, names)

    def _batches_from(self, sample_iter):
        """Group samples into batches: each batch is {slot: [arr, ...]};
        fixed-length slots stack into a dense [B, L] array."""
        batch = []
        for s in sample_iter:
            batch.append(s)
            if len(batch) == self._batch_size:
                yield self._collate(batch)
                batch = []
        if batch:
            yield self._collate(batch)

    def _collate(self, samples):
        names = self._slot_names()
        out = {}
        for name in names:
            arrs = [s[name] for s in samples]
            lens = {a.shape[0] for a in arrs}
            out[name] = (np.stack(arrs) if len(lens) == 1
                         else arrs)
        return out


class QueueDataset(DatasetBase):
    """Streaming dataset: batches read lazily from the filelist
    (ref: fleet/dataset/dataset.py:1240)."""

    def __iter__(self):
        return self._batches_from(self._iter_samples())


class _NativeMultiSlot:
    """ctypes facade over the ms_* MultiSlot engine in runtime_core.cpp."""

    def __init__(self, lib, slot_names, slot_types):
        self._lib = lib
        self._names = slot_names
        self._types = slot_types  # 0=float32, 1=int64 per slot
        arr = (ctypes.c_int * len(slot_types))(*slot_types)
        self._h = lib.ms_create(len(slot_types), arr)

    def load_file(self, path, n_threads):
        return self._lib.ms_load_file(self._h, path.encode(),
                                      int(n_threads))

    def shuffle(self, seed):
        self._lib.ms_shuffle(self._h, seed & (2**64 - 1))

    def __len__(self):
        return int(self._lib.ms_num_records(self._h))

    def batch(self, start, count):
        """{slot: np.ndarray [count, L]} (or list of ragged arrays)."""
        out = {}
        for s, name in enumerate(self._names):
            lens = np.empty(count, np.uint64)
            total = self._lib.ms_batch_lens(
                self._h, start, count, s,
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
            if self._types[s] == 1:
                vals = np.empty(int(total), np.int64)
                self._lib.ms_fill_batch_i64(
                    self._h, start, count, s,
                    vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            else:
                vals = np.empty(int(total), np.float32)
                self._lib.ms_fill_batch_f32(
                    self._h, start, count, s,
                    vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
            if len(set(lens.tolist())) == 1 and count:
                out[name] = vals.reshape(count, -1)
            else:
                out[name] = np.split(vals, np.cumsum(lens)[:-1].astype(
                    np.int64))
        return out

    def release(self):
        self._lib.ms_release(self._h)

    def __del__(self):
        try:
            self._lib.ms_destroy(self._h)
        except Exception:
            pass


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (ref: fleet/dataset/dataset.py:341).

    Parsing/shuffling run in the native runtime when available; the
    pipe_command path (arbitrary subprocess filters) stays in Python.
    """

    def __init__(self):
        super().__init__()
        self._samples = []
        self._native = None

    def _detect_types(self):
        """Slot dtypes: declared dtype on the use_var Variables when
        available (the reference declares slot types up front in the
        data-feed proto), else sniffed from the first 100 data lines —
        a slot is int64 only if every sampled value parses as int."""
        names = self._slot_names()
        declared = []
        for v in self._use_var:
            dt = str(getattr(v, "dtype", "") or "")
            if "int" in dt:
                declared.append(1)
            elif "float" in dt or "double" in dt:
                declared.append(0)
            else:
                declared.append(None)
        if all(d is not None for d in declared) and declared:
            return declared
        import warnings
        warnings.warn(
            "MultiSlot slot dtypes not declared on use_vars; sniffing "
            "from the first 100 data lines — an all-integral float slot "
            "would be mistyped int64. Declare dtypes on the use_var "
            "Variables to silence this.", UserWarning)
        sampled = [1] * len(names)
        seen = 0
        for fname in self._filelist:
            with open(fname) as f:
                for line in f:
                    if not line.strip():
                        continue
                    parsed = _parse_multislot_line(line, names)
                    for i, n in enumerate(names):
                        if parsed[n].dtype != np.int64:
                            sampled[i] = 0
                    seen += 1
                    if seen >= 100:
                        break
            if seen >= 100:
                break
        return [d if d is not None else s
                for d, s in zip(declared, sampled)] if declared else sampled

    def load_into_memory(self):
        from ..runtime import get_lib
        lib = get_lib()
        if lib is None or self._pipe_command or not self._use_var:
            self._native = None
            self._samples = list(self._iter_samples())
            return
        self._native = _NativeMultiSlot(lib, self._slot_names(),
                                        self._detect_types())
        for fname in self._filelist:
            if self._native.load_file(fname, self._thread_num) < 0:
                # malformed for the fast parser — python fallback
                self._native = None
                self._samples = list(self._iter_samples())
                return

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self):
        if self._native is not None:
            self._native.shuffle(random.getrandbits(63))
        else:
            random.shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single-process world: global == local
        self.local_shuffle()

    def get_memory_data_size(self, fleet=None):
        return len(self._native) if self._native is not None \
            else len(self._samples)

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size(fleet)

    def release_memory(self):
        if self._native is not None:
            self._native.release()
        self._samples = []

    def __iter__(self):
        if self._native is None:
            return self._batches_from(iter(self._samples))
        return self._native_batches()

    def _native_batches(self):
        n = len(self._native)
        bs = self._batch_size
        for start in range(0, n, bs):
            yield self._native.batch(start, min(bs, n - start))
