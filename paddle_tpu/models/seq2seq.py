"""Transformer encoder-decoder seq2seq (machine-translation family).

The reference ships this family as its flagship nn.Transformer use
(fluid tests + book examples: "Transformer for MT"); here it is a
first-class model on top of paddle_tpu.nn.Transformer with shared
target embedding/generator weights and greedy decode.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .. import nn
from ..nn import functional as F

__all__ = ["Seq2SeqConfig", "Seq2SeqTransformer"]


class Seq2SeqConfig:
    def __init__(self, src_vocab_size=32000, tgt_vocab_size=32000,
                 d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 max_position_embeddings=512, pad_id=0, bos_id=1,
                 eos_id=2):
        self.src_vocab_size = src_vocab_size
        self.tgt_vocab_size = tgt_vocab_size
        self.d_model = d_model
        self.nhead = nhead
        self.num_encoder_layers = num_encoder_layers
        self.num_decoder_layers = num_decoder_layers
        self.dim_feedforward = dim_feedforward
        self.dropout = dropout
        self.max_position_embeddings = max_position_embeddings
        self.pad_id = pad_id
        self.bos_id = bos_id
        self.eos_id = eos_id


class Seq2SeqTransformer(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.src_embed = nn.Embedding(cfg.src_vocab_size, cfg.d_model)
        self.tgt_embed = nn.Embedding(cfg.tgt_vocab_size, cfg.d_model)
        self.pos_embed = nn.Embedding(cfg.max_position_embeddings,
                                      cfg.d_model)
        self.transformer = nn.Transformer(
            d_model=cfg.d_model, nhead=cfg.nhead,
            num_encoder_layers=cfg.num_encoder_layers,
            num_decoder_layers=cfg.num_decoder_layers,
            dim_feedforward=cfg.dim_feedforward, dropout=cfg.dropout)
        self.drop = nn.Dropout(cfg.dropout)
        self.scale = float(np.sqrt(cfg.d_model))

    def _embed(self, table, ids):
        T = ids.shape[1]
        from ..tensor.creation import arange
        pos = arange(0, T, dtype="int64").unsqueeze(0)
        return self.drop(table(ids) * self.scale + self.pos_embed(pos))

    def _pad_mask(self, ids):
        # additive mask broadcastable to [B, nhead, Tq, Tk]
        neg = (ids.value == self.cfg.pad_id)
        m = jnp.where(neg[:, None, None, :], jnp.float32(-1e9),
                      jnp.float32(0.0))
        return Tensor(m)

    def forward(self, src_ids, tgt_ids):
        """Teacher-forcing logits [B, T_tgt, tgt_vocab]; the generator
        shares the target embedding matrix (tied weights)."""
        src = self._embed(self.src_embed, src_ids)
        tgt = self._embed(self.tgt_embed, tgt_ids)
        tgt_mask = nn.Transformer.generate_square_subsequent_mask(
            tgt_ids.shape[1])
        out = self.transformer(
            src, tgt, src_mask=self._pad_mask(src_ids),
            tgt_mask=tgt_mask, memory_mask=self._pad_mask(src_ids))
        from ..tensor.linalg import matmul
        return matmul(out, self.tgt_embed.weight, transpose_y=True)

    def loss(self, src_ids, tgt_ids, label_ids):
        logits = self(src_ids, tgt_ids)
        V = logits.shape[-1]
        return F.cross_entropy(logits.reshape([-1, V]),
                               label_ids.reshape([-1]),
                               ignore_index=self.cfg.pad_id)

    def greedy_decode(self, src_ids, max_len=32):
        """Greedy decoding; one forward per emitted token (the decoder
        stack is small relative to the encoder, and shapes stay in a
        per-length jit cache)."""
        B = src_ids.shape[0]
        out = np.full((B, 1), self.cfg.bos_id, np.int64)
        finished = np.zeros((B,), bool)
        for _ in range(max_len):
            logits = self(src_ids, Tensor(jnp.asarray(out)))
            nxt = np.asarray(logits.value[:, -1, :].argmax(-1))
            nxt = np.where(finished, self.cfg.pad_id, nxt)
            finished |= nxt == self.cfg.eos_id
            out = np.concatenate([out, nxt[:, None]], axis=1)
            if finished.all():
                break
        return Tensor(jnp.asarray(out))
