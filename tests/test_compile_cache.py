"""Framework-level persistent compile cache (framework/compile_cache.py).

The acceptance proof for the warm-start contract: the same jitted train
step in two SEPARATE processes, sharing only the on-disk cache dir — the
second process must skip the cold compile (compile_s well under the 15 s
bound; on TPU the same mechanism turns a 60 s+ GPT compile into a
seconds-long cache load).
"""
import json
import os
import subprocess
import sys

import jax
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import compile_cache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = """
import json
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.jit import TrainStep

paddle.seed(0)
m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
step = TrainStep(
    m, lambda out, y: nn.functional.cross_entropy(out, y), o)
x = paddle.to_tensor(
    np.random.RandomState(0).randn(4, 16).astype(np.float32))
y = paddle.to_tensor(np.arange(4, dtype=np.int64) % 8)
float(step(x, y).item())
print(json.dumps({
    "compile_s": step.compile_s,
    "retraces": step.retraces,
    "cache_dir": __import__(
        "paddle_tpu.framework.compile_cache",
        fromlist=["cache_dir"]).cache_dir(),
}))
"""


def _run_child(cache_dir):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "PADDLE_TPU_COMPILE_CACHE": str(cache_dir),
        "PYTHONUNBUFFERED": "1",
    })
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    return json.loads(line)


def test_second_process_skips_cold_compile(tmp_path):
    cache = tmp_path / "xla_cache"
    first = _run_child(cache)
    assert first["cache_dir"] == str(cache)
    assert first["retraces"] == 1
    entries = [n for n in os.listdir(cache) if not n.startswith(".")]
    assert entries, "first process wrote no cache entries"
    second = _run_child(cache)
    # the acceptance bound: a warm process must never pay a cold compile
    assert second["compile_s"] < 15, second
    assert os.listdir(cache), "cache dir vanished"


def test_enable_disable_and_env_knobs(tmp_path):
    prev = compile_cache.cache_dir()
    try:
        d = compile_cache.enable_compile_cache(str(tmp_path / "cc"))
        assert d == str(tmp_path / "cc") and os.path.isdir(d)
        assert compile_cache.cache_dir() == d
        assert jax.config.jax_compilation_cache_dir == d
        # "0" and friends disable
        assert compile_cache.enable_compile_cache("0") is None
        assert compile_cache.cache_dir() is None
        compile_cache.disable_compile_cache()
        assert jax.config.jax_compilation_cache_dir is None
    finally:
        if prev:
            compile_cache.enable_compile_cache(prev)
        else:
            compile_cache.disable_compile_cache()


def test_respects_preconfigured_jax_dir(tmp_path):
    """bench.py configures jax's cache before importing the framework;
    framework init must keep that dir, not clobber it with the default
    (no env var, no explicit path)."""
    prev = compile_cache.cache_dir()
    prev_env = os.environ.pop("PADDLE_TPU_COMPILE_CACHE", None)
    try:
        pre = str(tmp_path / "pre")
        os.makedirs(pre)
        jax.config.update("jax_compilation_cache_dir", pre)
        assert compile_cache.enable_compile_cache() == pre
    finally:
        if prev_env is not None:
            os.environ["PADDLE_TPU_COMPILE_CACHE"] = prev_env
        if prev:
            compile_cache.enable_compile_cache(prev)
        else:
            compile_cache.disable_compile_cache()
