"""Execute the REFERENCE's own docstring examples against paddle_tpu.

VERDICT r3 #10: the cheapest systematic detector for parity breaks —
reference users' first contact with an API is its docstring example, so
each example that runs here is a workflow guaranteed not to crash.

Harvest: `.. code-block:: python` sections from the reference's amp /
PyLayer / to_static / DataParallel sources, executed with `paddle`
aliased to paddle_tpu (plus the module tree, so `from paddle.autograd
import PyLayer` resolves). Blocks that need infrastructure this
environment forbids (multi-process spawn, filesystem model zoos, GPU
device queries) are skipped by marker, not silently — the skip list IS
the parity gap ledger.
"""
import os
import re
import sys
import textwrap

import pytest

import paddle_tpu

REF = "/root/reference/python/paddle"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference checkout not present")


def _normalize(block):
    """Keep only the code body: lines at (or deeper than) the first code
    line's indent; reST prose resuming at shallower indent ends the
    block. Then strip that common indent. Leading reST directive options
    (':name: code-example1') are dropped first — they are part of the
    code-block directive, not the code."""
    lines = block.splitlines()
    while lines and (not lines[0].strip()
                     or lines[0].strip().startswith(":")):
        lines.pop(0)
    first = next((l for l in lines if l.strip()), "")
    pad = len(first) - len(first.lstrip())
    out = []
    for l in lines:
        if not l.strip():
            out.append("")
            continue
        if len(l) - len(l.lstrip()) < pad:
            break  # prose resumed
        out.append(l[pad:])
    return "\n".join(out)


def _harvest(relpath):
    src = open(os.path.join(REF, relpath)).read()
    blocks = re.findall(
        r"\.\. code-block:: python\n(.*?)(?=\n\s*(?:\.\. code-block|\"\"\"))",
        src, re.S)
    return [_normalize(b) for b in blocks]


@pytest.fixture()
def paddle_alias(monkeypatch):
    """Alias the full paddle_tpu module tree as `paddle` in sys.modules."""
    import paddle_tpu.autograd  # ensure key subtrees are imported
    import paddle_tpu.amp
    import paddle_tpu.jit
    import paddle_tpu.nn
    import paddle_tpu.distributed
    import paddle_tpu.optimizer
    import paddle_tpu.static
    for name, mod in list(sys.modules.items()):
        if name == "paddle_tpu" or name.startswith("paddle_tpu."):
            monkeypatch.setitem(sys.modules,
                                "paddle" + name[len("paddle_tpu"):], mod)
    return paddle_tpu


# which harvested blocks run. index -> skip reason (None = must pass)
_PYLAYER_BLOCKS = {
    0: None,   # cus_tanh forward/backward definition
    1: None,   # save_for_backward + saved_tensor
    2: None,   # saved_tensor retrieval
    3: None,   # non-tensor args (func1/func2)
    4: None,   # PyLayer.apply end-to-end
    5: None,   # forward with kwargs
    6: None,   # full apply + backward example
}

_AMP_AUTOCAST_BLOCKS = {
    0: None,   # auto_cast levels / custom lists (dtype prints differ: bf16)
}


_tmpdir = None


def _run(block, extra=None):
    """Exec a block from a REAL file so inspect.getsource works — the
    dy2static converter needs source for functions the example defines."""
    global _tmpdir
    import tempfile
    if _tmpdir is None:
        _tmpdir = tempfile.mkdtemp(prefix="refdoc")
    path = os.path.join(_tmpdir, f"block_{abs(hash(block)) % 10**10}.py")
    with open(path, "w") as f:
        f.write(block)
    ns = {"__name__": "__main__", "__file__": path}
    ns.update(extra or {})
    # run from a FRESH per-block dir: reference examples write relative
    # paths (e.g. hapi's model.save('checkpoint/test')) and must not
    # dirty the repo working tree or leak artifacts between blocks
    cwd = os.getcwd()
    os.chdir(tempfile.mkdtemp(dir=_tmpdir))
    try:
        exec(compile(block, path, "exec"), ns)
    finally:
        os.chdir(cwd)
    return ns


@pytest.mark.parametrize("idx", sorted(_PYLAYER_BLOCKS))
def test_pylayer_doc_examples(paddle_alias, idx):
    blocks = _harvest("autograd/py_layer.py")
    reason = _PYLAYER_BLOCKS[idx]
    if reason:
        pytest.skip(reason)
    _run(blocks[idx])


@pytest.mark.parametrize("idx", sorted(_AMP_AUTOCAST_BLOCKS))
def test_amp_auto_cast_doc_example(paddle_alias, idx):
    blocks = _harvest("amp/auto_cast.py")
    reason = _AMP_AUTOCAST_BLOCKS[idx]
    if reason:
        pytest.skip(reason)
    _run(blocks[idx])


def test_grad_scaler_doc_examples(paddle_alias):
    """grad_scaler.py has ~20 blocks, mostly variations of one training
    idiom; run every block that is self-contained (defines `model` and
    `data` itself) and uses only the eager API."""
    blocks = _harvest("amp/grad_scaler.py")
    ran = 0
    for b in blocks:
        if not ("paddle.nn.Conv2D" in b or "paddle.nn.Linear" in b):
            continue
        if "spawn" in b or "fleet" in b:
            continue
        _run(b)
        ran += 1
    assert ran >= 5, f"only {ran} grad_scaler examples were runnable"


def test_to_static_doc_examples(paddle_alias):
    """fluid/dygraph/jit.py examples: to_static decoration, save, load.
    Blocks touching TranslatedLayer training or ProgramTranslator
    internals are filtered to the save/load/core subset. (_run execs
    each block in its own fresh tmpdir, so save/load artifacts are
    isolated per block.)"""
    blocks = _harvest("fluid/dygraph/jit.py")
    ran = 0
    for b in blocks:
        # run the declarative-decorator examples; skip blocks needing the
        # reference's example zoo files or fluid legacy Program plumbing
        if "@paddle.jit.to_static" not in b and "@to_static" not in b:
            continue
        if "load_inference_model" in b or "fluid.dygraph.guard" in b:
            continue
        _run(b)
        ran += 1
    assert ran >= 1, "no to_static examples were runnable"


def test_data_parallel_doc_examples(paddle_alias):
    """parallel.py DataParallel examples. The reference examples call
    dist.spawn / multi-process launch; here init_parallel_env maps onto
    the single-process SPMD mesh, so the per-example bodies run in this
    process (the multi-process path is covered by
    tests/test_launch_multiproc.py)."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    # the canonical DataParallel docstring workflow (parallel.py:436),
    # inlined because the raw block calls dist.spawn
    class LinearNet(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self._linear1 = paddle.nn.Linear(10, 10)
            self._linear2 = paddle.nn.Linear(10, 1)

        def forward(self, x):
            return self._linear2(self._linear1(x))

    dist.init_parallel_env()
    layer = LinearNet()
    dp_layer = paddle.DataParallel(layer)
    loss_fn = paddle.nn.loss.MSELoss()
    adam = paddle_tpu.optimizer.Adam(
        learning_rate=0.001, parameters=dp_layer.parameters())
    inputs = paddle.randn([10, 10], "float32")
    outputs = dp_layer(inputs)
    labels = paddle.randn([10, 1], "float32")
    loss = loss_fn(outputs, labels)
    loss.backward()
    adam.step()
    adam.clear_grad()


def _run_blocks(relpath, paddle_alias, filter_fn=None, min_ran=1,
                skip_if=()):
    blocks = _harvest(relpath)
    ran, skipped = 0, []
    for i, b in enumerate(blocks):
        if filter_fn and not filter_fn(b):
            continue
        if any(s in b for s in skip_if):
            skipped.append(i)
            continue
        _run(b)
        ran += 1
    assert ran >= min_ran, (relpath, ran, skipped)
    return ran


def test_lr_scheduler_doc_examples(paddle_alias):
    """optimizer/lr.py: every self-contained scheduler example (the
    dynamic-graph halves; static-graph halves use fluid Program plumbing
    covered elsewhere)."""
    _run_blocks(
        "optimizer/lr.py", paddle_alias,
        # the static-graph halves (Program/program_guard/Executor) run
        # against our static API as-is — no filtering needed
        filter_fn=lambda b: "import paddle" in b,
        min_ran=10)


def test_adamw_doc_example(paddle_alias):
    _run_blocks("optimizer/adamw.py", paddle_alias,
                filter_fn=lambda b: "paddle.optimizer.AdamW" in b)


def test_metric_doc_examples(paddle_alias):
    """metric/metrics.py: Accuracy/Precision/Recall/Auc examples (the
    fleet/distributed ones need a cluster)."""
    _run_blocks("metric/metrics.py", paddle_alias,
                filter_fn=lambda b: "paddle.metric." in b,
                skip_if=("fleet", "spawn", "MNIST"),  # MNIST: zero egress
                min_ran=3)


def test_hapi_model_doc_examples(paddle_alias):
    """hapi/model.py: Model.fit / evaluate / predict workflows on
    synthetic data (dataset-downloading examples are zero-egress-skipped)."""
    _run_blocks("hapi/model.py", paddle_alias,
                filter_fn=lambda b: "paddle.Model" in b
                and "MNIST" not in b and "hub" not in b,
                skip_if=("download", "flowers"), min_ran=1)


def test_nn_common_layer_doc_examples(paddle_alias):
    """nn/layer/common.py: all 18 layer examples (Linear/Upsample/Pad/
    Dropout/Embedding/Unfold/Fold...) run verbatim."""
    _run_blocks("nn/layer/common.py", paddle_alias, min_ran=15)


def test_dataloader_from_generator_doc_example(paddle_alias):
    """fluid/reader.py block 0 (dygraph from_generator workflow);
    remaining blocks use the legacy paddle.fluid namespace (out of
    scope, SURVEY §3) or the static pipe reader."""
    _run_blocks("fluid/reader.py", paddle_alias,
                filter_fn=lambda b: "fluid" not in b
                and "from_generator" not in b and "from_dataset" not in b,
                min_ran=1)


def test_from_generator_api():
    """DataLoader.from_generator: all three source setters (legacy fluid
    reader.py API surface)."""
    import numpy as np
    import paddle_tpu as paddle
    loader = paddle.io.DataLoader.from_generator(capacity=10)

    def reader():
        for i in range(10):
            yield np.full((4,), i, np.float32), np.array([i], np.int64)

    loader.set_sample_generator(reader, batch_size=4)
    batches = list(loader())
    assert len(batches) == 2  # drop_last on the tail of 10
    assert batches[0][0].shape == [4, 4]

    loader2 = paddle.io.DataLoader.from_generator()

    def breader():
        for i in range(3):
            yield (np.ones((2, 4), np.float32) * i,
                   np.zeros((2, 1), np.int64))

    loader2.set_batch_generator(breader)
    assert len(list(loader2)) == 3


# ---------------------------------------------------------------------------
# Module matrix: run EVERY docstring example of a reference module, with
# the known-bad blocks skipped by index. A skip entry is (index, reason);
# reasons fall into two classes only — "ref-bug:" the reference's own
# example cannot run anywhere (undefined names, wrong shapes, mixed
# indentation), or "env:" needs something this environment forbids
# (network downloads, cv2). Everything else must PASS: any new failure
# here is a parity regression.
# ---------------------------------------------------------------------------

# quick tier: modules where real parity bugs were found and fixed
# (round 4) — these lock the fixes.
_MATRIX_QUICK = [
    ("tensor/creation.py", ()),
    ("tensor/manipulation.py", ()),
    ("tensor/random.py", ()),
    ("nn/functional/pooling.py", (
        (7, "ref-bug: calls max_pool2d on 5-D input, then indexes "
            ".shape with a tuple"),
        (8, "ref-bug: adaptive_average_pool1d is a typo for "
            "adaptive_avg_pool1d"),
    )),
    ("nn/layer/pooling.py", ()),
    ("distribution/beta.py", ()),
    ("distribution/categorical.py", ()),
    ("distribution/uniform.py", ()),
    ("optimizer/adamax.py", ()),
    ("optimizer/optimizer.py", ()),
    ("vision/transforms/transforms.py", (
        (0, "env: Flowers dataset download (zero egress)"),
    )),
    ("framework/io.py", ()),
    ("tensor/to_string.py", ()),
    ("static/input.py", ()),
    ("nn/functional/common.py", (
        (0, "ref-bug: mixed indentation inside the code block"),
    )),
]

# heavy tier: broad pass-only sweeps over the rest of the API surface.
_MATRIX_HEAVY = [
    ("tensor/math.py", (
        (42, "ref-bug: uses undefined names start/end"),
    )),
    ("tensor/linalg.py", ()),
    ("tensor/search.py", ()),
    ("tensor/logic.py", ()),
    ("tensor/stat.py", ()),
    ("tensor/einsum.py", ()),
    ("tensor/attribute.py", ()),
    ("nn/layer/activation.py", ()),
    ("nn/layer/conv.py", ()),
    ("nn/layer/loss.py", (
        (3, "ref-bug: HSigmoidLoss example feeds a [4] label with a "
            "[2, 3] input"),
    )),
    ("nn/layer/norm.py", ()),
    ("nn/layer/rnn.py", ()),
    ("nn/layer/transformer.py", ()),
    ("nn/layer/vision.py", ()),
    ("nn/layer/distance.py", ()),
    ("nn/layer/container.py", ()),
    ("nn/functional/loss.py", (
        (2, "ref-bug: HSigmoidLoss example feeds a [4] label with a "
            "[2, 3] input"),
    )),
    ("nn/functional/activation.py", ()),
    ("nn/functional/norm.py", ()),
    ("nn/functional/conv.py", ()),
    ("nn/functional/input.py", ()),
    ("nn/functional/vision.py", ()),
    ("nn/functional/extension.py", ()),
    ("nn/functional/sparse_attention.py", ()),
    ("distribution/dirichlet.py", ()),
    ("distribution/kl.py", ()),
    ("distribution/multinomial.py", ()),
    ("distribution/normal.py", ()),
    ("optimizer/adadelta.py", ()),
    ("optimizer/adagrad.py", ()),
    ("optimizer/adam.py", ()),
    ("optimizer/lamb.py", ()),
    ("optimizer/momentum.py", ()),
    ("optimizer/rmsprop.py", ()),
    ("optimizer/sgd.py", ()),
    ("fft.py", ()),
    ("signal.py", ()),
    ("framework/random.py", ()),
    ("text/viterbi_decode.py", ()),
    ("static/nn/common.py", ()),
    ("vision/ops.py", (
        (4, "ref-bug: uses np without importing it; needs cv2"),
        (5, "ref-bug: uses np without importing it; needs cv2"),
    )),
]


def _run_module_matrix(relpath, skips, paddle_alias):
    skip_idx = {i for i, _ in skips}
    blocks = _harvest(relpath)
    ran = 0
    for i, b in enumerate(blocks):
        if not b.strip() or i in skip_idx:
            continue
        _run(b)
        ran += 1
    assert ran >= max(1, len(blocks) - len(skip_idx) - 1), (relpath, ran)


@pytest.mark.parametrize("relpath,skips", _MATRIX_QUICK,
                         ids=[m for m, _ in _MATRIX_QUICK])
def test_doc_example_matrix_quick(relpath, skips, paddle_alias):
    _run_module_matrix(relpath, skips, paddle_alias)


@pytest.mark.heavy
@pytest.mark.parametrize("relpath,skips", _MATRIX_HEAVY,
                         ids=[m for m, _ in _MATRIX_HEAVY])
def test_doc_example_matrix_heavy(relpath, skips, paddle_alias):
    _run_module_matrix(relpath, skips, paddle_alias)
