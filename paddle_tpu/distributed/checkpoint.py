"""Distributed (sharded/async) checkpointing.

Parity: the reference's large-model checkpoint paths
(distributed/fleet/meta_parallel/sharding state dict save +
fleet/utils/fs.py). TPU-native: orbax-checkpoint writes each shard from
the device holding it (multi-host safe, async option), restoring directly
into the sharded layout — no gather-to-host-0 bottleneck.
"""
import os

import numpy as np
import jax

__all__ = ["save_sharded", "load_sharded", "save_train_state",
           "load_train_state"]


def _checkpointer(use_async=False):
    import orbax.checkpoint as ocp
    if use_async:
        return ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return ocp.Checkpointer(ocp.StandardCheckpointHandler())


def save_sharded(tree, path, use_async=False):
    """Save a pytree of (possibly sharded) jax arrays."""
    path = os.path.abspath(path)
    ckptr = _checkpointer(use_async)
    ckptr.save(path, tree, force=True)
    if use_async:
        return ckptr  # caller may .wait_until_finished()
    return None


def load_sharded(path, target_tree=None, shardings=None):
    """Restore; when `shardings` (matching pytree of NamedSharding) is
    given, arrays land directly in their distributed placement."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    if target_tree is None and shardings is None:
        return ckptr.restore(path)
    if shardings is not None:
        abstract = jax.tree.map(
            lambda arr, sh: jax.ShapeDtypeStruct(arr.shape, arr.dtype,
                                                 sharding=sh),
            target_tree, shardings)
        return ckptr.restore(path, args=ocp.args.StandardRestore(abstract))
    return ckptr.restore(path, args=ocp.args.StandardRestore(target_tree))


def save_train_state(step_obj, path, use_async=False):
    """Checkpoint a HybridTrainStep / TrainStep (params + opt state)."""
    tree = {"params": step_obj.params,
            "opt_state": jax.tree.map(
                lambda x: x, step_obj.opt_state,
                is_leaf=lambda x: hasattr(x, "dtype")),
            "step": np.asarray(step_obj._step_i)}
    return save_sharded(tree, path, use_async)


def load_train_state(step_obj, path):
    shardings = None
    if hasattr(step_obj, "param_shardings"):
        shardings = {
            "params": step_obj.param_shardings,
            "opt_state": jax.tree.map(
                lambda arr: arr.sharding, step_obj.opt_state,
                is_leaf=lambda x: hasattr(x, "dtype")),
            "step": None,
        }
    target = {"params": step_obj.params, "opt_state": step_obj.opt_state,
              "step": np.asarray(step_obj._step_i)}
    restored = load_sharded(path, target, None)
    opt_state = jax.tree.map(
        lambda cur, new: new, step_obj.opt_state, restored["opt_state"],
        is_leaf=lambda x: hasattr(x, "dtype"))
    if hasattr(step_obj, "set_tree_state"):
        # TrainStep: params/opt_state are per-leaf VIEWS (the donated
        # truth may be the fused epilogue's flat stores) — restore
        # through the layout-aware setter
        step_obj.set_tree_state(restored["params"], opt_state)
    else:
        step_obj.params = restored["params"]
        step_obj.opt_state = opt_state
    step_obj._step_i = int(restored["step"])
    return step_obj
