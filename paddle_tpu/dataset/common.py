"""paddle.dataset.common — DATA_HOME, file integrity, reader sharding.

Parity: /root/reference/python/paddle/dataset/common.py. `download` is
a zero-egress shim: it returns the path when the file is already on
disk and raises a clear placement instruction otherwise (this
environment has no network; see vision/datasets for the same contract).
"""
import errno
import glob
import hashlib
import os
import pickle

__all__ = []

DATA_HOME = os.path.expanduser("~/.cache/paddle/dataset")


def must_mkdirs(path):
    try:
        os.makedirs(path)
    except OSError as exc:
        if exc.errno != errno.EEXIST:
            raise


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname,
        url.split("/")[-1] if save_name is None else save_name)
    if os.path.exists(filename) and (
            not md5sum or md5file(filename) == md5sum):
        return filename
    raise FileNotFoundError(
        f"{module_name}: no network access in this environment — place "
        f"the official file from {url} at {filename} manually")


def fetch_all():
    raise NotImplementedError(
        "fetch_all downloads every dataset; this environment is "
        "zero-egress (see paddle_tpu.dataset.common.download)")


def split(reader, line_count, suffix="%05d.pickle", dumper=pickle.dump):
    """Split a reader's samples into chunked files of `line_count`
    samples each; returns the written filenames."""
    if not callable(reader):
        raise TypeError("reader should be callable")
    if not isinstance(line_count, int):
        raise TypeError("line_count should be int")
    import re
    if not isinstance(suffix, str) or not re.search(r"%\d*d", suffix):
        raise TypeError("suffix should be a str with a %d slot in it")
    lines = []
    indx_f = 0
    written = []
    for i, d in enumerate(reader()):
        lines.append(d)
        if i >= line_count and i % line_count == 0:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
                written.append(suffix % indx_f)
                lines = []
                indx_f += 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)
            written.append(suffix % indx_f)
    return written


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=pickle.load):
    """Round-robin shard chunked files across trainers and replay their
    samples."""
    def reader():
        file_list = glob.glob(files_pattern)
        file_list.sort()
        my_file_list = [f for i, f in enumerate(file_list)
                        if i % trainer_count == trainer_id]
        for fn in my_file_list:
            with open(fn, "rb") as f:
                for line in loader(f):
                    yield line

    return reader
