"""Deferred device scalars — the non-blocking half of the async step loop.

`TrainStep` / `HybridTrainStep` dispatch one fused XLA program per step
and, under JAX's async dispatch, return before the device finishes. The
old `float(loss.item())` in every train loop threw that away: each step
blocked the host on the previous step's result, serializing dispatch
with compute. A `DeferredLoss` keeps the pipeline moving:

- construction starts a device->host copy (`jax.Array.copy_to_host_async`)
  and returns immediately — by the time anyone reads the value, the DMA
  has usually already landed;
- it IS a `Tensor` (drop-in for every existing `loss.item()` /
  `loss.value` call site), so nothing downstream needs to know;
- any host read (`float()`, `.item()`, `.numpy()`) resolves at most
  once, and the time the host actually spent blocked is recorded — the
  `host.block` span and the `host.blocked_s` histogram — so synchronous
  pressure shows up in telemetry instead of hiding inside step time.

The hapi fit loop holds these handles unresolved until a `log_freq`
boundary or epoch end; `tools/check_no_hot_sync.py` lints the hot paths
so a blocking read can't sneak back in.
"""
import time

import numpy as np

from ..framework.core import Tensor
from ..profiler import statistic as _stat
from ..profiler import monitor as _monitor

__all__ = ["DeferredLoss"]


class DeferredLoss(Tensor):
    """A scalar (or small) device array whose host value is fetched
    lazily. See module docstring for the overlap contract."""

    def __init__(self, value):
        arr = value.value if isinstance(value, Tensor) else value
        super().__init__(arr)
        self._resolved = None
        try:
            # start the D2H DMA now; the eventual np.asarray only waits
            # for whatever is still in flight
            arr.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass  # non-jax array (tests) or backend without async copy

    def numpy(self):
        if self._resolved is None:
            t0 = time.perf_counter()
            out = np.asarray(self.value)
            dt = time.perf_counter() - t0
            _stat.record_span("host.block", dt)
            _monitor.histogram("host.blocked_s").observe(dt)
            self._resolved = out
        return self._resolved

    def resolve(self):
        """Blocking fetch as a python float (cached)."""
        return float(self.numpy().reshape(()))

    def __format__(self, spec):
        # keep pre-deferred callbacks working: f"{logs['loss'][0]:.4f}"
        # resolves here (the reader opted into a host sync)
        return format(self.resolve(), spec)
