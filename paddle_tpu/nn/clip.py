"""Gradient clipping. Parity: python/paddle/fluid/clip.py."""
import jax.numpy as jnp

from ..framework.core import Tensor, no_grad

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        """params_grads: list of (param, grad Tensor) pairs → clipped."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        with no_grad():
            for p, g in params_grads:
                if g is None or not getattr(p, "need_clip", True):
                    out.append((p, g))
                    continue
                out.append((p, Tensor(jnp.clip(g.value, self.min,
                                               self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        with no_grad():
            for p, g in params_grads:
                if g is None or not getattr(p, "need_clip", True):
                    out.append((p, g))
                    continue
                n = jnp.sqrt(jnp.sum(jnp.square(
                    g.value.astype(jnp.float32))))
                factor = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12),
                                     1.0)
                out.append((p, Tensor((g.value * factor).astype(
                    g.value.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        with no_grad():
            sq = 0.0
            any_clip = False
            for p, g in params_grads:
                if g is None or not getattr(p, "need_clip", True):
                    continue
                any_clip = True
                sq = sq + jnp.sum(jnp.square(g.value.astype(jnp.float32)))
            if not any_clip:
                return params_grads
            gn = jnp.sqrt(sq)
            factor = jnp.minimum(self.clip_norm / jnp.maximum(gn, 1e-12),
                                 1.0)
            out = []
            for p, g in params_grads:
                if g is None or not getattr(p, "need_clip", True):
                    out.append((p, g))
                    continue
                out.append((p, Tensor((g.value * factor).astype(
                    g.value.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    params = [p for p in (parameters if isinstance(parameters, (list, tuple))
                          else [parameters]) if p.grad is not None]
    with no_grad():
        if norm_type == float("inf"):
            total = max((jnp.max(jnp.abs(p.grad.value)) for p in params),
                        default=0.0)
        else:
            total = sum(jnp.sum(jnp.abs(
                p.grad.value.astype(jnp.float32)) ** norm_type)
                for p in params) ** (1.0 / norm_type)
        factor = jnp.minimum(max_norm / (total + 1e-6), 1.0)
        for p in params:
            p.grad = Tensor((p.grad.value * factor).astype(
                p.grad.value.dtype))
    return Tensor(jnp.asarray(total))


def clip_grad_value_(parameters, clip_value):
    params = parameters if isinstance(parameters, (list, tuple)) \
        else [parameters]
    with no_grad():
        for p in params:
            if p.grad is not None:
                p.grad = Tensor(jnp.clip(p.grad.value, -clip_value,
                                         clip_value))


def clip_grads_tree(grads, clip):
    """Apply a grad-clip config to a pytree of RAW jax arrays (the shared
    jit-path implementation for TrainStep / HybridTrainStep /
    LocalSGDTrainStep — one source of truth for the clip math)."""
    if clip is None:
        return grads
    import jax
    import jax.numpy as jnp
    if isinstance(clip, ClipGradByGlobalNorm):
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for g in jax.tree.leaves(grads)))
        f = jnp.minimum(clip.clip_norm / jnp.maximum(gn, 1e-12), 1.0)
        return jax.tree.map(lambda g: (g * f).astype(g.dtype), grads)
    if isinstance(clip, ClipGradByNorm):
        def per_leaf(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            f = jnp.minimum(clip.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            return (g * f).astype(g.dtype)
        return jax.tree.map(per_leaf, grads)
    if isinstance(clip, ClipGradByValue):
        return jax.tree.map(lambda g: jnp.clip(g, clip.min, clip.max),
                            grads)
    return grads
