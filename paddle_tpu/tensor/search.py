"""Search & sort ops. Parity: python/paddle/tensor/search.py."""
import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply_op(lambda a: jnp.argmax(a, axis=axis, keepdims=keepdim), x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply_op(lambda a: jnp.argmin(a, axis=axis, keepdims=keepdim), x)


def argsort(x, axis=-1, descending=False, name=None):
    def fn(a):
        idx = jnp.argsort(a, axis=axis, descending=descending)
        return idx
    return apply_op(fn, x)


def sort(x, axis=-1, descending=False, name=None):
    return apply_op(
        lambda a: jnp.sort(a, axis=axis, descending=descending), x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    def fn(a):
        ax = -1 if axis is None else axis
        src = a if largest else -a
        moved = jnp.moveaxis(src, ax, -1)
        import jax
        vals, idx = jax.lax.top_k(moved, k)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax))
    v, i = apply_op(fn, x)
    return v, i


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    xt = x if isinstance(x, Tensor) else Tensor(np.asarray(x))
    yt = y if isinstance(y, Tensor) else Tensor(np.asarray(y))
    return apply_op(lambda c, a, b: jnp.where(c, a, b), condition, xt, yt)


def where_(condition, x=None, y=None, name=None):
    out = where(condition, x, y)
    x._bind(out._slot)
    return x


def nonzero(x, as_tuple=False):
    nz = np.nonzero(x.numpy())
    if as_tuple:
        return tuple(Tensor(n.reshape(-1, 1)) for n in nz)
    return Tensor(np.stack(nz, axis=1).astype(np.int64))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(a):
        srt = jnp.sort(a, axis=axis)
        idx = jnp.argsort(a, axis=axis)
        vals = jnp.take(srt, k - 1, axis=axis)
        ids = jnp.take(idx, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            ids = jnp.expand_dims(ids, axis)
        return vals, ids
    return apply_op(fn, x)


def mode(x, axis=-1, keepdim=False, name=None):
    a = x.numpy()
    moved = np.moveaxis(a, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], dtype=a.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for r in range(flat.shape[0]):
        u, c = np.unique(flat[r], return_counts=True)
        best = u[np.argmax(c)]
        vals[r] = best
        idxs[r] = np.max(np.nonzero(flat[r] == best)[0])
    shp = moved.shape[:-1]
    vals, idxs = vals.reshape(shp), idxs.reshape(shp)
    if keepdim:
        vals = np.expand_dims(vals, axis)
        idxs = np.expand_dims(idxs, axis)
    return Tensor(vals), Tensor(idxs)


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as ms
    return ms(x, mask)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    side = "right" if right else "left"
    def fn(s, v):
        if s.ndim == 1:
            return jnp.searchsorted(s, v, side=side)
        import jax
        return jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side)
                        )(s.reshape(-1, s.shape[-1]),
                          v.reshape(-1, v.shape[-1])).reshape(v.shape)
    return apply_op(fn, sorted_sequence, values)


def index_select(x, index, axis=0, name=None):
    from .manipulation import index_select as isel
    return isel(x, index, axis)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)
