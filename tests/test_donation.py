"""Buffer donation + in-step GradScaler for the fused train step.

Donation is the difference between XLA updating params/optimizer
state/scaler state IN PLACE in HBM and holding a second full copy of the
model per step. The proof is structural: the lowered executable's
input_output_alias map must alias every param and optimizer-state leaf,
and paddle.device.max_memory_allocated() (jax.Device.memory_stats-backed)
must report sane nonzero peaks to measure the win with.
"""
import re

import numpy as np
import jax
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.amp import GradScaler
from paddle_tpu.jit import TrainStep


def _loss_fn(out, y):
    return nn.functional.cross_entropy(out, y)


def _make(donate=True, scaler=None, optimizer=None):
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    o = optimizer or opt.AdamW(learning_rate=1e-3,
                               parameters=m.parameters())
    step = TrainStep(m, _loss_fn, o, donate=donate, scaler=scaler)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(np.array([0, 1, 2, 3], np.int64))
    return step, x, y


def _alias_count(hlo_text):
    # entries look like `{0}: (14, {}, may-alias)`; without donation the
    # input_output_alias attribute is absent from the module header
    m = re.search(r"input_output_alias=\{(.*?)\n", hlo_text)
    if m is None or not m.group(1).strip():
        return 0
    return m.group(1).count("must-alias") + m.group(1).count("may-alias")


def _donated_leaves(step):
    # what the dispatch actually donates: on the fused-epilogue layout
    # that is the dtype-bucketed flat stores (few megabuffers), on the
    # tree layout the per-leaf params/opt-state trees
    return (len(jax.tree.leaves(step._params_store))
            + len(jax.tree.leaves(step._opt_store)))


def test_train_step_aliases_params_and_opt_state():
    step, x, y = _make()
    n_leaves = _donated_leaves(step)
    aliases = _alias_count(step.compiled_text(x, y))
    assert aliases >= n_leaves, (
        f"{aliases} aliased buffers < {n_leaves} donated leaves — "
        "the step is copying the model instead of updating in place")


def test_train_step_aliases_every_tree_leaf_unfused():
    """The tree path's per-leaf donation contract, kept alive by the
    escape hatch: every param and optimizer-state leaf aliases."""
    step, x, y = _make()
    tree = TrainStep(step.model, _loss_fn, step.optimizer,
                     fused_update=False)
    n_leaves = (len(jax.tree.leaves(tree.params))
                + len(jax.tree.leaves(tree.opt_state)))
    assert _alias_count(tree.compiled_text(x, y)) >= n_leaves


def test_no_donation_no_aliases():
    step, x, y = _make(donate=False)
    assert _alias_count(step.compiled_text(x, y)) == 0


def test_scaler_state_is_donated_too():
    step, x, y = _make(scaler=GradScaler(init_loss_scaling=2.0 ** 10))
    n_leaves = (_donated_leaves(step)
                + len(jax.tree.leaves(step.scaler_state)))
    assert _alias_count(step.compiled_text(x, y)) >= n_leaves


def test_retrace_counter_and_compile_seconds():
    step, x, y = _make()
    float(step(x, y).item())
    assert step.retraces == 1 and step.compile_s > 0
    t_first = step.compile_s
    float(step(x, y).item())  # same signature: no retrace
    assert step.retraces == 1 and step.compile_s == t_first
    x2 = paddle.to_tensor(
        np.random.RandomState(1).randn(8, 8).astype(np.float32))
    y2 = paddle.to_tensor(np.arange(8, dtype=np.int64) % 4)
    float(step(x2, y2).item())  # new batch shape: one retrace
    assert step.retraces == 2


def test_scaled_step_trains_and_keeps_scale():
    sc = GradScaler(init_loss_scaling=2.0 ** 10)
    step, x, y = _make(scaler=sc)
    before = np.asarray(step.params["0.weight"]).copy()
    l1 = float(step(x, y).item())
    l2 = float(step(x, y).item())
    assert np.isfinite(l1) and np.isfinite(l2)
    assert not np.allclose(before, np.asarray(step.params["0.weight"]))
    # finite grads at default incr_every=1000: scale must not move
    assert float(step.scaler_state["scale"]) == 2.0 ** 10
    step.sync_to_model()
    assert sc.get_loss_scaling() == 2.0 ** 10


def test_overflow_step_is_skipped_and_scale_backs_off():
    """A bad batch (non-finite activations -> non-finite gradients):
    found_inf must skip the whole update (params + optimizer state
    unchanged) and the dynamic scaling must halve the scale — all inside
    the one donated XLA step, no host sync."""
    sc = GradScaler(init_loss_scaling=2.0 ** 15,
                    decr_every_n_nan_or_inf=1)
    step, x, y = _make(scaler=sc)
    bad = paddle.to_tensor(
        np.full((4, 8), np.inf, np.float32))
    before = np.asarray(step.params["0.weight"]).copy()
    m_before = np.asarray(jax.tree.leaves(step.opt_state)[0]).copy()
    step(bad, y)
    np.testing.assert_array_equal(before,
                                  np.asarray(step.params["0.weight"]))
    np.testing.assert_array_equal(
        m_before, np.asarray(jax.tree.leaves(step.opt_state)[0]))
    assert float(step.scaler_state["scale"]) == 2.0 ** 14
    # a good batch afterwards still trains
    l2 = float(step(x, y).item())
    assert np.isfinite(l2)
    assert not np.allclose(before, np.asarray(step.params["0.weight"]))


def test_run_steps_carries_scaler_state():
    sc = GradScaler(init_loss_scaling=2.0 ** 8)
    step, x, y = _make(scaler=sc)
    losses = step.run_steps(3, x, y)
    assert losses.shape == [3]
    assert all(np.isfinite(v) for v in losses.numpy())
    assert float(step.scaler_state["scale"]) == 2.0 ** 8


def test_max_memory_allocated_returns_sane_nonzero():
    step, x, y = _make()
    float(step(x, y).item())
    peak = paddle.device.max_memory_allocated()
    assert peak > 0
    assert paddle.device.memory_allocated() >= 0
    assert paddle.device.max_memory_reserved() >= 0
    # the cuda-namespace alias goes through the same implementation
    assert paddle.device.cuda.max_memory_allocated() == \
        pytest.approx(paddle.device.max_memory_allocated(), rel=0.5)


def test_hybrid_train_step_donates_and_scales():
    from paddle_tpu.distributed.env import build_mesh
    from paddle_tpu.distributed.fleet.hybrid_train import HybridTrainStep

    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
    mesh = build_mesh(dp=8)
    sc = GradScaler(init_loss_scaling=2.0 ** 6)
    step = HybridTrainStep(m, _loss_fn, o, mesh, scaler=sc)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(8, 8).astype(np.float32))
    y = paddle.to_tensor(np.arange(8, dtype=np.int64) % 4)
    assert _alias_count(step.compiled_text(x, y)) >= (
        len(jax.tree.leaves(step.params))
        + len(jax.tree.leaves(step.opt_state)))
    loss = float(step(x, y).item())
    assert np.isfinite(loss)
    assert step.retraces >= 1 and step.compile_s > 0
    assert float(step.scaler_state["scale"]) == 2.0 ** 6
