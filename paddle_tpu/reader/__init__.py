"""paddle.reader — legacy reader-decorator utilities.

Parity: /root/reference/python/paddle/reader/__init__.py.
"""
from .decorator import (cache, map_readers, shuffle, chain, compose,
                        buffered, firstn, xmap_readers,
                        multiprocess_reader, ComposeNotAligned)

__all__ = []
