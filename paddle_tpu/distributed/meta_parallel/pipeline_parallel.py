"""Pipeline-parallel execution engine.

Parity: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py
(PipelineParallel: 1F1B/GPipe schedules over NCCL p2p).

TPU-native design: the schedule is ONE SPMD program. Per-stage parameter
pytrees are stacked on a leading [pp] axis and sharded over the 'pp' mesh
axis; inside shard_map every device runs the same stage function on its
local shard while lax.ppermute rotates microbatch activations to the next
stage over ICI. The fill/steady/drain phases of GPipe fall out of a single
fori_loop of length (n_micro + n_stages - 1); reverse-mode AD through
ppermute yields the backward pipeline automatically, so 1F1B-style
interleaving is XLA's scheduling problem, not hand-written control flow
(see PAPERS.md: MPMD pipeline parallelism — we deliberately choose the
SPMD formulation natural to XLA).

Constraint (documented): stages must be structurally uniform (same layer
stack per stage) — embedding/head run replicated outside the pipelined
segment. This matches how transformer trunks are pipelined in practice.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from ...framework.jax_compat import shard_map

from ...framework.core import Tensor
from ...jit.api import functional_call, state_arrays, _bind, _restore

__all__ = ["PipelineParallel", "pipeline_apply",
           "pipeline_apply_interleaved", "pipeline_1f1b"]


def pipeline_apply(stage_fn, stacked_params, x_micro, mesh, n_stages,
                   n_micro):
    """Run the GPipe schedule. stacked_params leaves: [pp, ...];
    x_micro: [n_micro, mb, ...] (replicated over pp). Returns stacked
    last-stage outputs [n_micro, mb, ...]."""

    def spmd(params_local, xs):
        # params_local leaves: [1, ...] → this stage's params
        params_here = jax.tree.map(lambda a: a[0], params_local)
        stage = jax.lax.axis_index("pp")
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        T = n_micro + n_stages - 1
        mb_shape = xs.shape[1:]
        outputs = jnp.zeros((n_micro,) + mb_shape, xs.dtype)
        carry = jnp.zeros(mb_shape, xs.dtype)

        def tick(t, state):
            recv, outputs = state
            feed_idx = jnp.clip(t, 0, n_micro - 1)
            first_in = jnp.where(t < n_micro, xs[feed_idx],
                                 jnp.zeros(mb_shape, xs.dtype))
            inp = jnp.where(stage == 0, first_in, recv)
            out = stage_fn(params_here, inp)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            is_valid = (t >= n_stages - 1) & (stage == n_stages - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(is_valid, out, outputs[out_idx]), out_idx, 0)
            recv = jax.lax.ppermute(out, "pp", perm)
            return recv, outputs

        recv, outputs = jax.lax.fori_loop(0, T, tick, (carry, outputs))
        # broadcast last-stage outputs to every pp rank so downstream
        # (replicated head/loss) sees them
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outputs, 0.0), "pp")
        return outputs

    pp_specs = jax.tree.map(lambda _: P("pp"), stacked_params)
    return shard_map(
        spmd, mesh=mesh,
        in_specs=(pp_specs, P()), out_specs=P(),
        check_vma=False)(stacked_params, x_micro)


def pipeline_apply_interleaved(stage_fn, stacked_params, x_micro, mesh,
                               n_stages, n_micro, n_virtual):
    """Interleaved virtual-stage schedule (Megatron-style; ref
    pipeline_parallel.py "interleaved"/virtual pp + pp_layers.py virtual
    stages): each device owns V non-contiguous model chunks, so the
    pipeline fill is V× shallower relative to per-tick work — bubble
    fraction drops from (S-1)/(M+S-1) toward (S-1)/(M·V+S-1).

    stacked_params leaves: [S*V, ...] in DEVICE-MAJOR order (row d*V+c =
    chunk c living on device d); under P("pp") sharding device d holds
    exactly its V chunks. Schedule position for device d at tick t:
    k = t-d; group g = k//(S·V), j = k%(S·V), chunk c = j//S, and
    micro m = g·S + j%S. Activations hop d→d+1 each tick; the wrap
    S-1→0 carries the micro into its next chunk. Requires n_micro %
    n_stages == 0. Backward is reverse-mode AD through the loop (GPipe-
    class memory; combine with recompute for depth-bounded footprint)."""
    S, V, M = n_stages, n_virtual, n_micro
    if M % S != 0:
        raise ValueError(f"interleaved schedule needs n_micro ({M}) "
                         f"divisible by n_stages ({S})")
    G = M // S
    T = S - 1 + G * S * V

    def spmd(params_local, xs):
        # params_local leaves: [V, ...] — this device's chunks
        d = jax.lax.axis_index("pp")
        perm = [(i, (i + 1) % S) for i in range(S)]
        mb_shape = xs.shape[1:]
        outputs = jnp.zeros((M,) + mb_shape, xs.dtype)
        recv0 = jnp.zeros(mb_shape, xs.dtype)

        def tick(t, state):
            recv, outputs = state
            k = t - d
            valid = (k >= 0) & (k < G * S * V)
            kc = jnp.clip(k, 0, G * S * V - 1)
            g = kc // (S * V)
            j = kc % (S * V)
            c = j // S
            m = g * S + (j % S)
            params_here = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(a, c, 0,
                                                       keepdims=False),
                params_local)
            inp = jnp.where((d == 0) & (c == 0), xs[m], recv)
            out = stage_fn(params_here, inp)
            done = valid & (d == S - 1) & (c == V - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(done, out, outputs[m]), m, 0)
            recv = jax.lax.ppermute(out, "pp", perm)
            return recv, outputs

        _, outputs = jax.lax.fori_loop(0, T, tick, (recv0, outputs))
        return jax.lax.psum(
            jnp.where(d == S - 1, outputs, 0.0), "pp")

    pp_specs = jax.tree.map(lambda _: P("pp"), stacked_params)
    return shard_map(
        spmd, mesh=mesh,
        in_specs=(pp_specs, P()), out_specs=P(),
        check_vma=False)(stacked_params, x_micro)


def pipeline_1f1b(stage_fn, stacked_params, edge_params, pre_fn, post_fn,
                  loss_arr, x_micro, y_micro, mesh, n_stages, n_micro):
    """1F1B schedule with a hand-written, recompute-based backward.

    Parity: the 1f1b schedule in the reference's
    fleet/meta_parallel/pipeline_parallel.py:81,170 — but formulated SPMD:
    one fori_loop of combined fwd+bwd "cycles"; each stage keeps only a
    ring buffer of min(n_micro, 2*n_stages-1) saved stage INPUTS and
    recomputes the stage forward inside jax.vjp at backward time. Peak
    activation memory is therefore bounded by the pipeline depth, not by
    n_micro (GPipe-via-AD saves every tick's residuals).

    Schedule algebra (stage s of S, cycle c):
      forward  micro  fm = c - s            (valid while 0 <= fm < n_micro)
      backward micro  bm = c - 2(S-1) + s   (last stage: bm == fm, so it
                                             backwards a micro in the same
                                             cycle it forwarded it)
    Cotangents ride the reverse ppermute ring; a micro's backward at stage
    s+1 lands exactly one cycle before stage s needs it.

    pre_fn/post_fn(edge_params, x) run at the pipeline edges (stage 0 /
    last stage) inside the loop — SharedLayerDesc tied weights live in
    `edge_params` once, so d(pre)+d(post) accumulate into one leaf.
    Returns (loss, trunk_grads [pp-sharded], edge_grads [replicated]).
    """
    S, M = n_stages, n_micro
    R = min(M, 2 * S - 1)

    def spmd(params_local, edge_p, xs, ys):
        params_here = jax.tree.map(lambda a: a[0], params_local)
        stage = jax.lax.axis_index("pp")
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [(i, (i - 1) % S) for i in range(S)]
        C = M + 2 * (S - 1)

        # probe shapes (abstract eval only — no FLOPs at runtime)
        x0 = pre_fn(edge_p, xs[0])
        mb_shape, mb_dtype = x0.shape, x0.dtype

        ring = jnp.zeros((R,) + mb_shape, mb_dtype)
        fwd_recv = jnp.zeros(mb_shape, mb_dtype)
        bwd_recv = jnp.zeros(mb_shape, mb_dtype)
        grads0 = jax.tree.map(jnp.zeros_like, params_here)
        egrads0 = jax.tree.map(jnp.zeros_like, edge_p)
        loss0 = jnp.zeros((), jnp.float32)

        def cycle(c, state):
            ring, fwd_recv, bwd_recv, grads, egrads, loss_acc = state

            # ---------- forward slot ----------
            fm = c - stage
            fwd_valid = (fm >= 0) & (fm < M)
            fm_c = jnp.clip(fm, 0, M - 1)
            inp = jnp.where(stage == 0, pre_fn(edge_p, xs[fm_c]), fwd_recv)
            out = stage_fn(params_here, inp)
            slot = fm_c % R
            old = jax.lax.dynamic_index_in_dim(ring, slot, 0,
                                               keepdims=False)
            ring = jax.lax.dynamic_update_index_in_dim(
                ring, jnp.where(fwd_valid, inp, old), slot, 0)

            # last stage: per-micro loss + seed cotangent, same cycle
            def head_loss(ep, o):
                return loss_arr(post_fn(ep, o), ys[fm_c])

            l_m, head_vjp = jax.vjp(head_loss, edge_p, out)
            dep_head, seed = head_vjp(jnp.float32(1.0 / M))
            last = stage == S - 1
            loss_acc = loss_acc + jnp.where(
                fwd_valid & last, l_m.astype(jnp.float32) / M, 0.0)

            # ---------- backward slot ----------
            bm = c - 2 * (S - 1) + stage
            bwd_valid = (bm >= 0) & (bm < M)
            bm_c = jnp.clip(bm, 0, M - 1)
            x_saved = jax.lax.dynamic_index_in_dim(ring, bm_c % R, 0,
                                                   keepdims=False)
            cot_in = jnp.where(last, seed, bwd_recv)
            _, stage_vjp = jax.vjp(stage_fn, params_here, x_saved)
            dp, dx = stage_vjp(cot_in)

            bmask = bwd_valid.astype(jnp.float32)
            grads = jax.tree.map(
                lambda g, d: g + d.astype(g.dtype) * bmask.astype(g.dtype),
                grads, dp)
            # edge grads: head side lands on the last stage at fwd time;
            # pre side chains dx through pre_fn on stage 0 at bwd time
            def pre_chain(ep):
                return pre_fn(ep, xs[bm_c])

            _, pre_vjp = jax.vjp(pre_chain, edge_p)
            (dep_pre,) = pre_vjp(dx)
            hmask = (fwd_valid & last).astype(jnp.float32)
            pmask = (bwd_valid & (stage == 0)).astype(jnp.float32)
            egrads = jax.tree.map(
                lambda g, dh, dpr: g + dh.astype(g.dtype) *
                hmask.astype(g.dtype) + dpr.astype(g.dtype) *
                pmask.astype(g.dtype),
                egrads, dep_head, dep_pre)

            fwd_recv = jax.lax.ppermute(out, "pp", fwd_perm)
            bwd_recv = jax.lax.ppermute(dx, "pp", bwd_perm)
            return ring, fwd_recv, bwd_recv, grads, egrads, loss_acc

        state = (ring, fwd_recv, bwd_recv, grads0, egrads0, loss0)
        *_, grads, egrads, loss_acc = jax.lax.fori_loop(0, C, cycle, state)
        loss = jax.lax.psum(loss_acc, "pp")  # only last stage contributed
        egrads = jax.tree.map(lambda g: jax.lax.psum(g, "pp"), egrads)
        grads = jax.tree.map(lambda g: g[None], grads)
        return loss, grads, egrads

    pp_specs = jax.tree.map(lambda _: P("pp"), stacked_params)
    rep_specs = jax.tree.map(lambda _: P(), edge_params)
    return shard_map(
        spmd, mesh=mesh,
        in_specs=(pp_specs, rep_specs, P(), P()),
        out_specs=(P(), pp_specs, rep_specs),
        check_vma=False)(stacked_params, edge_params, x_micro, y_micro)


class PipelineParallel:
    """Engine over a PipelineLayer: builds the stacked-stage params and a
    jitted train step. Used by fleet and by tests/dryrun.

    schedule: "gpipe" (AD through the fill/steady/drain loop) or "1f1b"
    (hand-written interleaved backward, depth-bounded activation memory —
    ref fleet/meta_parallel/pipeline_parallel.py:81,170).

    SharedLayerDesc entries at the head/tail of the stack (tied
    embedding/LM-head) are lifted out of the pipelined trunk into
    replicated `edge` params applied at stage 0 / last stage; because the
    tied weight is ONE leaf used by both, its gradient is the sum of both
    uses (ref parallel_layers/pp_layers.py:49)."""

    def __init__(self, pipeline_layer, optimizer, mesh, n_micro=2,
                 loss_fn=None, schedule="gpipe", n_virtual=1):
        self.layer = pipeline_layer
        self.optimizer = optimizer
        self.mesh = mesh
        self.n_micro = n_micro
        self.n_stages = pipeline_layer.num_stages
        self.loss_fn = loss_fn or pipeline_layer._loss_fn
        self.schedule = schedule.lower().replace("-", "")
        if self.schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        if self.schedule == "interleaved":
            self.n_virtual = max(2, int(n_virtual))
        else:
            self.n_virtual = 1
        self._step_i = 0

        # ---- split the stack: [pre edge][uniform trunk][post edge] -----
        shared_ids = {id(l) for l in pipeline_layer._shared.values()}
        items = list(pipeline_layer.run_function)
        pre_items, post_items = [], []
        while items and id(items[0][0]) in shared_ids:
            pre_items.append(items.pop(0))
        while items and id(items[-1][0]) in shared_ids:
            post_items.append(items.pop())
        post_items.reverse()
        n_seg = self.n_stages * self.n_virtual
        if len(items) % n_seg != 0:
            raise ValueError(
                f"trunk of {len(items)} layers does not divide into "
                f"{n_seg} uniform stages "
                f"({self.n_stages} stages x {self.n_virtual} chunks)")
        per = len(items) // n_seg
        segments = [items[i * per:(i + 1) * per] for i in range(n_seg)]
        self._segments = segments

        # ---- edge (replicated, possibly tied) params -------------------
        key_of = {id(l): name for name, l in pipeline_layer._shared.items()}
        edge = {}

        def _with_prefix(edge_items, base):
            out = []
            for j, (l, tag) in enumerate(edge_items):
                pref = key_of.get(id(l), f"{base}{j}") \
                    if hasattr(l, "named_parameters") else None
                out.append((l, tag, pref))
                if pref is not None:
                    for name, p in l.named_parameters():
                        edge[f"{pref}.{name}"] = p.value  # tied: one key
            return out

        pre_triples = _with_prefix(pre_items, "pre")
        post_triples = _with_prefix(post_items, "post")
        self.edge = edge

        def _edge_fn(triples):
            def fn(edge_p, x):
                xt = Tensor(x) if not isinstance(x, Tensor) else x
                for l, tag, pref in triples:
                    if pref is not None:
                        sub = {k[len(pref) + 1:]: v
                               for k, v in edge_p.items()
                               if k.startswith(pref + ".")}
                        saved = _bind(l, sub)
                        try:
                            xt = tag(l, xt) if callable(tag) and \
                                tag != "fn" else l(xt)
                        finally:
                            _restore(saved)
                    else:
                        xt = l(xt)
                return xt.value if isinstance(xt, Tensor) else xt
            return fn

        self._pre_fn = _edge_fn(pre_triples)
        self._post_fn = _edge_fn(post_triples)

        # ---- stacked per-stage trunk params; stages must be uniform ----
        seg_params = []
        for seg in segments:
            stage_arrays = {}
            for idx, (layer, tag) in enumerate(seg):
                if tag == "fn" or not hasattr(layer, "named_parameters"):
                    continue
                for name, p in layer.named_parameters():
                    stage_arrays[f"{idx}.{name}"] = p.value
            seg_params.append(stage_arrays)
        keys = sorted(seg_params[0].keys())
        for sp in seg_params[1:]:
            if sorted(sp.keys()) != keys:
                raise ValueError(
                    "pipeline stages are not structurally uniform: "
                    f"{sorted(sp.keys())} vs {keys}")
        # row order: device-major (row d*V+c = logical segment c*S+d) so
        # the P('pp') shard of device d is exactly its V chunks; for
        # V=1 this is plain segment order
        S, V = self.n_stages, self.n_virtual
        row_order = [c * S + d for d in range(S) for c in range(V)]
        self.stacked = {
            k: jnp.stack([seg_params[l][k] for l in row_order])
            for k in keys}
        pp_shard = {k: NamedSharding(mesh, P("pp"))
                    for k in self.stacked}
        self.stacked = {k: jax.device_put(v, pp_shard[k])
                        for k, v in self.stacked.items()}
        rep = NamedSharding(mesh, P())
        self.edge = {k: jax.device_put(v, rep)
                     for k, v in self.edge.items()}
        self.opt_state = {
            k: jax.tree.map(lambda s, _sh=pp_shard[k]:
                            jax.device_put(s, _sh),
                            optimizer.init_leaf_state(v))
            for k, v in self.stacked.items()}
        self.edge_opt_state = {
            k: jax.tree.map(lambda s: jax.device_put(s, rep),
                            optimizer.init_leaf_state(v))
            for k, v in self.edge.items()}

        seg0 = segments[0]

        def stage_fn(params_here, x):
            out = x
            for idx, (layer, tag) in enumerate(seg0):
                if tag == "fn":
                    out = layer(Tensor(out)).value if isinstance(
                        out, jnp.ndarray) else layer(out)
                    continue
                prefix = f"{idx}."
                sub = {name[len(prefix):]: arr
                       for name, arr in params_here.items()
                       if name.startswith(prefix)}
                out = functional_call(layer, sub, {}, (out,),
                                      training=True)
            return out

        self._stage_fn = stage_fn
        mesh_ = mesh
        n_stages = self.n_stages
        n_micro_ = n_micro
        opt = optimizer
        lfn = self.loss_fn
        pre_fn, post_fn = self._pre_fn, self._post_fn

        def loss_arr(out, y):
            l = lfn(Tensor(out), Tensor(y))
            return l.value if isinstance(l, Tensor) else l

        n_virtual_ = self.n_virtual

        def apply_trunk(ps, xa):
            if n_virtual_ > 1:
                return pipeline_apply_interleaved(
                    stage_fn, ps, xa, mesh_, n_stages, n_micro_,
                    n_virtual_)
            return pipeline_apply(stage_fn, ps, xa, mesh_, n_stages,
                                  n_micro_)

        if self.schedule == "1f1b":
            def train_step(stacked, edge, opt_state, edge_state, lr,
                           step_i, x, y):
                xm = jnp.stack(jnp.split(x, n_micro_, axis=0))
                ym = jnp.stack(jnp.split(y, n_micro_, axis=0))
                loss, grads, egrads = pipeline_1f1b(
                    stage_fn, stacked, edge, pre_fn, post_fn, loss_arr,
                    xm, ym, mesh_, n_stages, n_micro_)
                new_p, new_s = opt.apply_gradients_tree(
                    stacked, grads, opt_state, lr, step_i)
                if edge:
                    new_e, new_es = opt.apply_gradients_tree(
                        edge, egrads, edge_state, lr, step_i)
                else:
                    new_e, new_es = edge, edge_state
                return loss, new_p, new_e, new_s, new_es
        else:
            def train_step(stacked, edge, opt_state, edge_state, lr,
                           step_i, x, y):
                def loss_of(ps, ep):
                    xa = jax.vmap(lambda xi: pre_fn(ep, xi))(
                        jnp.stack(jnp.split(x, n_micro_, axis=0)))
                    outs = apply_trunk(ps, xa)
                    flat = outs.reshape((-1,) + outs.shape[2:])
                    return loss_arr(post_fn(ep, flat), y)

                loss, (grads, egrads) = jax.value_and_grad(
                    loss_of, argnums=(0, 1))(stacked, edge)
                new_p, new_s = opt.apply_gradients_tree(
                    stacked, grads, opt_state, lr, step_i)
                if edge:
                    new_e, new_es = opt.apply_gradients_tree(
                        edge, egrads, edge_state, lr, step_i)
                else:
                    new_e, new_es = edge, edge_state
                return loss, new_p, new_e, new_s, new_es

        self._train_step_fn = train_step
        self._jitted = jax.jit(train_step, donate_argnums=(0, 1, 2, 3))

    def train_batch(self, x, y):
        self._step_i += 1
        xa = x.value if isinstance(x, Tensor) else jnp.asarray(x)
        ya = y.value if isinstance(y, Tensor) else jnp.asarray(y)
        (loss, self.stacked, self.edge, self.opt_state,
         self.edge_opt_state) = self._jitted(
            self.stacked, self.edge, self.opt_state, self.edge_opt_state,
            jnp.asarray(self.optimizer.get_lr(), jnp.float32),
            self._step_i, xa, ya)
        return Tensor(loss)

    def forward(self, x):
        xa = x.value if isinstance(x, Tensor) else jnp.asarray(x)
        xm = jnp.stack(jnp.split(xa, self.n_micro, axis=0))
        xm = jax.vmap(lambda xi: self._pre_fn(self.edge, xi))(xm)
        if self.n_virtual > 1:
            outs = pipeline_apply_interleaved(
                self._stage_fn, self.stacked, xm, self.mesh,
                self.n_stages, self.n_micro, self.n_virtual)
        else:
            outs = pipeline_apply(self._stage_fn, self.stacked, xm,
                                  self.mesh, self.n_stages, self.n_micro)
        flat = outs.reshape((-1,) + outs.shape[2:])
        return Tensor(self._post_fn(self.edge, flat))
