"""Optimizers. Parity: python/paddle/optimizer/*.py.

Each optimizer defines a pure functional update core
(`_init_state` / `_update`) over jax arrays; the eager `step()` walks
parameters applying it, and the jit trainer (paddle_tpu.jit) calls
`apply_gradients` on whole pytrees inside a single compiled step — the
same math, fused by XLA. Master-weight (multi_precision) fp32 copies are
kept for bf16/fp16 params, mirroring the reference's multi-precision adam
(paddle/fluid/operators/optimizers/adam_op.h).
"""
import jax.numpy as jnp

from ..framework.core import Tensor, Parameter, no_grad
from ..regularizer import WeightDecayRegularizer, L2Decay
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "LarsMomentum", "Adam", "AdamW", "Adamax",
           "Adagrad", "Adadelta", "RMSProp", "Lamb"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        if parameters is not None and isinstance(parameters, (list, tuple)) \
                and len(parameters) and isinstance(parameters[0], dict):
            self._param_groups = [dict(g) for g in parameters]
            self._parameters = [p for g in self._param_groups
                                for p in g["params"]]
        else:
            self._param_groups = None
            self._parameters = list(parameters) if parameters is not None \
                else []
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        if isinstance(weight_decay, float):
            self._regularization = L2Decay(weight_decay)
        else:
            self._regularization = weight_decay  # regularizer or None
        self._states = {}
        self._step_count = 0
        self._accumulators = {}
        # TPU-native memory/precision knobs for the jit tree path (set as
        # attributes; bench/fleet configs flip them):
        # _stochastic_rounding: downcasts (f32 update -> bf16 param/state)
        #   add uniform sub-ulp noise before truncation, so updates below
        #   one bf16 ulp accumulate in expectation — master-weight-grade
        #   convergence without the 4-bytes/param master copy.
        # _state_dtype: store optimizer accumulators in this dtype
        #   (default f32); bf16 + stochastic rounding halves state HBM.
        self._stochastic_rounding = False
        self._state_dtype = None

    # -- lr ------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the lr is an LRScheduler instance")
        self._learning_rate = float(value)

    def _lr_for(self, p):
        return self.get_lr() * p.optimize_attr.get("learning_rate", 1.0) \
            if isinstance(p, Parameter) else self.get_lr()

    # -- functional core (override in subclasses) -----------------------
    def _init_state(self, p_val):
        return ()

    def _update(self, p, g, state, lr, step):
        raise NotImplementedError

    def _decoupled_decay_coeff(self):
        return 0.0

    # -- fused multi-tensor epilogue (ops/pallas/fused_update.py) --------
    def _fused_kind(self):
        """Kernel family this optimizer's update maps onto ("sgd" /
        "momentum" / "adam" / "adamw"), or None when only the per-leaf
        tree path can express it (Lars, Adamax, RMSProp, ...)."""
        return None

    def fused_spec(self):
        """Static hyperparameter dict for the fused multi-tensor update
        kernels, or None when this optimizer (or its current config)
        must take the per-leaf tree path."""
        kind = self._fused_kind()
        if kind is None or self._stochastic_rounding:
            return None
        spec = {"kind": kind,
                "n_moments": {"sgd": 0, "momentum": 1,
                              "adam": 2, "adamw": 2}[kind],
                "state_dtype": self._state_dtype,
                "wd": float(self._decoupled_decay_coeff() or 0.0)}
        if kind in ("adam", "adamw"):
            spec.update(beta1=float(self._beta1),
                        beta2=float(self._beta2),
                        eps=float(self._epsilon))
        elif kind == "momentum":
            spec.update(momentum=float(self._momentum),
                        nesterov=bool(self._nesterov))
        return spec

    def _decay_applies_name(self, name):
        """Per-leaf decoupled-decay decision for the jit/tree path,
        keyed by the flat param-tree name (AdamW apply_decay_param_fun;
        the eager path's _decay_applies uses Parameter.name instead)."""
        apply_fn = getattr(self, "_apply_decay_param_fun", None)
        return True if apply_fn is None else bool(apply_fn(name))

    # -- eager path -----------------------------------------------------
    def _ensure_state(self, p):
        if id(p) not in self._states:
            val = p.value
            master = val.astype(jnp.float32) if (
                self._multi_precision and val.dtype != jnp.float32) else None
            self._states[id(p)] = [self._init_state(
                val.astype(jnp.float32) if master is not None else val),
                master]
        return self._states[id(p)]

    @no_grad()
    def step(self):
        self._step_count += 1
        pg = [(p, p.grad) for p in self._parameters
              if p.grad is not None and p.trainable]
        # coupled regularization (L1/L2Decay): add dR/dw to the gradient,
        # per-param regularizer wins over the global one (reference
        # semantics: fluid/regularizer.py append_regularization_ops)
        fixed = []
        for p, g in pg:
            reg = p.regularizer if getattr(p, "regularizer", None) \
                is not None else self._regularization
            if isinstance(reg, WeightDecayRegularizer) and \
                    not isinstance(self, AdamW):
                g = Tensor(g.value + reg.grad_term(
                    p.value.astype(g.value.dtype)))
            fixed.append((p, g))
        pg = fixed
        if self._grad_clip is not None:
            pg = self._grad_clip(pg)
        for p, g in pg:
            state_box = self._ensure_state(p)
            state, master = state_box
            work = master if master is not None else p.value
            gval = g.value.astype(work.dtype)
            lr = self._lr_for(p)
            wd = self._decoupled_decay_coeff()
            if wd and self._decay_applies(p):
                work = work * (1.0 - lr * wd)
            new_p, new_state = self._update(work, gval, state, lr,
                                            self._step_count)
            state_box[0] = new_state
            if master is not None:
                state_box[1] = new_p
            # cast back: fp update math must not promote a bf16/fp16 param
            p.set_value(new_p.astype(p.value.dtype))

    def _decay_applies(self, p):
        apply_fn = getattr(self, "_apply_decay_param_fun", None)
        if apply_fn is None:
            return True
        return apply_fn(p.name)

    def clear_grad(self, set_to_zero=True):
        for p in self._parameters:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        import paddle_tpu as _pd
        if not _pd.in_dynamic_mode():
            # static graph: minimize DECLARES the objective — no update
            # happens at build time. Executor.run executes one optimizer
            # step per call (reference executor semantics): the hook
            # carries the build-time param slots so the updated values
            # can be synced back into the recorded tape for the next
            # replay.
            from ..static import default_main_program
            prog = default_main_program()
            if not any(o is loss for o in prog.outputs):  # identity, not
                prog.outputs.append(loss)                 # Tensor.__eq__
            if not self._parameters:
                self._parameters = list(prog._params)
            prog._train_hooks.append(
                (loss, self, [(p, p._slot) for p in self._parameters]))
            return None, [(p, None) for p in self._parameters]
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameters]

    def backward(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None, callbacks=None):
        """First half of the reference's split minimize (optimizer.py
        Optimizer.backward doc example): run autograd, return the
        (param, grad) pairs for a later apply_gradients call."""
        loss.backward()
        params = parameters if parameters is not None else self._parameters
        return [(p, p.grad) for p in params
                if p.grad is not None and p.trainable]

    def apply_gradients(self, params_grads):
        """Apply pre-computed (param, grad) pairs (reference
        optimizer.py apply_gradients): grads land on the params, then
        the normal step() path (regularizer, clip, state) runs."""
        from ..framework.core import Tensor as _T
        for p, g in params_grads:
            p.grad = g if isinstance(g, _T) or g is None else _T(g)
        self.step()

    # -- state dict ----------------------------------------------------
    def state_dict(self):
        out = {"step": self._step_count}
        for i, p in enumerate(self._parameters):
            if id(p) in self._states:
                state, master = self._states[id(p)]
                out[f"state_{i}"] = [Tensor(s) for s in state]
                if master is not None:
                    out[f"master_{i}"] = Tensor(master)
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("step", 0))
        for i, p in enumerate(self._parameters):
            key = f"state_{i}"
            if key in state_dict:
                state = tuple(t.value for t in state_dict[key])
                master = state_dict.get(f"master_{i}")
                self._states[id(p)] = [
                    state, master.value if master is not None else None]
        if "LR_Scheduler" in state_dict and isinstance(
                self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])

    set_dict = set_state_dict

    # -- functional API for the jit path --------------------------------
    def _f32_zeros(self, v):
        """Optimizer accumulators default to f32 regardless of param dtype
        — bf16 moments drop the (1-beta)*g increment once |m| >> |g|.
        _state_dtype=bf16 opts into half-size state; pair it with
        _stochastic_rounding so the dropped tail still accumulates in
        expectation."""
        return jnp.zeros(v.shape, self._state_dtype or jnp.float32)

    def init_leaf_state(self, v):
        """Per-param state for the jit/tree path. With multi_precision and
        a low-precision param this wraps the inner state with an f32
        master copy (reference: multi-precision adam,
        paddle/fluid/operators/optimizers/adam_op.h); apply_gradients_tree
        then updates the master and casts down only the working param."""
        if self._multi_precision and v.dtype != jnp.float32:
            vf = v.astype(jnp.float32)
            return {"master": vf, "state": self._init_state(vf)}
        return self._init_state(v)

    def init_tree_state(self, params_tree):
        import jax
        return jax.tree.map(self.init_leaf_state, params_tree,
                            is_leaf=lambda x: hasattr(x, "dtype"))

    def apply_gradients_tree(self, params_tree, grads_tree, state_tree, lr,
                             step, found_inf=None, decay_mask=None,
                             lr_scale=None):
        """Pure: returns (new_params, new_state). Call under jit.

        `decay_mask` / `lr_scale` are optional per-leaf metadata trees
        (same structure as params): decay_mask=False skips decoupled
        decay for that leaf (AdamW apply_decay_param_fun, threaded by
        TrainStep via _decay_applies_name), lr_scale multiplies the
        learning rate per leaf (Parameter.optimize_attr). Defaults (all
        True / 1.0) reproduce the historical tree-path numerics exactly.

        `found_inf` (a traced bool from GradScaler.jit_unscale_and_update)
        turns the whole update into a branchless skip: every param and
        state leaf keeps its old value when the step overflowed, so the
        fp16 loss-scaling semantics survive inside one donated XLA step
        with no host sync.

        Dtype-stable by construction: the update math runs in float32
        (bf16 moments/gradients would lose the (1-beta) tail), then the
        new parameter is cast back to the parameter's own dtype and each
        state leaf to its own dtype. Without the cast, `p - lr_t * m`
        silently promoted bf16 params to f32 after the first step — every
        subsequent matmul ran in f32 (~1/3 MXU rate)."""
        import jax
        wd = self._decoupled_decay_coeff()
        sr = self._stochastic_rounding
        if sr:
            base_key = jax.random.fold_in(
                jax.random.PRNGKey(0x5bd1e995),
                jnp.asarray(step, jnp.int32).reshape(()))

        def down(x32, dtype, key):
            """f32 -> low dtype, stochastically rounded when enabled."""
            if dtype == jnp.float32 or x32.dtype == dtype:
                return x32.astype(dtype)
            if sr and dtype == jnp.bfloat16:
                bits = jax.lax.bitcast_convert_type(
                    x32.astype(jnp.float32), jnp.uint32)
                r = jax.random.bits(key, x32.shape, jnp.uint32) \
                    & jnp.uint32(0xFFFF)
                return jax.lax.bitcast_convert_type(
                    (bits + r) & jnp.uint32(0xFFFF0000),
                    jnp.float32).astype(jnp.bfloat16)
            return x32.astype(dtype)

        def upd(p, g, s, idx, decay_on, lrs):
            # master-weight leaf (init_leaf_state, multi_precision): the
            # f32 master accumulates sub-bf16-ulp updates; the working
            # param is just its rounded shadow
            key = jax.random.fold_in(base_key, idx) if sr else None
            master = None
            if isinstance(s, dict) and "master" in s:
                master, s = s["master"], s["state"]
            w = master if master is not None else p.astype(jnp.float32)
            lr_leaf = lr if lrs is None else lr * lrs
            if wd and decay_on:
                w = w * (1.0 - lr_leaf * wd)
            np_, ns_ = self._update(w, g.astype(jnp.float32), s, lr_leaf,
                                    step)
            leaves = jax.tree.leaves(ns_)
            keys = (jax.random.split(jax.random.fold_in(key, 1),
                                     max(len(leaves), 1))
                    if sr else [None] * len(leaves))
            ki = iter(range(len(leaves)))
            ns_ = jax.tree.map(
                lambda a, b: down(a, b.dtype, keys[next(ki)])
                if hasattr(b, "dtype") else a,
                ns_, s)
            if master is not None:
                return np_.astype(p.dtype), {"master": np_, "state": ns_}
            return down(np_, p.dtype, key), ns_

        flat_p, treedef = jax.tree.flatten(params_tree)
        flat_g = treedef.flatten_up_to(grads_tree)
        flat_s = treedef.flatten_up_to(state_tree)
        flat_dm = treedef.flatten_up_to(decay_mask) \
            if decay_mask is not None else [True] * len(flat_p)
        flat_ls = treedef.flatten_up_to(lr_scale) \
            if lr_scale is not None else [None] * len(flat_p)
        new_p, new_s = [], []
        for i, (p, g, s) in enumerate(zip(flat_p, flat_g, flat_s)):
            np_, ns_ = upd(p, g, s, i, flat_dm[i],
                           None if flat_ls[i] is None
                           or float(flat_ls[i]) == 1.0 else flat_ls[i])
            if found_inf is not None:
                np_ = jnp.where(found_inf, p, np_)
                ns_ = jax.tree.map(
                    lambda new, old: jnp.where(found_inf, old, new)
                    if hasattr(old, "dtype") else new, ns_, s)
            new_p.append(np_)
            new_s.append(ns_)
        return treedef.unflatten(new_p), treedef.unflatten(new_s)


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _update(self, p, g, state, lr, step):
        return p - lr * g, state

    def _fused_kind(self):
        return "sgd"


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, v):
        return (self._f32_zeros(v),)

    def _update(self, p, g, state, lr, step):
        (vel,) = state
        vel = self._momentum * vel + g
        if self._nesterov:
            p = p - lr * (g + self._momentum * vel)
        else:
            p = p - lr * vel
        return p, (vel,)

    def _fused_kind(self):
        return "momentum"


class LarsMomentum(Momentum):
    """LARS (layer-wise adaptive rate scaling) momentum. Parity:
    fluid/optimizer.py LarsMomentumOptimizer / fleet meta_optimizers/
    lars_optimizer.py. local_lr = lr * coeff * ||w|| /
    (||g|| + lars_weight_decay * ||w|| + epsilon), per parameter."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, epsilon=1e-9,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, momentum, parameters,
                         False, None, grad_clip, multi_precision,
                         1.0, name)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon

    def _update(self, p, g, state, lr, step):
        (vel,) = state
        pf = p.astype(jnp.float32)
        gf = g.astype(jnp.float32)
        w_norm = jnp.sqrt(jnp.sum(pf * pf))
        g_norm = jnp.sqrt(jnp.sum(gf * gf))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            lr * self._lars_coeff * w_norm /
            (g_norm + self._lars_wd * w_norm + self._eps),
            lr)
        vel = self._momentum * vel + local_lr * (
            gf + self._lars_wd * pf).astype(vel.dtype)
        return (pf - vel.astype(jnp.float32)).astype(p.dtype), (vel,)

    def _fused_kind(self):
        return None  # per-leaf norms: tree path only


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        # reference accepts Tensor betas (adamw.py doc example); the
        # update math is jnp — coerce to python floats
        self._beta1 = float(beta1) if hasattr(beta1, "numpy") else beta1
        self._beta2 = float(beta2) if hasattr(beta2, "numpy") else beta2
        self._epsilon = float(epsilon) if hasattr(epsilon, "numpy") \
            else epsilon

    def _init_state(self, v):
        return (self._f32_zeros(v), self._f32_zeros(v))

    def _update(self, p, g, state, lr, step):
        m, v = state
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * (1 - b2 ** step) ** 0.5 / (1 - b1 ** step)
        p = p - lr_t * m / (jnp.sqrt(v) + eps)
        return p, (m, v)

    def _fused_kind(self):
        return "adam"


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._coeff = weight_decay if isinstance(weight_decay, float) \
            else 0.01
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled_decay_coeff(self):
        return self._coeff

    def _fused_kind(self):
        return "adamw"


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        # betas may arrive as Tensors (reference adamax.py doc example)
        self._beta1 = float(beta1) if hasattr(beta1, "numpy") else beta1
        self._beta2 = float(beta2) if hasattr(beta2, "numpy") else beta2
        self._epsilon = epsilon

    def _init_state(self, v):
        return (self._f32_zeros(v), self._f32_zeros(v))

    def _update(self, p, g, state, lr, step):
        m, u = state
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * m + (1 - b1) * g
        u = jnp.maximum(b2 * u, jnp.abs(g))
        p = p - lr / (1 - b1 ** step) * m / (u + eps)
        return p, (m, u)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, v):
        return (jnp.full(v.shape, self._init_acc, jnp.float32),)

    def _update(self, p, g, state, lr, step):
        (acc,) = state
        acc = acc + g * g
        p = p - lr * g / (jnp.sqrt(acc) + self._epsilon)
        return p, (acc,)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._epsilon, self._rho = epsilon, rho

    def _init_state(self, v):
        return (self._f32_zeros(v), self._f32_zeros(v))

    def _update(self, p, g, state, lr, step):
        acc_g, acc_x = state
        rho, eps = self._rho, self._epsilon
        acc_g = rho * acc_g + (1 - rho) * g * g
        upd = jnp.sqrt(acc_x + eps) / jnp.sqrt(acc_g + eps) * g
        acc_x = rho * acc_x + (1 - rho) * upd * upd
        return p - lr * upd, (acc_g, acc_x)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, v):
        return (self._f32_zeros(v), self._f32_zeros(v), self._f32_zeros(v))

    def _update(self, p, g, state, lr, step):
        ms, mg, mom = state
        rho, eps = self._rho, self._epsilon
        ms = rho * ms + (1 - rho) * g * g
        if self._centered:
            mg = rho * mg + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + eps)
        else:
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * mom + lr * g / denom
        return p - mom, (ms, mg, mom)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-06, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, False,
                         name)
        self._wd = lamb_weight_decay
        self._beta1 = float(beta1) if hasattr(beta1, "numpy") else beta1
        self._beta2 = float(beta2) if hasattr(beta2, "numpy") else beta2
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, v):
        return (self._f32_zeros(v), self._f32_zeros(v))

    def _update(self, p, g, state, lr, step):
        m, v = state
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** step)
        v_hat = v / (1 - b2 ** step)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + self._wd * p
        p_norm = jnp.sqrt(jnp.sum(p * p))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        ratio = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return p - lr * ratio * r, (m, v)
