#!/usr/bin/env python
"""Merge per-rank Chrome trace files into ONE timeline.

A `paddle_tpu.distributed.launch` run yields one trace file per rank
(each rank calls `Profiler.export_chrome_tracing(...)`, or the operator
pulls them from per-rank debug bundles). Every file's events are
pid-tagged with that rank, and timestamps are unix-epoch microseconds
(same host ⇒ same clock), so merging is: concatenate, de-conflict pids,
sort. The merged file opens in Perfetto with one process group per rank
— the standard way to see a multi-process stall: which rank's step track
stretched while the others waited at the collective.

Usage:
    python tools/merge_traces.py -o merged.json rank0.json rank1.json ...
    python tools/merge_traces.py -o merged.json trace_dir/   # *.json in dir

Exit 0 on success; 2 on unreadable/invalid inputs.
"""
import argparse
import glob
import json
import os
import sys


def load_events(path):
    """A trace file's event list (object format {"traceEvents": [...]}
    or the bare-array format chrome also accepts)."""
    with open(path) as f:
        payload = json.load(f)
    if isinstance(payload, dict):
        events = payload.get("traceEvents")
        if not isinstance(events, list):
            raise ValueError(f"{path}: no traceEvents array")
        return events
    if isinstance(payload, list):
        return payload
    raise ValueError(f"{path}: not a Chrome trace (object or array)")


def merge(event_lists, labels=None):
    """One sorted event list; colliding pids across files are remapped
    (two single-process traces both claim pid 0 = rank 0) and every
    process keeps/gains a process_name so tracks stay attributable."""
    used_pids = set()
    merged = []
    for i, events in enumerate(event_lists):
        pids = {e.get("pid", 0) for e in events}
        remap = {}
        for p in sorted(pids, key=lambda x: str(x)):
            q = p
            while q in used_pids:
                q = (q if isinstance(q, int) else 0) + 1000 + i
            remap[p] = q
            used_pids.add(q)
        named = set()
        for e in events:
            e = dict(e)
            e["pid"] = remap.get(e.get("pid", 0), e.get("pid", 0))
            if e.get("ph") == "M" and e.get("name") == "process_name":
                named.add(e["pid"])
            merged.append(e)
        for p in sorted(remap.values(), key=str):
            if p not in named:
                label = labels[i] if labels and i < len(labels) else \
                    f"trace {i}"
                merged.append({"ph": "M", "name": "process_name",
                               "pid": p, "tid": 0, "ts": 0,
                               "args": {"name": label}})
    # metadata (ph M) leads; everything else in timestamp order — the
    # "sorted ts per track" property tools/check_metrics_schema.py lints
    merged.sort(key=lambda e: (0 if e.get("ph") == "M" else 1,
                               float(e.get("ts", 0))))
    return merged


def expand_inputs(inputs):
    paths = []
    for p in inputs:
        if os.path.isdir(p):
            paths.extend(sorted(glob.glob(os.path.join(p, "*.json"))))
        else:
            paths.append(p)
    return paths


def main(argv=None):
    ap = argparse.ArgumentParser(
        "merge_traces", description="merge per-rank Chrome trace files")
    ap.add_argument("-o", "--output", required=True)
    ap.add_argument("inputs", nargs="+",
                    help="trace files, or directories of *.json")
    args = ap.parse_args(argv)
    paths = expand_inputs(args.inputs)
    if not paths:
        print("merge_traces: no input trace files", file=sys.stderr)
        return 2
    lists = []
    for p in paths:
        try:
            lists.append(load_events(p))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"merge_traces: {e}", file=sys.stderr)
            return 2
    merged = merge(lists, labels=[os.path.basename(p) for p in paths])
    out = os.path.abspath(args.output)
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump({"traceEvents": merged, "displayTimeUnit": "ms",
                   "otherData": {"merged_from": paths}}, f)
    print(f"merged {len(paths)} trace(s), {len(merged)} events -> "
          f"{args.output}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
