"""Flash attention for TPU in Pallas.

Replaces the reference's fused attention CUDA kernels
(paddle/fluid/operators/fused/fused_attention_op.cu) and the O(T^2)-memory
XLA composition: attention is computed blockwise in VMEM with an online
softmax, so the [T, T] probability matrix never hits HBM. Backward is the
standard two-pass flash backward (dq pass, then dk/dv pass) via
jax.custom_vjp, accumulating in fp32 scratch.

Layout contract: q, k, v are [batch, seq, heads, head_dim] (paddle
incubate fused-attention layout); internally we fold to [B*H, T, D].
Causal masking is applied per-block; fully-masked blocks are skipped.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import I0, NEG_INF  # noqa: F401


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                l_ref, *, scale, causal, block_q, block_k, seq_k):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, jnp.float32(NEG_INF))
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _body():
        q = q_ref[0].astype(jnp.float32)          # [bq, d]
        k = k_ref[0].astype(jnp.float32)          # [bk, d]
        v = v_ref[0].astype(jnp.float32)          # [bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * jnp.float32(scale)                 # [bq, bk]
        if causal:
            rows = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, jnp.float32(NEG_INF))

        m_prev = m_ref[:]                          # [bq]
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal:
        # skip blocks strictly above the diagonal band
        @pl.when(ik * block_k <= (iq + 1) * block_q - 1)
        def _run():
            _body()
    else:
        _body()

    @pl.when(ik == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:], jnp.float32(1e-30))
        o_ref[0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[:] + jnp.log(l)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale, causal, block_q, block_k):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * jnp.float32(scale)
        if causal:
            rows = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ik * block_k <= (iq + 1) * block_q - 1)
        def _run():
            _body()
    else:
        _body()

    @pl.when(ik == nk - 1)
    def _fin():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_acc, dv_acc, *, scale, causal, block_q,
                block_k):
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * jnp.float32(scale)
        if causal:
            rows = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse[:, None])                       # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bk, d]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)               # [bq, bk]
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bk, d]

    if causal:
        @pl.when(ik * block_k <= (iq + 1) * block_q - 1)
        def _run():
            _body()
    else:
        _body()

    @pl.when(iq == nq - 1)
    def _fin():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _choose_blocks(t_q, t_k, d):
    # Biggest blocks win decisively on real TPU (measured on
    # [128,1024,64] bf16: 1024x1024 runs fwd 1.9x / fwd+bwd 1.5x faster
    # than 512x512; small bk is the worst axis to shrink). 1024x1024
    # puts the f32 [bq, bk] score+prob tiles at ~8 MB of VMEM — about
    # the ceiling once q/k/v/do/acc tiles are added, so the cap is the
    # VMEM budget; round down to divisors of the seq lens.
    # the dkv backward holds ~3 concurrent f32 [bq, bk] tiles plus
    # q/k/v/do tiles that scale with d — shrink bk for head dims > 64
    # to stay inside the same budget the d=64 measurement validated
    bq = min(1024, t_q)
    while t_q % bq:
        bq //= 2
    # round the bk seed DOWN to a power of two first: for d=96/80 the
    # VMEM-budget quotient (682/819) is not a power of two, and the
    # halving loop would otherwise never land on a divisor of a
    # power-of-two t_k until bk collapsed to 1
    seed = 1024 * 64 // max(d, 64)
    seed = 1 << (seed.bit_length() - 1)
    bk = min(seed, t_k)
    while t_k % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, scale, interpret):
    out, _ = _flash_fwd_impl(q, k, v, causal, scale, interpret)
    return out


def _flash_fwd_impl(q, k, v, causal, scale, interpret):
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    bq, bk = _choose_blocks(Tq, Tk, D)
    grid = (BH, Tq // bq, Tk // bk)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, seq_k=Tk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, I0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, I0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, I0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, I0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, I0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
            # lse kept [BH, 1, Tq]: trailing block dims (1, bq) satisfy the
            # TPU (8, 128) tiling rule, which a [BH, Tq] layout cannot
            jax.ShapeDtypeStruct((BH, 1, Tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _flash_fwd(q, k, v, causal, scale, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, scale, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, interpret, res, dout):
    q, k, v, out, lse = res
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    bq, bk = _choose_blocks(Tq, Tk, D)
    delta = jnp.sum(out.astype(jnp.float32) * dout.astype(jnp.float32),
                    axis=-1)[:, None, :]  # [BH, 1, Tq]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(BH, Tq // bq, Tk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, I0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, I0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, I0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, I0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, I0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, I0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, I0)),
        out_shape=jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(BH, Tk // bk, Tq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, I0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, I0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, I0)),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, I0)),
            pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, I0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, I0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, I0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Tk, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_arrays(q, k, v, causal=False, scale=None,
                           interpret=False):
    """Array-level entry: q,k,v [B, T, H, D] → out [B, T, H, D]."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    fold = lambda x: jnp.swapaxes(x, 1, 2).reshape(B * H, x.shape[1], D)
    out = _flash(fold(q), fold(k), fold(v), causal, float(scale), interpret)
    return jnp.swapaxes(out.reshape(B, H, Tq, D), 1, 2)


def flash_attention(q, k, v, causal=False, scale=None, interpret=None):
    """Tensor-level entry used by F.scaled_dot_product_attention."""
    from ...framework.core import apply_op
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return apply_op(
        lambda qa, ka, va: flash_attention_arrays(
            qa, ka, va, causal=causal, scale=scale, interpret=interpret),
        q, k, v)
