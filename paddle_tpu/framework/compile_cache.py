"""Persistent XLA compilation cache — framework-level wiring.

The cold XLA compile of a real training step (60 s+ for the GPT-medium
bench config; minutes at 1.3B) dominates every short-lived process:
benchmarks, preemption restarts, eval jobs, CI. JAX ships a persistent
on-disk compilation cache keyed by the HLO fingerprint; this module turns
it on for the WHOLE framework at import time, so every
`paddle_tpu.jit`/`static.Executor`/`HybridTrainStep` compile in any
process is written to (and reloaded from) disk. A warm process skips the
cold compile entirely.

Environment knobs (documented in docs/PERFORMANCE.md):

  PADDLE_TPU_COMPILE_CACHE        cache directory; "0"/"off"/"none"
                                  disables. Default:
                                  ~/.cache/paddle_tpu/xla_cache
  PADDLE_TPU_CACHE_MIN_COMPILE_SECS  only cache compiles slower than this
                                  (default 0: cache everything — a bench
                                  or trainer wants every entry warm)
  PADDLE_TPU_CACHE_MIN_ENTRY_BYTES   skip entries smaller than this
                                  (default 0)

The cache is an optimization, never a blocker: any failure to configure
it (read-only filesystem, old jaxlib) leaves the framework fully
functional with cold compiles.

Beyond the on-at-import wiring, this module owns two more cache
concerns:

- **per-compile hit/miss attribution** (`observe_compile`): jax emits
  `/jax/compilation_cache/cache_hits` / `cache_misses` monitoring
  events ON THE COMPILING THREAD, so a thread-local listener attributes
  a hit to exactly the compile that got it — correct even when the
  background warm executor (jit/warm.py) overlaps many compiles, where
  the old entry-set diff around each compile could blame one compile's
  new on-disk entry on another's window.

- **pack / seed** (`pack`, `seed_from`, tools/seed_compile_cache.py):
  a compiled cache directory is a portable artifact — pack one on any
  machine that has paid the cold compile, seed it into a fresh
  machine/process, and the first train step loads instead of compiling
  (the warm-start-across-processes reuse of arxiv 2412.14374). bench.py
  seeds from `BENCH_CACHE_SEED` when set.
"""
import json
import os
import shutil
import threading
import time

import jax

__all__ = ["enable_compile_cache", "disable_compile_cache", "cache_dir",
           "DEFAULT_CACHE_DIR", "pack", "seed_from", "observe_compile",
           "PACK_SCHEMA"]

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "paddle_tpu", "xla_cache")

_OFF_VALUES = ("0", "off", "none", "false", "disabled")

_state = {"dir": None}


def cache_dir():
    """The active cache directory, or None when the cache is disabled."""
    return _state["dir"]


def enable_compile_cache(path=None):
    """Point JAX's persistent compilation cache at `path` (or the
    PADDLE_TPU_COMPILE_CACHE env var, or the default user-cache dir).

    Idempotent; safe to call before or after backend init (the config is
    consulted at compile time). Returns the active directory, or None
    when disabled/unavailable. An explicit `path` wins over the env var;
    with neither, a cache dir some earlier caller already configured on
    jax (e.g. bench.py's child before importing the framework) is kept
    rather than clobbered.
    """
    env = os.environ.get("PADDLE_TPU_COMPILE_CACHE", "")
    if path is None:
        path = env or None
    if path is None:
        # respect a dir configured directly on jax before framework import
        try:
            existing = jax.config.jax_compilation_cache_dir
        except AttributeError:
            existing = None
        if existing:
            _state["dir"] = existing
            return existing
        path = DEFAULT_CACHE_DIR
    if str(path).strip().lower() in _OFF_VALUES:
        _state["dir"] = None
        return None
    path = os.path.abspath(os.path.expanduser(str(path)))
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(os.environ.get("PADDLE_TPU_CACHE_MIN_COMPILE_SECS", "0")))
        jax.config.update(
            "jax_persistent_cache_min_entry_size_bytes",
            int(os.environ.get("PADDLE_TPU_CACHE_MIN_ENTRY_BYTES", "0")))
        _make_keys_portable()
    except Exception:
        _state["dir"] = None
        return None
    _state["dir"] = path
    return path


def _make_keys_portable():
    """Make cache keys independent of the cache DIRECTORY PATH, so a
    packed artifact seeds any machine. jax >= 0.4.36 plants
    GPU-oriented sub-caches (xla_gpu_kernel_cache_file,
    xla_gpu_per_fusion_autotune_cache_dir) INSIDE the compilation cache
    dir and — in this jaxlib — fails to strip those debug options from
    the cache key, so the key hashes the absolute cache path: the same
    program compiled under ~/.cache and under ./xla_cache gets two
    different keys, and a seeded directory can never hit (measured on
    this container: a byte-identical seeded cache recompiled from
    cold). Those sub-caches do nothing on TPU/CPU, so default them OFF;
    PADDLE_TPU_CACHE_XLA_CACHES overrides (jax's values: "all", "none",
    or a comma list of the flag names)."""
    try:
        jax.config.update(
            "jax_persistent_cache_enable_xla_caches",
            os.environ.get("PADDLE_TPU_CACHE_XLA_CACHES", "none"))
    except Exception:
        pass  # older jax: no sub-caches, keys already portable


def disable_compile_cache():
    """Turn the persistent cache off for this process."""
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        pass
    _state["dir"] = None


def cache_entry_count():
    """Number of entries currently on disk (0 when disabled/empty)."""
    return len(cache_entry_names())


def cache_entry_names():
    """The on-disk entry names as a frozenset (empty when disabled).
    Per-compile hit/miss attribution goes through `observe_compile`
    below (thread-local jax cache events + a claimed-entries ledger —
    exact under the background warm executor); this raw set remains the
    building block and the whole-process view tests diff."""
    d = _state["dir"]
    if not d or not os.path.isdir(d):
        return frozenset()
    try:
        return frozenset(n for n in os.listdir(d)
                         if not n.startswith(".")
                         and n not in _NON_ENTRY_FILES)
    except OSError:
        return frozenset()


# files that may live in a cache dir without being cache entries
_NON_ENTRY_FILES = frozenset(["bench_state.json", "MANIFEST.json"])


# -- per-compile hit/miss attribution ------------------------------------
#
# jax's compiler emits monitoring events on the thread running the
# compile; a thread-local slot therefore attributes hits/misses to
# exactly one compile even when the warm executor overlaps many.
# The on-disk entry-name diff stays as the `cache_entries_added` count,
# made overlap-safe by a claimed-entries ledger: each new entry is
# counted by at most one compile, and a compile the events called a HIT
# never claims (it wrote nothing — any entry in its window belongs to a
# concurrent miss).

_tls = threading.local()
_attr_lock = threading.Lock()
_claimed = set()           # entry names already attributed to a compile
_listener_state = {"installed": False, "ok": False}

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def _on_cache_event(event, **kwargs):
    slot = getattr(_tls, "slot", None)
    if slot is None:
        return
    if event == _HIT_EVENT:
        slot["hit"] = True
        slot["seen"] = True
    elif event == _MISS_EVENT:
        slot["seen"] = True


def _install_listener():
    if _listener_state["installed"]:
        return _listener_state["ok"]
    with _attr_lock:
        if _listener_state["installed"]:
            return _listener_state["ok"]
        try:
            from jax._src import monitoring as _mon
            _mon.register_event_listener(_on_cache_event)
            _listener_state["ok"] = True
        except Exception:
            _listener_state["ok"] = False
        _listener_state["installed"] = True
    return _listener_state["ok"]


class _CompileObservation:
    """Result slot of one `observe_compile()` window: `cache_hit`
    (exact, event-attributed when the listener is available) and
    `entries_added` (names this compile may claim; counts shift between
    overlapping misses only, totals stay exact, hits always claim 0)."""

    def __init__(self):
        self.cache_on = False
        self.cache_hit = False
        self.entries_added = frozenset()


class observe_compile:
    """Context manager wrapping ONE compile on the current thread:

        with observe_compile() as obs:
            compiled = lowered.compile()
        obs.cache_hit, obs.entries_added

    Hit/miss comes from jax's own per-thread cache events (exact under
    the background warm executor); the entry diff is serialized through
    a claimed-set so two overlapping compiles never double-count (or
    cross-claim after a hit) the entries they add. Nested use attributes
    to the innermost window. Never raises: with no listener and no
    cache dir it degrades to a no-op observation.

    Known limit of the NO-LISTENER fallback (a future jax renaming the
    events): hit/miss reverts to the window diff, which under
    overlapping compiles can let a hit whose window swallowed a
    concurrent miss's entry claim it — flipping both labels. The
    listener path (every jax this repo supports today) has no such
    race; the fallback only ever regresses to the pre-pipeline
    behavior, never worse."""

    def __enter__(self):
        self.obs = _CompileObservation()
        self.obs.cache_on = cache_dir() is not None
        self._listener = _install_listener() if self.obs.cache_on \
            else False
        self._before = cache_entry_names() if self.obs.cache_on \
            else frozenset()
        self._slot = {"hit": False, "seen": False}
        self._prev = getattr(_tls, "slot", None)
        _tls.slot = self._slot
        return self.obs

    def __exit__(self, exc_type, exc, tb):
        _tls.slot = self._prev
        if not self.obs.cache_on:
            return False
        after = cache_entry_names()
        with _attr_lock:
            added = after - self._before - frozenset(_claimed)
            if self._listener and self._slot["hit"]:
                added = frozenset()  # a hit wrote nothing; leave any
                # window entries for the concurrent miss that did
            else:
                _claimed.update(added)
        self.obs.entries_added = added
        if self._listener and self._slot["seen"]:
            self.obs.cache_hit = self._slot["hit"]
        else:
            # listener unavailable (future jax) or cache never consulted
            # (e.g. a sub-jaxpr compile path): fall back to the diff
            self.obs.cache_hit = not added
        return False


# -- pack / seed ---------------------------------------------------------

PACK_SCHEMA = "paddle_tpu.compile_cache_pack.v1"


def pack(dest, source=None):
    """Copy the cache's entries into `dest` as a portable seed artifact
    (entry files + MANIFEST.json naming them). `source` defaults to the
    active cache dir. Returns {"path", "entries", "bytes"}; raises
    ValueError when there is no cache to pack — packing is an explicit
    operator action (tools/seed_compile_cache.py), not best-effort
    telemetry."""
    src = source or cache_dir()
    if not src or not os.path.isdir(src):
        raise ValueError(
            "no compile cache to pack — enable_compile_cache() first or "
            f"pass source= (got {src!r})")
    dest = os.path.abspath(os.path.expanduser(str(dest)))
    os.makedirs(dest, exist_ok=True)
    names, total = [], 0
    for n in sorted(os.listdir(src)):
        if n.startswith(".") or n in _NON_ENTRY_FILES:
            continue
        p = os.path.join(src, n)
        if not os.path.isfile(p):
            continue
        shutil.copy2(p, os.path.join(dest, n))
        names.append(n)
        total += os.path.getsize(p)
    manifest = {"schema": PACK_SCHEMA, "entries": names,
                "total_bytes": total, "jax": jax.__version__,
                "packed_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime())}
    with open(os.path.join(dest, "MANIFEST.json"), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")
    return {"path": dest, "entries": len(names), "bytes": total}


def copy_seed_entries(source, dest):
    """The pure-file half of seeding (no jax/framework state): copy the
    cache entries of `source` (a pack() artifact or a raw cache dir)
    into `dest`, skipping entries already present. Returns
    (seeded, skipped). NOTE: bench.py's PARENT process deliberately
    re-implements this loop (bench._seed_cache) instead of importing it
    — this module imports jax at module top, and the parent stays
    jax-free by contract; keep the two skip-lists (_NON_ENTRY_FILES
    here, the inline tuple there) in sync when adding non-entry
    files."""
    os.makedirs(dest, exist_ok=True)
    seeded = skipped = 0
    for n in sorted(os.listdir(source)):
        if n.startswith(".") or n in _NON_ENTRY_FILES:
            continue
        sp = os.path.join(source, n)
        if not os.path.isfile(sp):
            continue
        dp = os.path.join(dest, n)
        if os.path.exists(dp):
            skipped += 1
            continue
        shutil.copy2(sp, dp)
        seeded += 1
    return seeded, skipped


def seed_from(source, dest=None):
    """Pre-populate the persistent cache from a donated artifact dir (a
    `pack()` output or any raw cache dir): every entry not already
    present is copied in, so the process's first compiles load instead
    of compiling. Enables the cache (at `dest` when given) if it is not
    already on. Emits one `kind:"seed"` metrics record + the
    `warm.seeded_entries` counter. Returns {"source", "cache_dir",
    "seeded", "skipped"}; raises ValueError on a missing source —
    a requested seed that silently does nothing would fake a warm
    start."""
    source = os.path.abspath(os.path.expanduser(str(source)))
    if not os.path.isdir(source):
        raise ValueError(f"seed source {source!r} is not a directory")
    d = cache_dir()
    if dest is not None or d is None:
        d = enable_compile_cache(dest)
    if d is None:
        raise ValueError("persistent compile cache unavailable — "
                         "cannot seed")
    seeded, skipped = copy_seed_entries(source, d)
    rec = {"source": source, "cache_dir": d, "entries_seeded": seeded,
           "entries_skipped": skipped}
    try:  # telemetry never blocks seeding
        from ..profiler import monitor as _monitor
        _monitor.counter("warm.seeded_entries").inc(seeded)
        _monitor.export_step(dict(rec), kind="seed")
    except Exception:
        pass
    return rec
