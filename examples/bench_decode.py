"""Decode-throughput benchmark: static-cache `generate()` on GPT-medium.

Two compiled programs regardless of length (prefill + scanned decode);
sampling (top-k) runs on device inside the scan. Through a remote/
tunneled TPU only a data fetch is a true barrier, hence the np.asarray.

Measured on a v5e-class chip (355M params, bf16, prompt 32, 128 new;
top-k threshold via lax.approx_max_k — 29x faster than exact top_k over
the 50k vocab):
  batch  1:  ~680 tok/s  (1.5 ms/token — weight-bandwidth bound)
  batch  8: ~2200 tok/s
  batch 32: ~3300 tok/s
For ragged many-request serving use `GPTForCausalLM.paged_decode_step`
(continuous batching over a shared paged KV pool) instead.
"""
import json
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_medium, gpt_tiny


def main():
    import jax
    on_tpu = jax.default_backend() == "tpu"
    cfg = gpt_medium() if on_tpu else gpt_tiny()
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    model.eval()
    rng = np.random.RandomState(0)
    batches = (1, 8, 32) if on_tpu else (2,)
    prompt, new = (32, 128) if on_tpu else (8, 8)
    for B in batches:
        ids = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (B, prompt)).astype(np.int32))
        t0 = time.perf_counter()
        out = model.generate(ids, max_new_tokens=new, top_k=50)
        np.asarray(out.value)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = model.generate(ids, max_new_tokens=new, top_k=50)
        np.asarray(out.value)
        dt = time.perf_counter() - t0
        # dt covers prefill + all decode steps; with a short prompt the
        # prefill share is negligible, but the metric is end-to-end
        print(json.dumps({
            "batch": B, "prompt": prompt, "new": new,
            "compile_s": round(compile_s, 1),
            "decode_tok_per_s": round(B * new / dt, 1),
            "e2e_ms_per_new_token": round(dt / new * 1e3, 2)}), flush=True)


if __name__ == "__main__":
    main()
