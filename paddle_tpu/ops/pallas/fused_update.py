"""Fused multi-tensor optimizer-update epilogue (Pallas, TPU-native).

The train-step epilogue — unscale, global-norm clip, decoupled decay,
moment update, master-weight downcast — is classically emitted as a
per-leaf op chain: for an L-layer model that is hundreds of tiny HLO ops
XLA cannot always fuse across leaf boundaries (*Operator Fusion in XLA*,
arxiv 2301.13062), inflating both bytes-accessed per step and compile
seconds. This module is the multi-tensor fix, scheduled as locality-aware
passes over contiguous buffers (the *Neptune* pattern, arxiv 2510.08726):

- Parameters, gradients, moments, and f32 master weights live in
  **dtype-bucketed flat buffers** (`BucketLayout`): one exact-sized
  buffer per (dtype, scan-group run) — the members of a run (same role
  across the layer stack, e.g. every layer's qkv weight) pack densely
  in layer order. The model's forward consumes cheap slice views
  (`unpack`); a scan-over-layers model's per-step `jnp.stack` of block
  weights folds onto the run buffer (a free reshape, not a gather),
  and the stacked gradient its backward emits folds straight back into
  the run's gradient buffer through `unpack`'s custom VJP (one stack
  per run — not a pad+add chain per leaf, and no concat traffic for
  scan groups).
- **Pass 1** (`_pass1_math`) fuses gradient unscaling with per-chunk L2
  partial sums and a non-finite sweep: ONE read of the grads yields the
  unscaled buffer, the global grad norm, and found_inf. The norm is
  shared three ways by the caller — GradScaler found_inf handling, the
  clip factor, and the health vector's grad_norm.
- **Pass 2** (`_pass2_math`) applies clip factor + decoupled weight
  decay + the moment update (AdamW/Adam/Momentum/SGD) + the
  master-weight downcast in one sweep, with the found_inf skip folded
  in as a select and optional health statistics (param norm, update
  norm) accumulated on the side.

Per-leaf metadata — lr scale, decay-applies, need-clip, and the norm
weight hybrid sharding uses to de-duplicate replicated leaves — rides as
scalar-prefetched arrays (`pltpu.PrefetchScalarGridSpec`): the kernel
looks its leaf up through the chunk->leaf offset table, so chunks never
carry per-element metadata. Stores are exact-sized (padding to the
kernel chunk grid exists only transiently at the Pallas call boundary),
and a run-bucket's metadata is uniform by construction, so the off-TPU
path resolves it to python-static decisions per bucket.

Execution modes (`FusedEpilogue`): on TPU the passes run as real Pallas
kernels (per-chunk grid, buffers aliased in place via
input_output_aliases to compose with the step's donation). Off-TPU the
SAME `_math` bodies run directly on the whole flat buffers — XLA:CPU
then fuses them like any elementwise graph, so tier-1 proves the
identical update math, and `PADDLE_TPU_FUSED_INTERPRET=1` (or
interpret=True) additionally routes CPU through Pallas interpret mode
so the kernel plumbing itself — grid, BlockSpecs, scalar prefetch,
offset-table lookups — is exercised by tests too.
"""
import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import I0

__all__ = ["BucketLayout", "FusedEpilogue", "default_chunk"]

# per-leaf metadata bit flags (leaf_flags i32 scalar-prefetch array)
FLAG_NEED_CLIP = 1
FLAG_DECAY = 2

_F32 = jnp.float32


def default_chunk():
    """Elements per kernel chunk (leaf alignment + TPU block width).
    One lane row (128) keeps per-leaf padding negligible even for toy
    models; real models see ~0 relative padding at any setting."""
    return int(os.environ.get("PADDLE_TPU_FUSED_CHUNK", "128"))


def _scan_group_order(named_leaves):
    """Reorder leaves so same-role leaves across a layer stack sit
    ADJACENTLY in layer order: "h.0.qkv", "h.1.qkv", ... become one
    contiguous region. This is what lets a scan-over-layers model's
    per-step `jnp.stack([h.0.qkv, h.1.qkv, ...])` fold into a FREE
    reshape of one contiguous slice (XLA folds a concat of adjacent
    ascending slices) instead of a gather/copy of every block weight —
    the flat layout turns the scan path's stacking cost into zero.
    Grouping key: the leaf name with its last integer path component
    wildcarded, plus shape+dtype (stacking requires homogeneity)."""
    groups = {}
    entries = []
    for pos, (name, shape, dtype) in enumerate(named_leaves):
        parts = str(name).split(".")
        idx = 0
        gparts = parts
        for j in range(len(parts) - 1, -1, -1):
            if parts[j].isdigit():
                idx = int(parts[j])
                gparts = parts[:j] + ["*"] + parts[j + 1:]
                break
        gkey = (".".join(gparts), tuple(shape), str(jnp.dtype(dtype)))
        if gkey not in groups:
            groups[gkey] = len(groups)
        entries.append((groups[gkey], idx, pos, (name, shape, dtype)))
    entries.sort(key=lambda t: (t[0], t[1], t[2]))
    return [(e[0], e[3]) for e in entries]


class _Leaf:
    """One flat slice of a bucket: name + shape + [start, start+size)."""
    __slots__ = ("name", "shape", "size", "start", "index")

    def __init__(self, name, shape, size, start, index):
        self.name = name
        self.shape = tuple(shape)
        self.size = size
        self.start = start          # element offset into the flat bucket
        self.index = index          # row in the per-leaf metadata arrays


class _Bucket:
    """One (dtype, scan-group run) flat buffer: the run's members (same
    role across the layer stack, same metadata) pack back-to-back in
    layer order so a stacked view is one contiguous — free — reshape.
    Exact-sized; the Pallas drivers pad to the chunk grid transiently."""
    __slots__ = ("dtype", "leaves", "chunk", "n_chunks", "total",
                 "chunk_leaf", "cursor", "last_group")

    def __init__(self, dtype, chunk):
        self.dtype = dtype
        self.leaves = []
        self.chunk = chunk
        self.n_chunks = 0
        self.total = 0
        self.cursor = 0
        self.last_group = None      # (group_id, meta) of previous leaf
        self.chunk_leaf = None      # np.int32 [n_chunks] -> leaf.index


class BucketLayout:
    """Static description of the dtype-bucketed flat layout for one
    parameter tree, plus the per-leaf metadata tables the kernels
    prefetch. Built once at TrainStep construction; everything here is
    host-side numpy, nothing traced."""

    def __init__(self, named_leaves, chunk=None, meta=None):
        """named_leaves: ordered [(name, shape, dtype)]. meta: optional
        {name: {"need_clip": bool, "decay": bool, "lr_scale": float,
        "norm_weight": float}} — missing names/keys default to
        (True, True, 1.0, 1.0), which reproduces the tree path."""
        self.chunk = int(chunk or default_chunk())
        meta = meta or {}
        # ONE bucket per (dtype, scan-group run, metadata class): the
        # run's members (same role across the layer stack) pack densely
        # in layer order, so the scan path's per-step jnp.stack of
        # block weights folds onto the buffer (free view) and — the
        # mirror image — the stacked gradient the scan's backward emits
        # IS the run's gradient buffer, no concat/pack traffic at all.
        # Still dtype-bucketed (a run is dtype-homogeneous); a run is
        # the contiguity unit the multi-tensor kernels sweep.
        self.buckets = {}           # "dtype#run" -> _Bucket
        self.leaf_order = []        # (bucket_key, _Leaf) in layout order
        self._by_name = {}
        flags, lr_scale, norm_w = [], [], []
        prev = None
        for gid, (name, shape, dtype) in _scan_group_order(named_leaves):
            dt = jnp.dtype(dtype)
            size = int(np.prod(shape)) if shape else 1
            m = meta.get(name, {})
            mtup = (
                (FLAG_NEED_CLIP if m.get("need_clip", True) else 0)
                | (FLAG_DECAY if m.get("decay", True) else 0),
                float(m.get("lr_scale", 1.0)),
                float(m.get("norm_weight", 1.0)))
            if prev != (gid, mtup, str(dt)):
                key = f"{dt}#{len(self.buckets)}"
                b = self.buckets[key] = _Bucket(dt, self.chunk)
            prev = (gid, mtup, str(dt))
            leaf = _Leaf(name, shape, size, b.cursor, len(flags))
            b.cursor += size
            b.leaves.append(leaf)
            self.leaf_order.append((key, leaf))
            self._by_name[name] = (key, leaf)
            flags.append(mtup[0])
            lr_scale.append(mtup[1])
            norm_w.append(mtup[2])
        self.leaf_flags = np.asarray(flags, np.int32)
        self.leaf_lr_scale = np.asarray(lr_scale, np.float32)
        self.leaf_norm_weight = np.asarray(norm_w, np.float32)
        for b in self.buckets.values():
            # stores are EXACT-sized (padding would ride every store
            # traversal); the Pallas drivers pad to the chunk grid
            # transiently at the kernel boundary
            b.total = b.cursor
            b.n_chunks = -(-b.total // self.chunk)
            cl = np.zeros((b.n_chunks,), np.int32)
            for leaf in b.leaves:
                c0 = leaf.start // self.chunk
                c1 = (leaf.start + max(leaf.size, 1) - 1) // self.chunk
                cl[c0:c1 + 1] = leaf.index
            b.chunk_leaf = cl
        self.n_leaves = len(flags)
        # unpack with a custom VJP: the cotangent of the flat buffer is
        # ONE concatenate of leaf cotangents per bucket, not the pad+add
        # chain jax's slice transpose would emit per leaf
        self._unpack = jax.custom_vjp(self._unpack_impl)
        self._unpack.defvjp(
            lambda store: (self._unpack_impl(store), None),
            lambda _, cts: (self.pack(cts),))

    def segments(self, key):
        """Maximal runs of one bucket with UNIFORM per-leaf metadata:
        [(start, end, flags, lr_scale, norm_weight)] in elements. The
        direct (off-TPU) path executes one pure-1-D sweep per segment
        with the metadata folded in as python-static decisions — with
        default metadata that is exactly ONE whole-bucket sweep, which
        XLA:CPU schedules copy-free even under donation (a reshape or
        per-row metadata array in the fused expression would defeat its
        in-place analysis)."""
        b = self.buckets[key]
        li = b.leaves[0].index  # metadata is uniform per run-bucket
        return [(0, b.total, int(self.leaf_flags[li]),
                 float(self.leaf_lr_scale[li]),
                 float(self.leaf_norm_weight[li]))]

    # -- pack / unpack ---------------------------------------------------
    # Buckets are stored 1-D [total]. This is load-bearing for honest
    # cost accounting, not style: a [n_chunks, chunk] store would make
    # every unpack slice start with a flattening bitcast, and XLA's
    # HloCostAnalysis cannot see slice utilization through that bitcast
    # — every consumer fusion of a 512-byte bias would be charged the
    # whole megabuffer. The kernels reshape to [n_chunks, chunk] at
    # their call boundary, where the whole buffer is genuinely read.
    def bucket_shape(self, key):
        b = self.buckets[key]
        return (b.total,)

    def pack(self, tree, dtype_map=None, keys=None):
        """Tree {name: array} -> {bucket_key: [n_chunks, chunk]}.
        dtype_map optionally overrides the storage dtype per bucket key
        (moment/master buffers share the param layout at another
        dtype); keys restricts packing to a subset of buckets (master
        buffers exist only for low-precision buckets)."""
        out = {}
        for key, b in self.buckets.items():
            if keys is not None and key not in keys:
                continue
            dt = (dtype_map or {}).get(key, b.dtype)
            vals = [jnp.asarray(tree[leaf.name]).astype(dt)
                    for leaf in b.leaves]
            if len(vals) == 1:
                flat = vals[0].reshape(-1)
            elif all(v.shape == vals[0].shape for v in vals):
                # a scan-group run: stack of its members — when the
                # members are the per-layer slices of a scan's stacked
                # gradient, XLA folds this straight back onto that
                # buffer and the "pack" costs nothing
                flat = jnp.stack(vals).reshape(-1)
            else:
                flat = jnp.concatenate([v.reshape(-1) for v in vals])
            out[key] = flat
        return out

    def _unpack_impl(self, store):
        out = {}
        for key, b in self.buckets.items():
            flat = store[key]
            for leaf in b.leaves:
                out[leaf.name] = jax.lax.slice(
                    flat, (leaf.start,),
                    (leaf.start + leaf.size,)).reshape(leaf.shape)
        return out

    def unpack(self, store):
        """{bucket_key: buffer} -> {name: array} views (differentiable;
        the VJP packs cotangents with one concat per bucket)."""
        return self._unpack(store)

    def leaf_view(self, store, name, dtype=None):
        """One leaf's values out of a store (host/eager inspection)."""
        key, leaf = self._by_name[name]
        flat = store[key]
        v = jax.lax.slice(flat, (leaf.start,),
                          (leaf.start + leaf.size,)).reshape(leaf.shape)
        return v.astype(dtype) if dtype is not None else v


# ---------------------------------------------------------------------------
# the shared per-block math — ONE definition executed by both the Pallas
# kernels (TPU / interpret) and the direct off-TPU path
# ---------------------------------------------------------------------------

def _pass1_math(g, inv, flags, nw, write_u):
    """Unscale + weighted partial L2 + non-finite sweep of one [R, C]
    block. Returns (u or None, partial_sumsq, nonfinite_flag)."""
    g32 = g.astype(_F32)
    # found_inf sweeps the RAW grads (pre-unscale), exactly like the
    # tree path's GradScaler.jit_unscale_and_update
    nonfin = jnp.any(~jnp.isfinite(g32)).astype(_F32)
    if write_u:
        u = (g32 * inv).astype(g.dtype)
        u32 = u.astype(_F32)
    else:
        u, u32 = None, g32
    clip_on = ((flags & FLAG_NEED_CLIP) > 0).astype(_F32)
    w = (nw * clip_on)[:, 0]
    ss = jnp.sum(w * jnp.sum(u32 * u32, axis=1))
    return u, ss, nonfin


def _update_core(kind, hp, w, g32, ms32, lr, lr_t):
    """The optimizer recurrence itself, shared by the Pallas kernels
    (vector metadata, [R, C] blocks) and the direct 1-D segment path.
    Returns (np32, new_moments32)."""
    if kind in ("adam", "adamw"):
        # (1 - beta) precomputed in f64 then rounded, exactly like the
        # tree path's weak-typed python-float literals — bit parity
        b1 = jnp.float32(hp["beta1"])
        b2 = jnp.float32(hp["beta2"])
        omb1 = jnp.float32(1.0 - hp["beta1"])
        omb2 = jnp.float32(1.0 - hp["beta2"])
        eps = jnp.float32(hp["eps"])
        m = b1 * ms32[0] + omb1 * g32
        v = b2 * ms32[1] + omb2 * g32 * g32
        return w - lr_t * m / (jnp.sqrt(v) + eps), [m, v]
    if kind == "momentum":
        mom = jnp.float32(hp["momentum"])
        vel = mom * ms32[0] + g32
        if hp.get("nesterov"):
            return w - lr * (g32 + mom * vel), [vel]
        return w - lr * vel, [vel]
    return w - lr * g32, []  # sgd


def _pass1_direct(layout, key, g, inv, write_u):
    """Pass 1 as pure 1-D sweeps: unscale + non-finite over the whole
    bucket, the weighted L2 per metadata segment (python-static
    weights). No reshapes, no per-row metadata arrays — XLA:CPU keeps
    the whole thing in-place-analyzable and fusible."""
    g32 = g.astype(_F32)
    nonfin = jnp.any(~jnp.isfinite(g32)).astype(_F32)
    if write_u:
        u = (g32 * inv).astype(g.dtype)
        u32 = u.astype(_F32)
    else:
        u, u32 = None, g32
    segs = layout.segments(key)
    ss = jnp.zeros((), _F32)
    for start, end, flags, _lrsc, nw in segs:
        w = nw if (flags & FLAG_NEED_CLIP) else 0.0
        if not w:
            continue
        part = u32 if len(segs) == 1 else jax.lax.slice(u32, (start,),
                                                        (end,))
        ss = ss + jnp.float32(w) * jnp.sum(part * part)
    return u, ss, nonfin


def _pass2_segment(g, p, ms, mw, flags, lrsc, nw, sc, *, kind, hp,
                   global_clip, clip_value, with_stats):
    """One metadata-uniform 1-D segment of pass 2: the same math as the
    Pallas kernel, with the per-leaf metadata resolved to python-static
    decisions (exactly how the tree path decides per leaf)."""
    found = sc[2] > jnp.float32(0.0)
    clip_f = sc[3]
    lr = sc[0] if lrsc == 1.0 else sc[0] * jnp.float32(lrsc)
    lr_t = sc[1] if lrsc == 1.0 else sc[1] * jnp.float32(lrsc)

    if global_clip and (flags & FLAG_NEED_CLIP):
        g = (g.astype(_F32) * clip_f).astype(g.dtype)
    if clip_value is not None:
        g = jnp.clip(g, jnp.asarray(clip_value[0], g.dtype),
                     jnp.asarray(clip_value[1], g.dtype))
    g32 = g.astype(_F32)
    p32 = p.astype(_F32)
    w = mw if mw is not None else p32
    wd = hp.get("wd", 0.0)
    if wd and (flags & FLAG_DECAY):
        w = w * (jnp.float32(1.0) - lr * jnp.float32(wd))
    np32, new_m32 = _update_core(kind, hp, w, g32,
                                 [m.astype(_F32) for m in ms], lr, lr_t)
    npw = np32.astype(p.dtype)
    new_p = jnp.where(found, p, npw)
    new_ms = [jnp.where(found, old, nm.astype(old.dtype))
              for old, nm in zip(ms, new_m32)]
    new_mw = jnp.where(found, mw, np32) if mw is not None else None
    sp = su = None
    if with_stats:
        sel32 = new_p.astype(_F32)
        sp = jnp.float32(nw) * jnp.sum(sel32 * sel32)
        su = jnp.float32(nw) * jnp.sum((sel32 - p32) * (sel32 - p32))
    return new_p, new_ms, new_mw, sp, su


def _pass2_direct(layout, key, g, p, ms, mw, scalars, *, kind, hp,
                  global_clip, clip_value, with_stats):
    """Pass 2 as 1-D metadata segments (one whole-bucket sweep in the
    default all-uniform case), concatenating per-segment outputs when
    the metadata actually varies."""
    segs = layout.segments(key)
    if len(segs) == 1:
        _s, _e, flags, lrsc, nw = segs[0]
        return _pass2_segment(g, p, ms, mw, flags, lrsc, nw, scalars,
                              kind=kind, hp=hp, global_clip=global_clip,
                              clip_value=clip_value,
                              with_stats=with_stats)
    pieces, sp_t, su_t = [], jnp.zeros((), _F32), jnp.zeros((), _F32)
    for start, end, flags, lrsc, nw in segs:
        cut = lambda a: jax.lax.slice(a, (start,), (end,))  # noqa: E731
        po, mos, mwo, sp, su = _pass2_segment(
            cut(g), cut(p), [cut(m) for m in ms],
            cut(mw) if mw is not None else None, flags, lrsc, nw,
            scalars, kind=kind, hp=hp, global_clip=global_clip,
            clip_value=clip_value, with_stats=with_stats)
        pieces.append((po, mos, mwo))
        if with_stats:
            sp_t, su_t = sp_t + sp, su_t + su
    new_p = jnp.concatenate([pc[0] for pc in pieces])
    new_ms = [jnp.concatenate([pc[1][j] for pc in pieces])
              for j in range(len(ms))]
    new_mw = jnp.concatenate([pc[2] for pc in pieces]) \
        if mw is not None else None
    return new_p, new_ms, new_mw, \
        sp_t if with_stats else None, su_t if with_stats else None


def _pass2_math(g, p, ms, mw, flags, lrsc, nw, sc, *, kind, hp,
                global_clip, clip_value, with_stats):
    """Clip + decoupled decay + moment update + master downcast +
    found_inf skip of one [R, C] block. `sc` = [lr, lr_t, found_inf,
    clip_factor] (lr_t is the bias-corrected Adam rate, == lr for
    SGD/Momentum); flags/lrsc/nw broadcast [R, 1]. Returns (new_p,
    new_moments, new_master, param_sumsq, update_sumsq)."""
    lr = sc[0] * lrsc
    lr_t = sc[1] * lrsc
    found = sc[2] > jnp.float32(0.0)
    clip_f = sc[3]

    if global_clip:
        # per-leaf need_clip gates BOTH the factor application here and
        # the norm contribution in pass 1 (same mask, same semantics as
        # nn.clip.clip_grads_tree with a need_clip mask)
        f = jnp.where((flags & FLAG_NEED_CLIP) > 0, clip_f,
                      jnp.float32(1.0))
        g = (g.astype(_F32) * f).astype(g.dtype)
    if clip_value is not None:
        g = jnp.clip(g, jnp.asarray(clip_value[0], g.dtype),
                     jnp.asarray(clip_value[1], g.dtype))
    g32 = g.astype(_F32)
    p32 = p.astype(_F32)
    w = mw if mw is not None else p32
    wd = hp.get("wd", 0.0)
    if wd:
        decay_on = (flags & FLAG_DECAY) > 0
        w = w * jnp.where(decay_on,
                          jnp.float32(1.0) - lr * jnp.float32(wd),
                          jnp.float32(1.0))

    np32, new_m32 = _update_core(kind, hp, w, g32,
                                 [m.astype(_F32) for m in ms], lr, lr_t)

    # downcast tails (master keeps f32; the working param is its
    # rounded shadow), then the branchless found_inf skip
    npw = np32.astype(p.dtype)
    new_p = jnp.where(found, p, npw)
    new_ms = [jnp.where(found, old, nm.astype(old.dtype))
              for old, nm in zip(ms, new_m32)]
    new_mw = jnp.where(found, mw, np32) if mw is not None else None
    sp = su = None
    if with_stats:
        # norm_weight de-duplicates mesh-replicated leaves in the psum'd
        # health sums, exactly like pass 1's grad-norm partials
        sel32 = new_p.astype(_F32)
        sp = jnp.sum(nw[:, 0] * jnp.sum(sel32 * sel32, axis=1))
        su = jnp.sum(nw[:, 0] * jnp.sum((sel32 - p32) * (sel32 - p32),
                                        axis=1))
    return new_p, new_ms, new_mw, sp, su


# ---------------------------------------------------------------------------
# Pallas kernel wrappers over the shared math
# ---------------------------------------------------------------------------

def _pad_to(x, n):
    """Tail-pad a 1-D buffer to the Pallas chunk grid (stores are
    exact-sized; only the kernel boundary sees the padded view)."""
    if x.shape[0] == n:
        return x
    return jnp.concatenate([x, jnp.zeros((n - x.shape[0],), x.dtype)])


def _row_meta(cl_ref, table_ref, i, rows):
    """Per-row [rows, 1] view of a per-leaf metadata table through the
    chunk->leaf offset table. rows == 1 is the TPU layout (one chunk
    per program, pure scalar SMEM reads); rows == n_chunks is the
    interpret-mode layout (whole bucket in one block), where the lookup
    is a tiny vector gather."""
    if rows == 1:
        return table_ref[cl_ref[i]].reshape(1, 1)
    return table_ref[cl_ref[...]].reshape(rows, 1)


def _pass1_kernel(cl_ref, fl_ref, nw_ref, sc_ref, g_ref, *rest,
                  write_u, rows):
    if write_u:
        u_ref, ss_ref, fi_ref = rest
    else:
        ss_ref, fi_ref = rest
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        ss_ref[0, 0] = jnp.float32(0.0)
        fi_ref[0, 0] = jnp.float32(0.0)

    flags = _row_meta(cl_ref, fl_ref, i, rows)
    nw = _row_meta(cl_ref, nw_ref, i, rows)
    u, ss, nonfin = _pass1_math(g_ref[...], sc_ref[0], flags, nw,
                                write_u)
    if write_u:
        u_ref[...] = u
    fi_ref[0, 0] = jnp.maximum(fi_ref[0, 0], nonfin)
    ss_ref[0, 0] += ss


def _pass2_kernel(cl_ref, fl_ref, lrs_ref, nw_ref, sc_ref, *refs, kind,
                  n_moments, has_master, with_stats, global_clip,
                  clip_value, hp, rows):
    n_in = 2 + n_moments + (1 if has_master else 0)
    ins, outs = refs[:n_in], refs[n_in:]
    g_ref, p_ref = ins[0], ins[1]
    m_refs = ins[2:2 + n_moments]
    mw_ref = ins[2 + n_moments] if has_master else None
    po_ref = outs[0]
    mo_refs = outs[1:1 + n_moments]
    mwo_ref = outs[1 + n_moments] if has_master else None
    sp_ref = outs[-2] if with_stats else None
    su_ref = outs[-1] if with_stats else None

    i = pl.program_id(0)
    if with_stats:
        @pl.when(i == 0)
        def _init_stats():
            sp_ref[0, 0] = jnp.float32(0.0)
            su_ref[0, 0] = jnp.float32(0.0)

    new_p, new_ms, new_mw, sp, su = _pass2_math(
        g_ref[...], p_ref[...], [m[...] for m in m_refs],
        mw_ref[...] if has_master else None,
        _row_meta(cl_ref, fl_ref, i, rows),
        _row_meta(cl_ref, lrs_ref, i, rows),
        _row_meta(cl_ref, nw_ref, i, rows),
        sc_ref, kind=kind, hp=hp, global_clip=global_clip,
        clip_value=clip_value, with_stats=with_stats)
    po_ref[...] = new_p
    for mo, nm in zip(mo_refs, new_ms):
        mo[...] = nm
    if has_master:
        mwo_ref[...] = new_mw
    if with_stats:
        sp_ref[0, 0] += sp
        su_ref[0, 0] += su


# ---------------------------------------------------------------------------
# per-bucket pass drivers
# ---------------------------------------------------------------------------

def _run_pass1(layout, grads, inv_scale, write_u, mode):
    """Per-bucket pass 1. Returns (unscaled store or None, sumsq f32
    scalar, found_inf f32 scalar). sumsq accumulates bucket-major then
    chunk-major — the multi-tensor analogue of the tree path's
    leaf-major sum (equal within reduction-order ulps)."""
    C = layout.chunk
    sumsq = jnp.zeros((), _F32)
    found = jnp.zeros((), _F32)
    out_u = {} if write_u else None
    inv = jnp.asarray(inv_scale, _F32)
    for key, b in layout.buckets.items():
        if mode == "direct":
            u, ss, fi = _pass1_direct(layout, key, grads[key], inv,
                                      write_u)
            if write_u:
                out_u[key] = u
            sumsq = sumsq + ss
            found = jnp.maximum(found, fi)
            continue
        # Pallas path: buckets live 1-D and exact-sized; the padded
        # chunk view exists only at the kernel boundary (a full read
        # through a reshape is charged exactly)
        g = _pad_to(grads[key], b.n_chunks * C).reshape(b.n_chunks, C)
        interpret = mode == "interpret"
        rows = b.n_chunks if interpret else 1
        acc = pl.BlockSpec((1, 1), lambda i, *pf: (I0, I0))
        row = pl.BlockSpec((rows, C), lambda i, *pf: (i, I0))
        out_shape = [jax.ShapeDtypeStruct((1, 1), _F32),
                     jax.ShapeDtypeStruct((1, 1), _F32)]
        out_specs = [acc, acc]
        if write_u:
            out_shape.insert(0, jax.ShapeDtypeStruct(g.shape, g.dtype))
            out_specs.insert(0, row)
        res = pl.pallas_call(
            functools.partial(_pass1_kernel, write_u=write_u,
                              rows=rows),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=4,
                grid=(b.n_chunks // rows,),
                in_specs=[row],
                out_specs=out_specs),
            out_shape=out_shape,
            interpret=interpret,
        )(jnp.asarray(b.chunk_leaf), jnp.asarray(layout.leaf_flags),
          jnp.asarray(layout.leaf_norm_weight), inv.reshape(1), g)
        if write_u:
            out_u[key] = res[0].reshape(-1)[:b.total]
            ss, fi = res[1][0, 0], res[2][0, 0]
        else:
            ss, fi = res[0][0, 0], res[1][0, 0]
        sumsq = sumsq + ss
        found = jnp.maximum(found, fi)
    return out_u, sumsq, found


def _run_pass2(layout, spec, grads, params, moments, masters, scalars,
               with_stats, global_clip, clip_value, mode):
    """Per-bucket pass 2. Returns (new_params, new_moments, new_masters,
    stats) — stats is (param_sumsq, update_sumsq) f32 or None."""
    C = layout.chunk
    kind = spec["kind"]
    n_moments = spec["n_moments"]
    new_p, new_m, new_mw = {}, [dict() for _ in range(n_moments)], {}
    p_sq = jnp.zeros((), _F32)
    u_sq = jnp.zeros((), _F32)
    for key, b in layout.buckets.items():
        has_master = key in (masters or {})
        if mode == "direct":
            po, mos, mwo, sp, su = _pass2_direct(
                layout, key, grads[key], params[key],
                [m[key] for m in moments],
                masters[key] if has_master else None, scalars,
                kind=kind, hp=spec, global_clip=global_clip,
                clip_value=clip_value, with_stats=with_stats)
            new_p[key] = po
            for j in range(n_moments):
                new_m[j][key] = mos[j]
            if has_master:
                new_mw[key] = mwo
            if with_stats:
                p_sq = p_sq + sp
                u_sq = u_sq + su
            continue
        shp = (b.n_chunks, C)
        padded = b.n_chunks * C
        p = _pad_to(params[key], padded).reshape(shp)
        g = _pad_to(grads[key], padded).reshape(shp)
        ms_2d = [_pad_to(m[key], padded).reshape(shp) for m in moments]
        mw = _pad_to(masters[key], padded).reshape(shp) \
            if has_master else None
        interpret = mode == "interpret"
        rows = b.n_chunks if interpret else 1
        ops = [g, p] + ms_2d + ([mw] if has_master else [])
        blk = pl.BlockSpec((rows, C), lambda i, *pf: (i, I0))
        in_specs = [blk] * len(ops)
        out_shape = [jax.ShapeDtypeStruct(shp, p.dtype)] \
            + [jax.ShapeDtypeStruct(shp, m.dtype) for m in ms_2d] \
            + ([jax.ShapeDtypeStruct(shp, _F32)]
               if has_master else [])
        out_specs = [blk] * len(out_shape)
        n_alias = len(out_shape)
        if with_stats:
            for _ in range(2):
                out_shape.append(jax.ShapeDtypeStruct((1, 1), _F32))
                out_specs.append(pl.BlockSpec(
                    (1, 1), lambda i, *pf: (I0, I0)))
        # alias param/moment/master buffers in place: operand index
        # counts the 5 scalar-prefetch args first; grads (input 5)
        # are NOT aliased (pass 1 may still own that buffer)
        aliases = {5 + 1 + j: j for j in range(n_alias)}
        res = pl.pallas_call(
            functools.partial(
                _pass2_kernel, kind=kind, n_moments=n_moments,
                has_master=has_master, with_stats=with_stats,
                global_clip=global_clip, clip_value=clip_value,
                hp=spec, rows=rows),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=5,
                grid=(b.n_chunks // rows,),
                in_specs=in_specs,
                out_specs=out_specs),
            out_shape=out_shape,
            input_output_aliases=aliases,
            interpret=interpret,
        )(jnp.asarray(b.chunk_leaf), jnp.asarray(layout.leaf_flags),
          jnp.asarray(layout.leaf_lr_scale),
          jnp.asarray(layout.leaf_norm_weight), scalars, *ops)
        po = res[0]
        mos = [res[1 + j] for j in range(n_moments)]
        mwo = res[1 + n_moments] if has_master else None
        sp = res[-2][0, 0] if with_stats else None
        su = res[-1][0, 0] if with_stats else None
        new_p[key] = po.reshape(-1)[:b.total]
        for j in range(n_moments):
            new_m[j][key] = mos[j].reshape(-1)[:b.total]
        if has_master:
            new_mw[key] = mwo.reshape(-1)[:b.total]
        if with_stats:
            p_sq = p_sq + sp
            u_sq = u_sq + su
    stats = (p_sq, u_sq) if with_stats else None
    return new_p, new_m, new_mw, stats


# ---------------------------------------------------------------------------
# the epilogue driver TrainStep/HybridTrainStep call under the trace
# ---------------------------------------------------------------------------

class FusedEpilogue:
    """Owns one BucketLayout + one optimizer fused-spec and drives the
    two passes. Pure w.r.t. its traced inputs — call under jit."""

    def __init__(self, layout, spec, interpret=None):
        self.layout = layout
        self.spec = dict(spec)
        if jax.default_backend() == "tpu":
            self.mode = "pallas"
        elif (interpret if interpret is not None
              else os.environ.get("PADDLE_TPU_FUSED_INTERPRET") == "1"):
            self.mode = "interpret"
        else:
            # off-TPU default: the same _math bodies run directly on
            # the flat buffers — XLA:CPU fuses them like any
            # elementwise graph (Pallas interpret mode would execute
            # the same math through grid emulation machinery that the
            # CPU backend cannot fuse, inflating bytes-accessed ~2x)
            self.mode = "direct"

    # -- state construction (host side, once) ----------------------------
    def init_stores(self, params_tree, multi_precision):
        """(param_store, opt_store). opt_store = {"moments": tuple of
        per-bucket dicts (state dtype), "masters": {bucket: f32}} —
        masters only for non-f32 buckets under multi_precision."""
        lay = self.layout
        p_store = lay.pack(params_tree)
        sdt = self.spec.get("state_dtype") or jnp.float32
        moments = tuple(
            {key: jnp.zeros(lay.bucket_shape(key), sdt)
             for key in lay.buckets}
            for _ in range(self.spec["n_moments"]))
        masters = {}
        if multi_precision:
            for key, b in lay.buckets.items():
                if b.dtype != jnp.float32:
                    masters[key] = p_store[key].astype(jnp.float32)
        return p_store, {"moments": moments, "masters": masters}

    def pack_opt_tree(self, state_tree):
        """Per-leaf optimizer-state tree (init_leaf_state layout) ->
        flat opt store — the inverse of state_view. HybridTrainStep
        packs its TREE-persistent (per-leaf-sharded) state into local
        buckets each step inside its shard_map epilogue."""
        lay = self.layout
        sdt = self.spec.get("state_dtype") or jnp.float32

        def inner(name):
            s = state_tree[name]
            return s["state"] if isinstance(s, dict) and "master" in s \
                else s

        moments = tuple(
            lay.pack({leaf.name: inner(leaf.name)[j]
                      for _, leaf in lay.leaf_order},
                     dtype_map={k: sdt for k in lay.buckets})
            for j in range(self.spec["n_moments"]))
        master_keys = {key for key, leaf in lay.leaf_order
                       if isinstance(state_tree[leaf.name], dict)}
        masters = lay.pack(
            {leaf.name: state_tree[leaf.name]["master"]
             for key, leaf in lay.leaf_order if key in master_keys},
            dtype_map={k: jnp.float32 for k in lay.buckets},
            keys=master_keys) if master_keys else {}
        return {"moments": moments, "masters": masters}

    def state_view(self, opt_store):
        """Per-leaf optimizer-state VIEW of the flat store — {name:
        tuple(moments) | {"master": f32, "state": tuple}} — mirroring
        Optimizer.init_leaf_state's tree layout exactly, so state_dict
        round-trips and tests see the same structure on both paths."""
        lay = self.layout
        out = {}
        for key, leaf in lay.leaf_order:
            moments = tuple(lay.leaf_view(m, leaf.name)
                            for m in opt_store["moments"])
            if key in opt_store["masters"]:
                out[leaf.name] = {
                    "master": lay.leaf_view(opt_store["masters"],
                                            leaf.name),
                    "state": moments}
            else:
                out[leaf.name] = moments
        return out

    def bytes_per_step(self, scaling, need_norm, master_keys=()):
        """Analytic HBM traffic of the epilogue passes (the
        `epilogue_bytes` step-record field): pass 1 reads grads (and
        writes the unscaled buffer when a scaler rides along), pass 2
        reads grads+params+moments+masters and writes
        params+moments+masters."""
        total = 0
        sdt = self.spec.get("state_dtype") or jnp.float32
        s_size = jnp.dtype(sdt).itemsize
        for key, b in self.layout.buckets.items():
            n = b.total
            it = b.dtype.itemsize
            if scaling:
                total += n * it * 2          # pass 1: read g, write u
            elif need_norm:
                total += n * it              # pass 1: read g
            total += n * it * 3              # pass 2: read g+p, write p
            total += n * s_size * 2 * self.spec["n_moments"]
            if key in master_keys:
                total += n * 4 * 2           # master read+write
        return int(total)

    # -- the traced epilogue --------------------------------------------
    def finish(self, grads, p_store, opt_store, lr, step, scaler=None,
               scaler_state=None, clip=None, with_stats=False):
        """From bucketed grads to the updated bucketed carry.

        Returns (new_p_store, new_opt_store, new_scaler_state, aux) with
        aux = {"grad_norm", "found_inf"} (+ "param_sumsq",
        "update_sumsq" when with_stats) — grad_norm is the ONE global
        norm shared by clip, found_inf handling, and the health vector.
        Hybrid sets psum axes (set_psum_axes) so the partial sums and
        found flag reduce across shards."""
        scaling = scaler is not None and scaler.is_enable()
        global_clip, clip_value, clip_norm = _resolve_clip(clip)
        need_norm = bool(global_clip) or with_stats

        found = jnp.zeros((), _F32)
        gn = jnp.zeros((), _F32)
        u = grads
        if scaling or need_norm:
            inv = (jnp.float32(1.0) / scaler_state["scale"]) if scaling \
                else jnp.float32(1.0)
            u_out, sumsq, found = _run_pass1(
                self.layout, grads, inv, write_u=scaling,
                mode=self.mode)
            if scaling:
                u = u_out
            sumsq = self._psum(sumsq)
            found = self._pmax(found)
            gn = jnp.sqrt(sumsq)
        new_scaler_state = scaler_state
        found_b = None
        if scaling:
            found_b = found > 0
            new_scaler_state = scaler.jit_update_scale_state(
                scaler_state, found_b)
        clip_f = jnp.float32(1.0)
        if global_clip:
            clip_f = jnp.minimum(
                jnp.float32(clip_norm) / jnp.maximum(gn,
                                                     jnp.float32(1e-12)),
                jnp.float32(1.0))
        # the rate math runs on lr/step exactly as the tree path's
        # _update would see them (weak-type promotion included); the
        # single round to f32 happens here, where the tree path rounds
        # at the multiply into the f32 update
        lr_t = self._rate(lr, step)
        # the found_inf SKIP only exists under a live GradScaler (tree
        # parity: found_inf=None otherwise, and a NaN grad updates)
        skip = found if scaling else jnp.zeros((), _F32)
        scalars = jnp.stack([jnp.asarray(lr).astype(_F32),
                             jnp.asarray(lr_t).astype(_F32),
                             skip, clip_f])
        new_p, new_m, new_mw, stats = _run_pass2(
            self.layout, self.spec, u, p_store,
            list(opt_store["moments"]), opt_store["masters"], scalars,
            with_stats, global_clip, clip_value, self.mode)
        aux = {"grad_norm": gn, "found_inf": found_b}
        if scaling or need_norm:
            # pass 1's non-finite sweep covers EVERY leaf (the clip
            # mask only gates the norm) — the health vector's found_inf
            # signal, exact even for need_clip=False leaves whose norm
            # contribution is masked out
            aux["nonfinite"] = found > 0
        if with_stats:
            aux["param_sumsq"] = self._psum(stats[0])
            aux["update_sumsq"] = self._psum(stats[1])
        return new_p, {"moments": tuple(new_m), "masters": new_mw}, \
            new_scaler_state, aux

    def _rate(self, lr, step):
        """The per-element rate pass 2 applies: bias-corrected for
        Adam/AdamW (the same scalar expression the tree path's _update
        evaluates, on the same lr/step values), plain lr otherwise."""
        if self.spec["kind"] in ("adam", "adamw"):
            b1 = self.spec["beta1"]
            b2 = self.spec["beta2"]
            return lr * (1 - b2 ** step) ** 0.5 / (1 - b1 ** step)
        return lr

    # hybrid: reduce partial sums / found across mesh axes. The partial
    # sums psum (replicated leaves pre-weighted by 1/replication via
    # norm_weight metadata, so the psum does not double-count them); the
    # found flag pmaxes (any shard's hit is everyone's hit).
    _psum_axes = None

    def set_psum_axes(self, axes):
        self._psum_axes = tuple(axes) if axes else None

    def _psum(self, v):
        return jax.lax.psum(v, self._psum_axes) if self._psum_axes else v

    def _pmax(self, v):
        return jax.lax.pmax(v, self._psum_axes) if self._psum_axes else v


def _resolve_clip(clip):
    """(global_clip, clip_value, clip_norm) for a nn.clip config the
    fused path supports; raises on an unsupported one (the caller's
    eligibility check is the real gate — this is the backstop)."""
    if clip is None:
        return False, None, None
    from ...nn.clip import (ClipGradByGlobalNorm, ClipGradByValue,
                            ClipGradByNorm)
    if isinstance(clip, ClipGradByGlobalNorm):
        return True, None, float(clip.clip_norm)
    if isinstance(clip, ClipGradByValue):
        return False, (float(clip.min), float(clip.max)), None
    if isinstance(clip, ClipGradByNorm):
        raise NotImplementedError(
            "fused epilogue does not support per-leaf ClipGradByNorm; "
            "use the tree path (PADDLE_TPU_FUSED_UPDATE=0)")
    return False, None, None
