"""Speculative decoding through the fixed-shape ragged step
(inference/speculative.py + the GenerationEngine surgery).

The whole subsystem's correctness contract is an EQUALITY, not a
distribution argument: position-keyed sampling (fold_in(request_key,
absolute_position)) makes the non-speculative token stream a pure
function of (seed, history), so every accepted speculative token must
be bit-identical to it — greedy AND sampled, through admit/evict
churn, prefix-cache sharing, and a disaggregated handoff. Covered:

- accept_length (the longest-prefix + bonus rule) and
  SpeculativeConfig validation (k bounded by the MIN_Q_TOKENS bucket)
- PagedKVCache.rollback: write-cursor only — pages, refcounts, and
  claims untouched (the rejected-tail protocol)
- engine equality vs the non-speculative stream under mid-stream
  admit/evict, greedy and sampled in one batch
- rejected tails never corrupt a registered CoW prefix: sharers
  admitted after a speculating sequence still match the oracle
- two-pool admission accounting drains clean (no leaked draft claims)
- mid-speculation handoff: the draft rider crosses the
  prefill->decode boundary and the journey still matches
- telemetry: request records carry proposed/accepted (zeros when
  speculation is off), load_report exposes accept_rate
- zero-new-executables: warm_async covers the draft schedule and a
  speculative steady state adds no (tag, signature) pairs
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig
from paddle_tpu.inference import (GenerationEngine, ServingRouter,
                                  SamplingParams, SpeculativeConfig)
from paddle_tpu.inference.speculative import accept_length
from paddle_tpu.ops.pallas.attention_core import MIN_Q_TOKENS
from paddle_tpu.profiler import serve_observatory as sobs

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick gate no


# compiled executables cache on the model instance and the disk
# compile cache is off under tests (conftest), so one model per
# (seed, layers) across this file's tests avoids repaying compiles;
# every compile assertion here is a warm-vs-steady snapshot delta,
# none requires a cold model
_MODELS = {}


def _tiny_lm(seed=0, layers=2):
    key = (seed, layers)
    if key in _MODELS:
        return _MODELS[key]
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=layers,
                    num_heads=4, max_position_embeddings=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    _MODELS[key] = m
    return m


def _draft_for(seed=0):
    """A 1-layer draft over the same vocab — seeded like the target so
    its argmax agrees often enough to exercise BOTH accept and reject
    paths (equality must hold at any accept rate)."""
    return _tiny_lm(seed=seed, layers=1)


def _spec_engine(target, draft, k=4, **kw):
    kw.setdefault("n_pages", 64)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_new_tokens", 10)
    return GenerationEngine(target, speculative=SpeculativeConfig(draft, k=k),
                            **kw)


# -- the acceptance rule (pure host) ------------------------------------

class TestAcceptLength:
    def test_longest_prefix_plus_bonus(self):
        # v_0 always accepted; each d_i == v_{i-1} extends the prefix
        assert accept_length([7, 8], [7, 8, 9]) == 3   # all + bonus
        assert accept_length([7, 8], [7, 9, 1]) == 2   # d_2 missed
        assert accept_length([7, 8], [5, 8, 9]) == 1   # d_1 missed
        assert accept_length([], [4]) == 1             # anchor only

    def test_shape_mismatch_raises(self):
        with pytest.raises(ValueError):
            accept_length([1, 2], [1, 2])

    def test_config_bounds_k_to_the_token_bucket(self):
        d = object()
        with pytest.raises(ValueError):
            SpeculativeConfig(d, k=0)
        with pytest.raises(ValueError):
            SpeculativeConfig(d, k=MIN_Q_TOKENS)  # k+1 would overflow
        with pytest.raises(ValueError):
            SpeculativeConfig(None, k=2)
        assert SpeculativeConfig(d, k=MIN_Q_TOKENS - 1).k \
            == MIN_Q_TOKENS - 1


# -- the rollback protocol (pool level) ---------------------------------

class TestRollback:
    def _cache(self):
        m = _tiny_lm()
        return m.make_paged_cache(n_pages=16, page_size=4)

    def test_cursor_only_pages_and_claims_untouched(self):
        c = self._cache()
        c.add_sequence("s")
        c.set_claim("s", 3)
        c.plan_ragged([("s", 6)])  # draw pages for 6 tokens
        c.advance("s", 6)
        held = c.pages_held("s")
        drawn = c.pages_drawn("s")
        claims = c.outstanding_claims()
        c.rollback("s", 4)  # reject a speculated tail
        assert c.length("s") == 2
        assert c.pages_held("s") == held          # pages stay drawn
        assert c.pages_drawn("s") == drawn
        assert c.outstanding_claims() == claims   # ledger untouched
        # the freed cursor range is rewritable without a new draw
        c.plan_ragged([("s", 4)])
        c.advance("s", 4)
        assert c.length("s") == 6

    def test_bounds_checked(self):
        c = self._cache()
        c.add_sequence("s")
        c.plan_ragged([("s", 2)])
        c.advance("s", 2)
        with pytest.raises(ValueError):
            c.rollback("s", 3)      # more than was ever written
        with pytest.raises(ValueError):
            c.rollback("s", -1)
        with pytest.raises(KeyError):
            c.rollback("ghost", 1)
        c.rollback("s", 0)          # no-op is legal
        assert c.length("s") == 2


# -- engine equality ----------------------------------------------------

def _nonspec_outputs(model, jobs):
    """Oracle: the SAME requests through a non-speculative engine."""
    eng = GenerationEngine(model, n_pages=64, page_size=4, max_batch=2,
                           max_new_tokens=10)
    try:
        hs = [eng.submit(p, max_new_tokens=n, sampling=sp)
              for p, n, sp in jobs]
        return [h.result(timeout=300).tolist() for h in hs]
    finally:
        eng.shutdown()


class TestSpeculativeEngine:
    def test_equality_greedy_and_sampled_under_churn(self):
        """Three requests (greedy + two seeded sampled) over 2 slots:
        admission churn, eviction mid-stream, and every emitted token
        bit-identical to the non-speculative stream."""
        target, draft = _tiny_lm(), _draft_for()
        rng = np.random.RandomState(5)
        jobs = [
            (rng.randint(0, 64, (4,)), 8, None),
            (rng.randint(0, 64, (6,)), 10,
             SamplingParams(temperature=0.9, top_k=16, seed=11)),
            (rng.randint(0, 64, (3,)), 6,
             SamplingParams(temperature=0.7, top_p=0.9, seed=23)),
        ]
        refs = _nonspec_outputs(target, jobs)
        eng = _spec_engine(target, draft, k=4)
        try:
            hs = [eng.submit(p, max_new_tokens=n, sampling=sp)
                  for p, n, sp in jobs]
            outs = [h.result(timeout=300).tolist() for h in hs]
            rep = eng.load_report()
        finally:
            eng.shutdown()
        assert outs == refs
        assert rep["speculative"] is True
        assert 0 <= rep["accepted_tokens"] <= rep["proposed_tokens"]
        assert 0.0 <= rep["accept_rate"] <= 1.0

    def test_rejected_tails_under_admit_evict_churn_drain_clean(self):
        """A tiny draft pool + queue pressure: sequences join, evict,
        and reject tails continuously; afterwards BOTH pools are fully
        free — no leaked pages, no leaked claims in either ledger."""
        target, draft = _tiny_lm(), _draft_for(seed=9)  # disagreeing draft
        rng = np.random.RandomState(6)
        jobs = [(rng.randint(0, 64, (rng.randint(2, 7),)),
                 int(rng.randint(2, 8)), None) for _ in range(5)]
        refs = _nonspec_outputs(target, jobs)
        eng = _spec_engine(target, draft, k=3, n_pages=32, max_batch=2)
        try:
            hs = [eng.submit(p, max_new_tokens=n, sampling=sp)
                  for p, n, sp in jobs]
            outs = [h.result(timeout=300).tolist() for h in hs]
            dc = eng._draft_cache
            eng.drain(timeout=60)
            assert dc.outstanding_claims() == 0
            assert dc.n_free_pages() == dc.n_pages - 1  # all but pad page
        finally:
            eng.shutdown()
        assert outs == refs

    def test_cow_prefix_sharers_never_observe_rejected_writes(self):
        """A registered prefix is shared copy-on-write; a speculating
        sharer writes (then rejects) tokens PAST the shared range. A
        sharer admitted afterwards must still decode the oracle stream
        — any speculated write leaking into a registered page would
        corrupt its attention over the prefix KV."""
        target, draft = _tiny_lm(), _draft_for(seed=9)
        sys_prompt = np.random.RandomState(7).randint(0, 64, (8,))
        ref = _nonspec_outputs(target, [(sys_prompt, 8, None)])[0]
        eng = _spec_engine(target, draft, k=4, n_pages=64)
        try:
            # seed the registry, then two sharers in sequence: the
            # second attends over pages the first speculated across
            assert eng.submit(sys_prompt, max_new_tokens=8
                              ).result(timeout=300).tolist() == ref
            h1 = eng.submit(sys_prompt, max_new_tokens=8)
            assert h1.result(timeout=300).tolist() == ref
            h2 = eng.submit(sys_prompt, max_new_tokens=8)
            assert h2.result(timeout=300).tolist() == ref
            # the second sharer really did hit the prefix cache
            tail = [r for r in sobs.requests_tail()
                    if r["outcome"] == "completed"]
            assert any(r["prefix_hit_tokens"] > 0 for r in tail)
        finally:
            eng.shutdown()

    def test_zero_new_executables_after_warm(self):
        """warm_async covers the draft's catch-up/proposal schedule and
        the verify rows reuse the decode signatures — a warmed
        speculative engine adds ZERO (tag, signature) pairs in steady
        state, and retraces_after_warm == 0 (draft compiles counted)."""
        from paddle_tpu.profiler import compile_observatory as cobs
        target, draft = _tiny_lm(), _draft_for()
        eng = _spec_engine(target, draft, k=4, prefix_cache=False,
                           max_new_tokens=6)
        try:
            eng.warm(5, 6)
            warmed = cobs.ledger_signatures()
            # model-level trace counters: warm's own compiles are done
            # (warm blocks), so any growth below is a steady-state
            # retrace — target's or the draft's
            traces0 = getattr(target, "_ragged_traces", 0) \
                + getattr(draft, "_ragged_traces", 0)
            eng.submit(np.random.RandomState(8).randint(0, 64, (5,)),
                       max_new_tokens=6).result(timeout=300)
            eng.submit(np.random.RandomState(9).randint(0, 64, (5,)),
                       max_new_tokens=6,
                       sampling=SamplingParams(temperature=0.8, seed=3)
                       ).result(timeout=300)
            steady = cobs.ledger_signatures()
            assert steady == warmed, sorted(steady - warmed)
            assert getattr(target, "_ragged_traces", 0) \
                + getattr(draft, "_ragged_traces", 0) == traces0
        finally:
            eng.shutdown()


# -- telemetry ----------------------------------------------------------

class TestSpeculativeTelemetry:
    def test_request_records_carry_spec_fields(self):
        target, draft = _tiny_lm(), _draft_for()
        eng = _spec_engine(target, draft, k=4)
        try:
            eng.submit(np.array([3, 1, 4, 1, 5]), max_new_tokens=6
                       ).result(timeout=300)
        finally:
            eng.shutdown()
        rec = [r for r in sobs.requests_tail()
               if r["outcome"] == "completed"][-1]
        assert rec["proposed_tokens"] >= 1
        assert 0 <= rec["accepted_tokens"] <= rec["proposed_tokens"]
        assert 0.0 <= rec["accept_rate"] <= 1.0

    def test_nonspec_records_carry_zeros(self):
        eng = GenerationEngine(_tiny_lm(), n_pages=64, page_size=4,
                               max_batch=2, max_new_tokens=4)
        try:
            eng.submit(np.array([2, 7, 1])).result(timeout=300)
            rep = eng.load_report()
        finally:
            eng.shutdown()
        rec = [r for r in sobs.requests_tail()
               if r["outcome"] == "completed"][-1]
        assert rec["proposed_tokens"] == 0
        assert rec["accepted_tokens"] == 0
        assert rec["accept_rate"] == 0.0
        assert rep["speculative"] is False
        assert rep["accept_rate"] == 0.0

    def test_config_rejects_nonragged_and_bad_draft(self):
        target = _tiny_lm()
        with pytest.raises(ValueError):
            GenerationEngine(target, ragged=False,
                             speculative=SpeculativeConfig(_draft_for()))
        with pytest.raises(TypeError):
            GenerationEngine(target, speculative="not-a-config")
        with pytest.raises(TypeError):
            GenerationEngine(
                target, speculative=SpeculativeConfig(object()))


# -- the disaggregated handoff ------------------------------------------

class TestSpeculativeHandoff:
    def test_mid_speculation_chain_handoff_equality(self):
        """Prefill role catches the draft up over the prompt, exports
        the chain WITH its draft rider; the decode role adopts both
        and keeps speculating — greedy and sampled streams both match
        the single-engine non-speculative oracle, and the journey
        record reconciles accepted <= proposed."""
        target, draft = _tiny_lm(), _draft_for()
        rng = np.random.RandomState(10)
        jobs = [
            (rng.randint(0, 64, (6,)), 8, None),
            (rng.randint(0, 64, (4,)), 8,
             SamplingParams(temperature=0.8, top_k=12, seed=31)),
        ]
        refs = _nonspec_outputs(target, jobs)
        router = ServingRouter.disaggregated(
            target, n_pages=64, page_size=4, max_batch=2,
            max_new_tokens=10, name="spec_rt",
            speculative=SpeculativeConfig(draft, k=4))
        try:
            # both engines share ONE draft pool: the rider's page ids
            # stay valid across the handoff
            pre, dec = router.engines
            assert pre._draft_cache is dec._draft_cache
            hs = [router.submit(p, max_new_tokens=n, sampling=sp)
                  for p, n, sp in jobs]
            outs = [h.result(timeout=300).tolist() for h in hs]
            rep = router.load_report()
        finally:
            router.shutdown()
        assert outs == refs
        fleet = rep["fleet"]
        assert 0 <= fleet["accepted_tokens"] <= fleet["proposed_tokens"]
        assert 0.0 <= fleet["accept_rate"] <= 1.0
        # the decode role did the speculating (prefill never decodes)
        assert rep["engines"]["spec_rt_decode"]["proposed_tokens"] >= 1
