"""Mamba-style selective-state-space models with the GPT serving contract.

The second model family behind the serving stack (PAPERS.md
"Compiler-First State Space Duality and Portable O(1) Autoregressive
Caching"): a stack of selective-SSM mixer blocks — optionally
interleaved with attention layers (`attn_every`) — whose decode cache
is ONE fixed-size state blob per sequence (conv tail + state matrix
per layer) instead of a length-proportional KV page list. The
continuous-batching engine, router, and disaggregation all drive it
through the same duck-typed surface `models/gpt.py` defined:

    make_paged_cache()    -> inference.cache_strategy.RecurrentStateCache
                             (or HybridCache for the interleaved model)
    paged_ragged_step()   the fixed-shape mixed prefill+decode step —
                          same `serve.ragged_step` warm/executable
                          ledger discipline, same on-device per-row
                          sampling (gpt.sample_token_rows)
    warm_ragged()         single-flight AOT compiles, shared tag
    paged_decode_step()   eager wrapper over the ragged step (the
                          tests' single-sequence reference oracle)

The selective scan itself is the Pallas kernel in
ops/pallas/ssm_scan.py; the FULL forward (training path) flattens
[B, T] onto the kernel's ragged token axis, so training and serving
execute the identical scan code. Chunked prefill needs no special
path: a prompt slice is just a multi-token row of the ragged step,
its conv tail and state carrying across chunks through the pools.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .. import nn
from ..nn import initializer as I
from .gpt import GPTAttention, RaggedJitSlot, sample_token_rows

__all__ = ["SSMConfig", "SSMForCausalLM", "SSMJitSlot", "ssm_tiny",
           "ssm_hybrid_tiny"]


class SSMConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 d_state=16, d_conv=4, expand=2, dt_rank=None,
                 attn_every=0, num_heads=12,
                 max_position_embeddings=1024, dropout=0.0,
                 layer_norm_epsilon=1e-5, initializer_range=0.02,
                 use_bias=True, sequence_parallel=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.d_state = d_state          # N: state matrix columns
        self.d_conv = d_conv            # K: causal depthwise conv taps
        self.expand = expand
        self.d_inner = expand * hidden_size
        self.dt_rank = dt_rank or max(hidden_size // 16, 1)
        # attn_every=k > 0: every k-th layer is a GPTAttention layer
        # (the hybrid model); 0 = pure SSM stack
        self.attn_every = attn_every
        self.num_heads = num_heads
        # SSM state has no positional ceiling; the limit stays as the
        # engine's context-guard contract (and bounds the hybrid wpe)
        self.max_position_embeddings = max_position_embeddings
        self.dropout = dropout
        self.layer_norm_epsilon = layer_norm_epsilon
        self.initializer_range = initializer_range
        self.use_bias = use_bias
        self.sequence_parallel = sequence_parallel

    def is_attn_layer(self, i):
        return self.attn_every > 0 \
            and i % self.attn_every == self.attn_every - 1


class SSMJitSlot:
    """One SSM layer's state for the fully-jitted RAGGED step:
    traced/donated conv + state pools plus the host plan from
    RecurrentStateCache.plan_step — per-token row/chunk coordinates,
    the dt validity mask that neutralizes pad tokens, and the per-row
    slot/boundary arrays the conv-tail update needs."""

    __slots__ = ("conv", "ssm", "token_seq", "chunk_pos", "tok_valid",
                 "slot_ids", "row_end", "row_len")

    def __init__(self, conv, ssm, token_seq, chunk_pos, tok_valid,
                 slot_ids, row_end, row_len):
        self.conv = conv
        self.ssm = ssm
        self.token_seq = token_seq
        self.chunk_pos = chunk_pos
        self.tok_valid = tok_valid
        self.slot_ids = slot_ids
        self.row_end = row_end
        self.row_len = row_len


class SSMMixer(nn.Layer):
    """Selective-SSM token mixer (Mamba block body): in-projection to
    (x, z), causal depthwise conv over x, input-dependent (dt, B, C)
    from x, the selective scan h_t = exp(dt*A)h_{t-1} + (dt*B_t)x_t /
    y_t = C_t.h_t + D*x_t, silu(z) gating, out-projection. The scan is
    ops/pallas/ssm_scan.py in BOTH the full forward and the ragged
    serving step."""

    def __init__(self, cfg):
        super().__init__()
        h, d = cfg.hidden_size, cfg.d_inner
        N, K, R = cfg.d_state, cfg.d_conv, cfg.dt_rank
        self.d_inner, self.d_state, self.d_conv = d, N, K
        w_init = nn.initializer.Normal(0.0, cfg.initializer_range)
        self.in_proj = nn.Linear(h, 2 * d, weight_attr=w_init,
                                 bias_attr=False)
        self.conv_weight = self.create_parameter(
            [K, d], default_initializer=w_init)
        self.conv_bias = self.create_parameter([d], is_bias=True)
        self.x_proj = nn.Linear(d, R + 2 * N, weight_attr=w_init,
                                bias_attr=False)
        self.dt_proj = nn.Linear(R, d, weight_attr=w_init)
        # S4/Mamba A init: A = -exp(A_log) with A_log = log(1..N) per
        # channel — a spread of decay rates; D (skip) starts at 1
        self.A_log = self.create_parameter(
            [d, N], default_initializer=I.Assign(
                np.log(np.tile(np.arange(1, N + 1, dtype=np.float32),
                               (d, 1)))))
        self.D = self.create_parameter(
            [d], default_initializer=I.Constant(1.0))
        self.out_proj = nn.Linear(d, h, weight_attr=w_init,
                                  bias_attr=None if cfg.use_bias
                                  else False)

    def _dt_bc(self, xc):
        """(dt [.., d], B [.., N], C [.., N]) from the conv output —
        the input-dependence that makes the scan selective. dt is
        softplus'd here; the caller masks pads."""
        R, N = self.x_proj.weight.shape[1] - 2 * self.d_state, \
            self.d_state
        dbc = self.x_proj(Tensor(xc)).value
        dt = jax.nn.softplus(self.dt_proj(Tensor(dbc[..., :R])).value)
        return dt, dbc[..., R:R + N], dbc[..., R + N:]

    def forward(self, x, slot=None):
        from ..ops.pallas.ssm_scan import ssm_scan
        B, T, H = x.shape
        d, N, K = self.d_inner, self.d_state, self.d_conv
        xz = self.in_proj(x).value
        xin, z = xz[..., :d], xz[..., d:]
        w = self.conv_weight.value.astype(jnp.float32)
        if slot is None:
            # full causal forward: conv via shifts from zeros, scan via
            # the kernel with [B, T] flattened onto the token axis (the
            # serving kernel IS the training kernel)
            acc = xin * w[K - 1]
            for s in range(1, K):
                prev = jnp.pad(xin, ((0, 0), (s, 0), (0, 0)))[:, :T]
                acc = acc + prev * w[K - 1 - s]
            xc = jax.nn.silu(acc + self.conv_bias.value)
            dt, b_t, c_t = self._dt_bc(xc)
            h0 = jnp.zeros((B, d, N), jnp.float32)
            token_seq = jnp.repeat(jnp.arange(B, dtype=jnp.int32), T)
            y, _ = ssm_scan(xc.reshape(B * T, d).astype(jnp.float32),
                            dt.reshape(B * T, d).astype(jnp.float32),
                            b_t.reshape(B * T, N).astype(jnp.float32),
                            c_t.reshape(B * T, N).astype(jnp.float32),
                            -jnp.exp(self.A_log.value), h0, token_seq)
            y = y.reshape(B, T, d) + xc * self.D.value
            y = y * jax.nn.silu(z)
            return self.out_proj(Tensor(y.astype(x.value.dtype)))
        # ragged serving step: B == 1, the token axis carries the batch
        xin, z = xin[0], z[0]
        tslot = slot.slot_ids[slot.token_seq]     # per-token pool slot
        acc = xin * w[K - 1]
        for s in range(1, K):
            # token s-back: this chunk when chunk_pos >= s, else the
            # row's saved conv tail (age s - chunk_pos at save time)
            prev_new = jnp.pad(xin, ((s, 0), (0, 0)))[:T]
            sidx = jnp.clip(slot.chunk_pos + (K - 1 - s), 0, K - 2)
            prev_old = slot.conv[tslot, sidx]
            prev = jnp.where((slot.chunk_pos >= s)[:, None], prev_new,
                             prev_old)
            acc = acc + prev * w[K - 1 - s]
        xc = jax.nn.silu(acc + self.conv_bias.value)
        dt, b_t, c_t = self._dt_bc(xc)
        # pads become identity state updates BY CONSTRUCTION (see
        # ssm_scan module doc): zero dt -> exp(0)h + 0
        dt = dt * slot.tok_valid[:, None]
        h0 = slot.ssm[slot.slot_ids].astype(jnp.float32)
        y, h_out = ssm_scan(xc.astype(jnp.float32),
                            dt.astype(jnp.float32),
                            b_t.astype(jnp.float32),
                            c_t.astype(jnp.float32),
                            -jnp.exp(self.A_log.value), h0,
                            slot.token_seq)
        slot.ssm = slot.ssm.at[slot.slot_ids].set(
            h_out.astype(slot.ssm.dtype))
        # conv-tail update: slot j holds the input aged K-1-j tokens
        # before the row's NEXT token — from this chunk's last tokens
        # when the row contributed enough, else the old tail shifted
        # by row_len (pad rows: row_len 0 rewrites slot 0 harmlessly)
        ages = jnp.arange(1, K, dtype=jnp.int32)
        idx = jnp.clip(slot.row_end[:, None] - ages[None, :], 0, T - 1)
        from_new = xin[idx]
        old = slot.conv[slot.slot_ids]
        shift = jnp.clip(K - 1 - ages[None, :] + slot.row_len[:, None],
                         0, K - 2)
        from_old = jnp.take_along_axis(old, shift[:, :, None], axis=1)
        keep_new = (ages[None, :] <= slot.row_len[:, None])[:, :, None]
        new_tail = jnp.where(keep_new, from_new, from_old)[:, ::-1]
        slot.conv = slot.conv.at[slot.slot_ids].set(
            new_tail.astype(slot.conv.dtype))
        y = y + xc * self.D.value
        y = y * jax.nn.silu(z)
        return self.out_proj(Tensor(y[None].astype(x.value.dtype))), \
            slot


class SSMBlock(nn.Layer):
    """Pre-norm residual block around one mixer — an SSMMixer, or a
    GPTAttention layer in the hybrid interleave. No separate MLP: the
    SSM mixer carries its own `expand`x inner width (Mamba's block
    shape), and hybrid attention layers ride the same skeleton."""

    def __init__(self, cfg, use_attn=False):
        super().__init__()
        self.ln_1 = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)
        self.mixer = GPTAttention(cfg) if use_attn else SSMMixer(cfg)

    def forward(self, x, cache=None):
        if cache is not None:
            a, cache = self.mixer(self.ln_1(x), cache)
            return x + a, cache
        return x + self.mixer(self.ln_1(x))


class SSMModel(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        w_init = nn.initializer.Normal(0.0, cfg.initializer_range)
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=w_init)
        self.hybrid = cfg.attn_every > 0
        if self.hybrid:
            # only attention needs absolute positions; the pure SSM
            # stack is position-aware through its recurrence alone
            self.wpe = nn.Embedding(cfg.max_position_embeddings,
                                    cfg.hidden_size, weight_attr=w_init)
        self.h = nn.LayerList([
            SSMBlock(cfg, use_attn=cfg.is_attn_layer(i))
            for i in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None, caches=None):
        B, T = input_ids.shape
        x = self.wte(input_ids)
        if self.hybrid:
            if position_ids is None:
                from ..tensor.creation import arange
                position_ids = arange(0, T, dtype="int64").unsqueeze(0)
            x = x + self.wpe(position_ids)
        if caches is None:
            for block in self.h:
                x = block(x)
            return self.ln_f(x)
        new_caches = []
        for i, block in enumerate(self.h):
            x, c = block(x, caches[i])
            new_caches.append(c)
        return self.ln_f(x), new_caches


class SSMForCausalLM(nn.Layer):
    """Causal LM head over the SSM trunk, exposing the SAME serving
    surface as gpt.GPTForCausalLM (see module doc) so
    GenerationEngine/ServingRouter drive it unchanged — only the cache
    strategy underneath differs."""

    def __init__(self, cfg):
        super().__init__()
        self.ssm = SSMModel(cfg)
        self.cfg = cfg

    def forward(self, input_ids, position_ids=None, caches=None):
        out = self.ssm(input_ids, position_ids, caches)
        hidden = out[0] if isinstance(out, tuple) else out
        from ..tensor.linalg import matmul
        logits = matmul(hidden, self.ssm.wte.weight, transpose_y=True)
        if isinstance(out, tuple):
            return logits, out[1]
        return logits

    def loss(self, input_ids, labels):
        from ..nn import functional as F
        logits = self(input_ids)
        V = logits.shape[-1]
        return F.cross_entropy(logits.reshape([-1, V]),
                               labels.reshape([-1]), ignore_index=-100)

    # ---- serving surface (the GPT duck type) -------------------------
    def make_paged_cache(self, n_pages, page_size=16, dtype=None):
        """The strategy-appropriate pool for this model: a
        RecurrentStateCache of n_pages - 1 state slots (the historical
        `n_pages` parameter keeps the engine's capacity arithmetic —
        slot 0 reserved, usable = n_pages - 1), or a HybridCache
        pairing it with a PagedKVCache over the attention layers."""
        from ..inference.cache_strategy import (RecurrentStateCache,
                                                HybridCache)
        cfg = self.cfg
        dtype = dtype or self.ssm.wte.weight.value.dtype
        n_ssm = sum(1 for i in range(cfg.num_layers)
                    if not cfg.is_attn_layer(i))
        rec = RecurrentStateCache(
            n_layers=n_ssm, n_slots=int(n_pages) - 1,
            d_inner=cfg.d_inner, d_state=cfg.d_state,
            d_conv=cfg.d_conv, dtype=dtype, page_size=page_size)
        if not self.ssm.hybrid:
            return rec
        from ..ops.paged_attention import PagedKVCache
        n_attn = cfg.num_layers - n_ssm
        paged = PagedKVCache(n_attn, n_pages, page_size, cfg.num_heads,
                             cfg.hidden_size // cfg.num_heads, dtype)
        return HybridCache(paged, rec)

    def clear_decode_cache(self):
        """Refresh the decode param snapshot after mutating weights
        mid-serving (compiled programs stay valid — params are traced
        arguments)."""
        self._paged_params = None

    def paged_decode_step(self, cache, seq_ids, input_ids, pad_to=None):
        """Eager continuous-batching step (prefill when T > 1, decode
        when T == 1) — a host wrapper over the ragged step, so the
        single-sequence reference oracle and the serving path run the
        SAME compiled program. Returns next-token logits [B, vocab]."""
        del pad_to  # the ragged step buckets its own shapes
        B, T = input_ids.shape
        self._check_pools(cache)
        toks = np.asarray(input_ids.value).astype(np.int32)
        rows = [(sid, toks[i].reshape(-1))
                for i, sid in enumerate(seq_ids)]
        last, _ = self.paged_ragged_step(cache, rows)
        return last

    # ---- ragged mixed prefill+decode step ----------------------------
    RAGGED_TAG = "serve.ragged_step"

    def _check_pools(self, cache):
        rec = getattr(cache, "recurrent", cache)
        dead = rec.conv is None or (self.ssm.hybrid
                                    and cache.paged.k is None)
        if dead:
            raise RuntimeError(
                "this cache was poisoned by an earlier failed step — "
                "rebuild it with make_paged_cache() and re-prefill "
                "in-flight sequences")

    def _poison(self, cache):
        rec = getattr(cache, "recurrent", cache)
        rec.conv = rec.ssm = None
        if self.ssm.hybrid:
            cache.paged.k = cache.paged.v = None

    def _donated_pools(self, cache):
        rec = getattr(cache, "recurrent", cache)
        pools = list(rec.conv) + list(rec.ssm)
        if self.ssm.hybrid:
            pools += list(cache.paged.k) + list(cache.paged.v)
        return pools

    def _ragged_jitted(self):
        """The one jax.jit wrapper every ragged signature lowers
        through (state pools — and, hybrid, kv page pools — donated:
        writes update HBM in place)."""
        fn = getattr(self, "_ragged_jit_fn", None)
        if fn is not None:
            return fn
        from ..jit.api import functional_call

        model = self
        cfg = self.cfg
        ssm_of = {}   # layer index -> index into the state pool lists
        attn_of = {}  # layer index -> index into the kv pool lists
        for i in range(cfg.num_layers):
            if cfg.is_attn_layer(i):
                attn_of[i] = len(attn_of)
            else:
                ssm_of[i] = len(ssm_of)

        def build_slots(kps, vps, convs, ssms, toks, pos, tok_seq,
                        chunk_pos, tok_valid, slot_ids, row_end,
                        row_len, attn_plan, out_idx, temps, top_ks,
                        top_ps, rng_keys):
            # trace-time side effect: exact count of ragged executables
            # traced — the serving engine folds the delta into
            # serve.retraces
            model._ragged_traces = getattr(
                model, "_ragged_traces", 0) + 1
            slots = []
            for i in range(cfg.num_layers):
                if i in attn_of:
                    a = attn_of[i]
                    (tok_pages, tok_in_pages, bounds, pt, blk_pages,
                     blk_seq, blk_start, blk_n) = attn_plan
                    slots.append(RaggedJitSlot(
                        kps[a], vps[a], tok_pages, tok_in_pages, pt,
                        tok_seq, bounds, blk_pages, blk_seq, blk_start,
                        blk_n))
                else:
                    j = ssm_of[i]
                    slots.append(SSMJitSlot(
                        convs[j], ssms[j], tok_seq, chunk_pos,
                        tok_valid, slot_ids, row_end, row_len))
            logits, out_slots = functional_call(
                model, build_slots.params, {}, (Tensor(toks[None, :]),),
                kwargs={"caches": slots,
                        "position_ids": Tensor(pos[None, :])},
                training=False)
            last = logits[0][out_idx]
            nxt_tok = sample_token_rows(
                logits[0], temps[tok_seq], top_ks[tok_seq],
                top_ps[tok_seq], rng_keys[tok_seq], pos)
            nxt = nxt_tok[out_idx]
            ssm_out = [s for s in out_slots if isinstance(s, SSMJitSlot)]
            attn_out = [s for s in out_slots
                        if isinstance(s, RaggedJitSlot)]
            return (last, nxt, nxt_tok, attn_out, ssm_out)

        if self.ssm.hybrid:
            def step(ps, kps, vps, convs, ssms, toks, pos, tok_seq,
                     chunk_pos, tok_valid, slot_ids, row_end, row_len,
                     tok_pages, tok_in_pages, bounds, pt, blk_pages,
                     blk_seq, blk_start, blk_n, out_idx, temps, top_ks,
                     top_ps, rng_keys):
                build_slots.params = ps
                last, nxt, nxt_tok, attn_out, ssm_out = build_slots(
                    kps, vps, convs, ssms, toks, pos, tok_seq,
                    chunk_pos, tok_valid, slot_ids, row_end, row_len,
                    (tok_pages, tok_in_pages, bounds, pt, blk_pages,
                     blk_seq, blk_start, blk_n), out_idx, temps,
                    top_ks, top_ps, rng_keys)
                return (last, nxt, nxt_tok,
                        [s.k for s in attn_out], [s.v for s in attn_out],
                        [s.conv for s in ssm_out],
                        [s.ssm for s in ssm_out])
            donate = (1, 2, 3, 4)
        else:
            def step(ps, convs, ssms, toks, pos, tok_seq, chunk_pos,
                     tok_valid, slot_ids, row_end, row_len, out_idx,
                     temps, top_ks, top_ps, rng_keys):
                build_slots.params = ps
                last, nxt, nxt_tok, _, ssm_out = build_slots(
                    None, None, convs, ssms, toks, pos, tok_seq,
                    chunk_pos, tok_valid, slot_ids, row_end, row_len,
                    None, out_idx, temps, top_ks, top_ps, rng_keys)
                return (last, nxt, nxt_tok,
                        [s.conv for s in ssm_out],
                        [s.ssm for s in ssm_out])
            donate = (1, 2)

        fn = self._ragged_jit_fn = jax.jit(step, donate_argnums=donate)
        return fn

    _RAGGED_ARG_NAMES_PURE = (
        "params", "conv_pools", "ssm_pools", "tokens", "positions",
        "token_seq", "chunk_pos", "tok_valid", "slot_ids", "row_end",
        "row_len", "out_idx", "temperatures", "top_ks", "top_ps",
        "rng_keys")
    _RAGGED_ARG_NAMES_HYBRID = (
        "params", "k_pages", "v_pages", "conv_pools", "ssm_pools",
        "tokens", "positions", "token_seq", "chunk_pos", "tok_valid",
        "slot_ids", "row_end", "row_len", "tok_pages", "tok_in_pages",
        "bounds", "page_table", "blk_pages", "blk_seq", "blk_start",
        "blk_n", "out_idx", "temperatures", "top_ks", "top_ps",
        "rng_keys")

    @staticmethod
    def _ragged_sig(cache, n_tokens, n_rows, width):
        return (int(n_tokens), int(n_rows), int(width)) \
            + tuple(cache.exec_signature())

    def _attn_block_geometry(self, cache, n_tokens, n_rows, width):
        """(QB, S) of the hybrid attention layers' q-block plan — same
        contract as gpt._ragged_block_geometry."""
        from ..ops.pallas.attention_core import MXU_ROWS, choose_q_block
        paged = cache.paged
        fold = max(self.cfg.num_heads // paged.n_heads, 1)
        q_block = choose_q_block(int(n_tokens),
                                 cap=max(MXU_ROWS // fold, 1))
        return int(n_tokens) // q_block, int(n_rows) * int(width)

    def ragged_arg_specs(self, cache, n_tokens, n_rows, width):
        """ShapeDtypeStructs of one ragged-step signature — what
        `warm_ragged` AOT-compiles ahead of traffic."""
        from ..jit.api import state_arrays
        params = getattr(self, "_paged_params", None)
        if params is None:
            params = self._paged_params = state_arrays(self)[0]
        sds = jax.ShapeDtypeStruct
        i32, f32 = jnp.int32, jnp.float32
        rec = getattr(cache, "recurrent", cache)
        S = rec.n_pages
        d, N, K = rec.d_inner, rec.d_state, rec.d_conv
        sdt = rec.conv[0].dtype
        convs = [sds((S, K - 1, d), sdt) for _ in range(rec.n_layers)]
        ssms = [sds((S, d, N), sdt) for _ in range(rec.n_layers)]
        T, B = int(n_tokens), int(n_rows)
        tok = lambda: sds((T,), i32)
        row = lambda: sds((B,), i32)
        pspec = jax.tree.map(lambda a: sds(a.shape, a.dtype), params)
        common_t = (tok(), tok(), tok(), tok(), sds((T,), f32))
        common_b = (row(), row(), row())
        sampling = (row(), sds((B,), f32), sds((B,), i32),
                    sds((B,), f32), sds((B, 2), jnp.uint32))
        if not self.ssm.hybrid:
            return (pspec, convs, ssms) + common_t + common_b + sampling
        paged = cache.paged
        pshape = (paged.n_pages, paged.page_size, paged.n_heads,
                  paged.head_dim)
        pools = [sds(pshape, paged.k[0].dtype)
                 for _ in range(paged.n_layers)]
        qb, s_cap = self._attn_block_geometry(cache, n_tokens, n_rows,
                                              width)
        return ((pspec, pools, list(pools), convs, ssms) + common_t
                + common_b
                + (tok(), tok(), tok(), sds((B, int(width)), i32),
                   sds((qb, s_cap), i32), sds((qb, s_cap), i32),
                   sds((qb, s_cap), i32), sds((qb,), i32))
                + sampling)

    def warm_ragged(self, cache, n_tokens, n_rows, width, inline=False):
        """Single-flight AOT compile of one ragged signature through
        the background warm pipeline (jit/warm.py) — same ledger tag
        and zero-new-executables discipline as the GPT step."""
        from ..jit import warm as _warm
        from ..jit.api import aot_compile
        exec_cache = getattr(self, "_ragged_exec", None)
        if exec_cache is None:
            exec_cache = self._ragged_exec = {}
        sig = self._ragged_sig(cache, n_tokens, n_rows, width)
        specs = self.ragged_arg_specs(cache, n_tokens, n_rows, width)
        jitted = self._ragged_jitted()
        names = self._RAGGED_ARG_NAMES_HYBRID if self.ssm.hybrid \
            else self._RAGGED_ARG_NAMES_PURE

        def thunk():
            return aot_compile(jitted, specs, tag=self.RAGGED_TAG,
                               arg_names=names)

        return _warm.submit_cached(exec_cache, sig, self.RAGGED_TAG,
                                   thunk, inline=inline)

    def paged_ragged_step(self, cache, rows, pad_to_tokens=None,
                          pad_to_rows=None, sampling=None,
                          return_per_token=False):
        """ONE continuous-batching step over mixed rows (decode rows
        carry one token, prefill-chunk rows a prompt slice), advanced
        in a single jitted program over the Pallas selective-scan
        kernel — each row's conv tail + state matrix gathered from its
        slot, updated, scattered back; pad tokens are identity state
        updates by construction. Same contract as
        gpt.paged_ragged_step (padded shapes pin the executable,
        `sampling` the per-row config, `return_per_token` the
        speculative verify lane — unused here: the recurrent strategy
        refuses speculation at engine construction)."""
        self._check_pools(cache)
        limit = self.cfg.max_position_embeddings
        over = [s for s, t in rows
                if cache.length(s) + len(t) > limit]
        if over:
            raise ValueError(
                f"sequences {over!r} would exceed "
                f"max_position_embeddings={limit}; free them or raise "
                "the limit")
        from ..jit.api import state_arrays
        params = getattr(self, "_paged_params", None)
        if params is None:
            params = self._paged_params = state_arrays(self)[0]
        hybrid = self.ssm.hybrid
        rec = getattr(cache, "recurrent", cache)
        # the cache lock holds from the plan through the donated-pool
        # swap (see gpt._paged_decode_jit): another engine sharing the
        # pool must see pre- or post-step buffers, never the carcass
        with cache.lock:
            lens = [(s, len(t)) for s, t in rows]
            t_real = sum(n for _, n in lens)
            T = int(pad_to_tokens) if pad_to_tokens else max(t_real, 1)
            B = int(pad_to_rows) if pad_to_rows else max(len(rows), 1)
            plan = cache.plan_step(lens, pad_to_tokens=T, pad_to_rows=B)
            if hybrid:
                aplan = cache.plan_ragged(lens, pad_to_tokens=T,
                                          pad_to_rows=B,
                                          q_heads=self.cfg.num_heads)
                W = aplan["page_table"].shape[1]
            else:
                W = 1
            toks = np.zeros((T,), np.int32)
            off = 0
            for _, t in rows:
                toks[off:off + len(t)] = \
                    np.asarray(t, np.int32).reshape(-1)
                off += len(t)
            entry = getattr(self, "_ragged_exec", {}).get(
                self._ragged_sig(cache, T, B, W))
            if entry is None:
                entry = self.warm_ragged(cache, T, B, W,
                                         inline=True).result()
            compiled, _ = entry
            if sampling is None:
                sampling = (np.zeros((B,), np.float32),
                            np.zeros((B,), np.int32),
                            np.ones((B,), np.float32),
                            np.zeros((B, 2), np.uint32))
            temps, top_ks, top_ps, rng_keys = sampling
            common_t = (jnp.asarray(toks),
                        jnp.asarray(plan["positions"]),
                        jnp.asarray(plan["token_seq"]),
                        jnp.asarray(plan["chunk_pos"]),
                        jnp.asarray(plan["tok_valid"]))
            common_b = (jnp.asarray(plan["slot_ids"]),
                        jnp.asarray(plan["row_end"]),
                        jnp.asarray(plan["row_len"]))
            tail = (jnp.asarray(plan["out_idx"]), jnp.asarray(temps),
                    jnp.asarray(top_ks), jnp.asarray(top_ps),
                    jnp.asarray(rng_keys))
            if hybrid:
                args = ((params, list(cache.paged.k),
                         list(cache.paged.v), list(rec.conv),
                         list(rec.ssm)) + common_t + common_b
                        + (jnp.asarray(aplan["tok_pages"]),
                           jnp.asarray(aplan["tok_in_pages"]),
                           jnp.asarray(aplan["bounds"]),
                           jnp.asarray(aplan["page_table"]),
                           jnp.asarray(aplan["blk_pages"]),
                           jnp.asarray(aplan["blk_seq"]),
                           jnp.asarray(aplan["blk_start"]),
                           jnp.asarray(aplan["blk_n"])) + tail)
            else:
                args = ((params, list(rec.conv), list(rec.ssm))
                        + common_t + common_b + tail)
            try:
                out = compiled(*args)
            except Exception as e:
                # donation only consumes the pools once the program
                # EXECUTES; a dispatch failure before that leaves them
                # valid
                if not any(getattr(a, "is_deleted", lambda: False)()
                           for a in self._donated_pools(cache)):
                    raise
                self._poison(cache)
                raise RuntimeError(
                    "jitted ragged SSM step failed AFTER its state "
                    "pools were donated — this cache is unrecoverable; "
                    "rebuild it with make_paged_cache() and re-prefill "
                    "in-flight sequences") from e
            if hybrid:
                last, nxt, nxt_tok, new_k, new_v, new_c, new_s = out
                cache.paged.k = list(new_k)
                cache.paged.v = list(new_v)
            else:
                last, nxt, nxt_tok, new_c, new_s = out
            rec.conv = list(new_c)
            rec.ssm = list(new_s)
            for s, t in rows:
                cache.advance(s, len(t))
            n = plan["n_rows"]
        if return_per_token:
            return Tensor(last[:n]), nxt[:n], nxt_tok
        return Tensor(last[:n]), nxt[:n]


def ssm_tiny(vocab=1024):
    return SSMConfig(vocab_size=vocab, hidden_size=64, num_layers=2,
                     d_state=8, d_conv=4, expand=2,
                     max_position_embeddings=128)


def ssm_hybrid_tiny(vocab=1024):
    """Tiny hybrid: layer 1 of 2 is attention (attn_every=2)."""
    return SSMConfig(vocab_size=vocab, hidden_size=64, num_layers=2,
                     d_state=8, d_conv=4, expand=2, attn_every=2,
                     num_heads=4, max_position_embeddings=128)
