"""Global RNG state.

Parity: paddle/fluid/framework/generator.cc (paddle.seed / rng state).
TPU-native design: JAX PRNG is functional (threaded keys), so we keep one
global key that is split per draw in eager mode. Inside a traced/jitted
region (jit.to_static, trainer steps), drawing from a Python global would
bake the randomness into the compilation; `rng_scope` therefore lets the
functional path thread an explicit key — each draw folds in a counter, so
a given trace is deterministic in the key argument (vary the key per step).
"""
import threading

import jax
import jax.numpy as jnp

__all__ = ["seed", "get_rng_state", "set_rng_state", "split_key", "rng_scope"]


class _RNGState(threading.local):
    # `key` is created lazily: building it here would run an eager op at
    # import time, initializing the JAX backend while Python's import
    # lock is held — which breaks PJRT plugin discovery (the plugin's
    # own module import gets skipped and its platform name vanishes
    # from the backend list). Observed with the axon TPU plugin.
    def __init__(self):
        self.key = None
        self.scope_key = None
        self.scope_counter = 0


_state = _RNGState()


def _key():
    if _state.key is None:
        _state.key = jax.random.key(0)
    return _state.key


def seed(s):
    _state.key = jax.random.key(int(s))
    return _state.key


def get_rng_state():
    return _key()


def set_rng_state(key):
    _state.key = key


class rng_scope:
    """Bind an explicit key for draws inside a traced function."""

    def __init__(self, key):
        self.key = key

    def __enter__(self):
        self.prev = (_state.scope_key, _state.scope_counter)
        _state.scope_key = self.key
        _state.scope_counter = 0
        return self

    def __exit__(self, *exc):
        _state.scope_key, _state.scope_counter = self.prev
        return False


def split_key():
    """Return a fresh PRNG key for one random draw."""
    if _state.scope_key is not None:
        _state.scope_counter += 1
        return jax.random.fold_in(_state.scope_key, _state.scope_counter)
    _state.key, sub = jax.random.split(_key())
    return sub
