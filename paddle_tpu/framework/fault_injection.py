"""Deterministic fault injection for robustness tests.

Production fault tolerance is only trustworthy when the failure paths
are *exercised*: a kill mid-checkpoint, a disk returning EIO, a shard
file truncated by a crashed writer, a gradient going NaN. This module
is the ONE switchboard every such test drives — instrumented framework
code calls `fire("<site>")` at named fault sites, and a declarative
spec (the `PADDLE_TPU_FAULT_SPEC` env var, or `configure()` in-process)
decides which hit of which site does what. With no spec configured a
fire is two dict lookups — the sites stay compiled into production
code paths at zero cost, so the tested path IS the shipped path.

Spec grammar (semicolon- or comma-separated entries):

    <action>@<site>[#<n>][=<arg>]

    action   kill      SIGKILL this process (no cleanup, no atexit —
                       a preempted host)
             exit      os._exit(<arg>, default 1) — a crash that skips
                       Python teardown but flushes nothing
             eio       raise OSError(EIO) at the site
             delay     sleep <arg> seconds (default 0.1) — a slow disk
                       or a congested writer
             truncate  cut the site's file to half (or <arg> bytes) —
                       a torn write
             corrupt   flip a byte mid-file — silent media corruption
             nan       soft action: the SITE OWNER implements it (the
                       train steps poison a float batch leaf so the
                       whole gradient goes non-finite)
             oom       soft action: the site owner raises a synthetic
                       XLA-shaped RESOURCE_EXHAUSTED from inside its
                       real dispatch try-block, so the memory
                       observatory's forensics path is exercised
                       end-to-end (catch, bundle dump, DeviceOOMError)
    site     dotted name the instrumented code fires, e.g.
             ckpt.write / ckpt.commit / ckpt.serialize / train.step
    #<n>     fire only on the n-th hit of the site (1-based, per
             process, counted from configure()); default: every hit
    =<arg>   action argument (seconds for delay, bytes for truncate,
             exit code for exit)

Examples:

    kill@ckpt.write#2            die while writing the 2nd shard file
    eio@ckpt.write               every write fails with EIO
    delay@ckpt.write=0.5         slow writer: each file write +0.5 s
    corrupt@ckpt.commit          damage the manifest before commit
    kill@train.step#50           preemption at optimizer step 50
    nan@train.step#3             gradients of step 3 are NaN
    oom@train.step#5             device OOM raised at step 5's dispatch

Sites currently instrumented: `train.step` (TrainStep /
HybridTrainStep dispatch), `ckpt.snapshot`, `ckpt.serialize`,
`ckpt.write` (per shard file, path-aware), `ckpt.commit` (before the
atomic rename). Firing is recorded as a `fault_injected` flight-
recorder event, so an injected failure is attributable in the debug
bundle it causes. See docs/FAULT_TOLERANCE.md.
"""
import errno
import os
import signal
import threading
import time

__all__ = ["Fault", "parse_spec", "configure", "fire", "active",
           "hit_counts", "SOFT_ACTIONS"]

_ENV = "PADDLE_TPU_FAULT_SPEC"
ACTIONS = ("kill", "exit", "eio", "delay", "truncate", "corrupt", "nan",
           "oom")
# actions fire() only REPORTS back to the caller (the site owner
# implements the effect) — everything else executes right here
SOFT_ACTIONS = ("nan", "oom")

_lock = threading.Lock()
_state = {"faults": (), "counts": {}, "env_seen": None}


class Fault:
    """One parsed spec entry."""
    __slots__ = ("action", "site", "nth", "arg", "raw")

    def __init__(self, action, site, nth=None, arg=None, raw=""):
        self.action = action
        self.site = site
        self.nth = nth
        self.arg = arg
        self.raw = raw or f"{action}@{site}"

    def __repr__(self):
        return f"Fault({self.raw!r})"


def parse_spec(text):
    """`PADDLE_TPU_FAULT_SPEC` text -> list of Fault. Raises ValueError
    on bad grammar (a mistyped fault spec must fail the test loudly,
    not silently inject nothing)."""
    faults = []
    for raw in (text or "").replace(";", ",").split(","):
        raw = raw.strip()
        if not raw:
            continue
        body, arg = (raw.split("=", 1) + [None])[:2]
        body, nth = (body.split("#", 1) + [None])[:2]
        if "@" not in body:
            raise ValueError(f"fault entry {raw!r}: expected "
                             "<action>@<site>[#n][=arg]")
        action, site = body.split("@", 1)
        action, site = action.strip(), site.strip()
        if action not in ACTIONS:
            raise ValueError(f"fault entry {raw!r}: unknown action "
                             f"{action!r} (one of {ACTIONS})")
        if not site:
            raise ValueError(f"fault entry {raw!r}: empty site")
        if nth is not None:
            nth = int(nth)
            if nth < 1:
                raise ValueError(f"fault entry {raw!r}: #n is 1-based")
        if action == "delay":
            arg = float(arg) if arg is not None else 0.1
        elif action in ("exit", "truncate") and arg is not None:
            arg = int(arg)
        faults.append(Fault(action, site, nth, arg, raw))
    return faults


def configure(spec=None):
    """Arm the injector from `spec` (str, list of Fault, or None to
    read PADDLE_TPU_FAULT_SPEC) and reset the per-site hit counters.
    Returns the active fault list. `configure("")` disarms."""
    if spec is None:
        spec = os.environ.get(_ENV, "")
    faults = tuple(spec) if isinstance(spec, (list, tuple)) \
        else tuple(parse_spec(spec))
    with _lock:
        _state["faults"] = faults
        _state["counts"] = {}
        _state["env_seen"] = os.environ.get(_ENV)
    return list(faults)


def _refresh():
    """Pick up an env-var change (tests flip the spec between phases
    without re-importing); counters reset with the new spec."""
    env = os.environ.get(_ENV)
    if env != _state["env_seen"]:
        configure(env or "")


def active():
    """True when any fault is armed (after syncing with the env var)."""
    _refresh()
    return bool(_state["faults"])


def hit_counts():
    """Copy of the per-site hit counters (diagnostics/tests)."""
    with _lock:
        return dict(_state["counts"])


def fire(site, path=None):
    """Count one hit of `site` and execute every matching fault.
    Returns the list of SOFT action names the caller must implement
    (e.g. ["nan"]), or None when nothing soft matched. Hard actions
    (kill/exit/eio/delay/truncate/corrupt) execute here — eio raises.
    With no spec armed this is two dict reads; safe on hot paths."""
    if not _state["faults"] and _state["env_seen"] == os.environ.get(_ENV):
        return None
    _refresh()
    if not _state["faults"]:
        return None
    with _lock:
        n = _state["counts"][site] = _state["counts"].get(site, 0) + 1
        matched = [f for f in _state["faults"]
                   if f.site == site and (f.nth is None or f.nth == n)]
    soft = []
    for f in matched:
        _record(f, site, n, path)
        if f.action in SOFT_ACTIONS:
            soft.append(f.action)
        else:
            _execute(f, site, path)
    return soft or None


def _record(fault, site, n, path):
    try:
        from ..profiler import flight_recorder as _flight
        _flight.record_event("fault_injected", action=fault.action,
                             site=site, hit=n, spec=fault.raw,
                             path=str(path) if path else None)
    except Exception:
        pass  # telemetry must never mask the injected fault itself


def _execute(fault, site, path):
    a = fault.action
    if a == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    elif a == "exit":
        os._exit(fault.arg if fault.arg is not None else 1)
    elif a == "delay":
        time.sleep(fault.arg)
    elif a == "eio":
        raise OSError(errno.EIO,
                      f"injected EIO at fault site {site!r} ({fault.raw})")
    elif a == "truncate":
        if path and os.path.isfile(path):
            size = os.path.getsize(path)
            keep = fault.arg if fault.arg is not None else size // 2
            with open(path, "r+b") as f:
                f.truncate(max(0, keep))
    elif a == "corrupt":
        if path and os.path.isfile(path):
            size = os.path.getsize(path)
            if size:
                with open(path, "r+b") as f:
                    f.seek(size // 2)
                    b = f.read(1) or b"\x00"
                    f.seek(size // 2)
                    f.write(bytes([b[0] ^ 0xFF]))


# arm from the env at import: subprocess tests set PADDLE_TPU_FAULT_SPEC
# before launching the worker, and the worker must not need to know
configure()
