"""GPT model family — the flagship for BASELINE.json's headline config
("GPT-3 6.7B with fleet hybrid-parallel"). API mirrors PaddleNLP's GPT
(reference trains it via python/paddle/distributed/fleet); architecture is
TPU-first:

- pre-norm decoder blocks, bias-less where harmless, bf16-friendly
- attention through F.scaled_dot_product_attention → Pallas flash kernel
- shapes kept static & MXU-aligned (head_dim multiple of 128 advised)
- `parallel_config` marks how each weight shards over the fleet mesh
  (mp column/row, dp replicated) — consumed by distributed.fleet.
"""
import math
import os

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from .. import nn
from ..nn import functional as F
from ..nn import initializer as I

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt_tiny",
           "gpt_small", "gpt_medium", "gpt_1p3b", "gpt_6p7b",
           "gpt_moe"]


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=None,
                 max_position_embeddings=1024, dropout=0.0,
                 layer_norm_epsilon=1e-5, initializer_range=0.02,
                 use_bias=True, scan_layers=True, scan_remat=False,
                 sequence_parallel=False, num_experts=0, moe_every=2,
                 moe_top_k=2, moe_capacity_factor=1.25):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.dropout = dropout
        self.layer_norm_epsilon = layer_norm_epsilon
        self.initializer_range = initializer_range
        self.use_bias = use_bias
        # scan_layers: under jit, run the homogeneous block stack as one
        # lax.scan over stacked per-layer params — the block is traced
        # and compiled ONCE instead of num_layers times (deep models
        # otherwise pay minutes of XLA compile). scan_remat wraps the
        # scan body in jax.checkpoint (recompute activations in backward).
        self.scan_layers = scan_layers
        self.scan_remat = scan_remat
        # sequence_parallel: shard the sequence dim over the 'sp' mesh
        # axis; attention runs as ring attention (K/V shards rotate via
        # ppermute, online-softmax merge) — exact, long-context capable
        self.sequence_parallel = sequence_parallel
        # num_experts > 0: every `moe_every`-th block swaps its MLP for
        # an expert-parallel MoELayer (experts shard over 'ep'); the
        # heterogeneous stack disables the scan-over-layers path
        self.num_experts = num_experts
        self.moe_every = moe_every
        self.moe_top_k = moe_top_k
        self.moe_capacity_factor = moe_capacity_factor


class StaticCacheSlot:
    """One layer's static KV cache: preallocated k/v [B, L, H, D] plus the
    write position (traced scalar). See GPTAttention._forward_static_cache."""

    __slots__ = ("k", "v", "pos")

    def __init__(self, k, v, pos):
        self.k = k
        self.v = v
        self.pos = pos


class PagedCacheSlot:
    """One layer's view of a shared PagedKVCache for continuous-batching
    decode: `cache` is the ops.paged_attention.PagedKVCache, `seq_ids`
    the batch rows, `views` the per-step (page_table, lengths)."""

    __slots__ = ("cache", "layer", "seq_ids", "views")

    def __init__(self, cache, layer, seq_ids, views):
        self.cache = cache
        self.layer = layer
        self.seq_ids = seq_ids
        self.views = views


class PagedJitSlot:
    """Traced twin of PagedCacheSlot for the fully-jitted decode step:
    one layer's k/v page pools (traced, donated by the caller) plus the
    host-planned write coordinates and read views (see
    PagedKVCache.plan_decode)."""

    __slots__ = ("k", "v", "pages", "in_pages", "pt", "lens")

    def __init__(self, k, v, pages, in_pages, pt, lens):
        self.k = k
        self.v = v
        self.pages = pages
        self.in_pages = in_pages
        self.pt = pt
        self.lens = lens


class RaggedJitSlot:
    """One layer's state for the fully-jitted RAGGED step (the mixed
    prefill+decode program over the Pallas kernel in
    ops/pallas/paged_attention.py): traced/donated k/v pools plus the
    host plan from PagedKVCache.plan_ragged — per-token scatter
    coordinates and causal bounds, per-row page tables, and the
    q-block kv-page walk (blk_*) the kernel's double-buffered DMA loop
    follows."""

    __slots__ = ("k", "v", "tok_pages", "tok_in_pages", "page_table",
                 "token_seq", "bounds", "blk_pages", "blk_seq",
                 "blk_start", "blk_n")

    def __init__(self, k, v, tok_pages, tok_in_pages, page_table,
                 token_seq, bounds, blk_pages=None, blk_seq=None,
                 blk_start=None, blk_n=None):
        self.k = k
        self.v = v
        self.tok_pages = tok_pages
        self.tok_in_pages = tok_in_pages
        self.page_table = page_table
        self.token_seq = token_seq
        self.bounds = bounds
        self.blk_pages = blk_pages
        self.blk_seq = blk_seq
        self.blk_start = blk_start
        self.blk_n = blk_n


def sample_token_rows(last, temps, top_ks, top_ps, rng_keys, positions):
    """On-device per-row sampling for the ragged serving step: one
    fixed-shape program covers every request's sampling config, so
    admit/evict (and mixed greedy/sampled batches) never change the
    compiled signature.

    last [B, V] next-token logits; temps [B] f32 (<= 0 selects the
    greedy argmax lane BIT-EXACTLY — the pre-sampling serving
    behavior); top_ks [B] i32 (0 disables); top_ps [B] f32 (1.0
    disables); rng_keys [B, 2] u32 per-SEQUENCE base PRNG keys;
    positions [B] i32 absolute position of each row's sampled token.

    The draw key is fold_in(base_key, position): a function of the
    request's seed and the token index ONLY — which batch the row
    landed in, what its neighbors were, or which ENGINE decoded it
    (prefill/decode disaggregation) cannot change the sample, so a
    handed-off chain decodes token-for-token equal to a single-engine
    run and a fixed seed reproduces exactly. Returns [B] int32."""
    import jax
    V = last.shape[-1]
    greedy = jnp.argmax(last, axis=-1).astype(jnp.int32)

    def _sampled(_):
        arr = last.astype(jnp.float32) \
            / jnp.maximum(temps[:, None], 1e-6)
        # per-row top-k: the kth-largest value is the row's floor
        # (k <= 0 keeps everything). One descending sort serves both
        # filters.
        srt = jnp.sort(arr, axis=-1)[:, ::-1]
        k_eff = jnp.clip(jnp.where(top_ks > 0, top_ks, V), 1, V)
        kth = jnp.take_along_axis(srt, (k_eff - 1)[:, None], axis=-1)
        arr = jnp.where(arr < kth, jnp.float32(-1e30), arr)
        # per-row nucleus over the top-k-masked logits: keep the
        # smallest prefix of the sorted probs reaching top_p (a token
        # stays iff the mass BEFORE it is < top_p) — top_p = 1.0 keeps
        # every survivor
        srt2 = jnp.sort(arr, axis=-1)[:, ::-1]
        p_srt = jax.nn.softmax(srt2, axis=-1)
        before = jnp.cumsum(p_srt, axis=-1) - p_srt
        keep = before < top_ps[:, None]
        thresh = jnp.min(jnp.where(keep, srt2, jnp.inf), axis=-1,
                         keepdims=True)
        arr = jnp.where(arr >= thresh, arr, jnp.float32(-1e30))
        step_keys = jax.vmap(jax.random.fold_in)(rng_keys, positions)
        sampled = jax.vmap(jax.random.categorical)(step_keys, arr)
        return jnp.where(temps <= 0.0, greedy,
                         sampled.astype(jnp.int32))

    # runtime branch, ONE executable: an all-greedy batch (the default
    # serving workload) skips the two [B, V] sorts + softmax/cumsum at
    # execution time instead of paying for a lane jnp.where would
    # force XLA to materialize; a mixed batch takes the sampled branch
    # and its greedy rows still ride the bit-exact argmax lane
    return jax.lax.cond(jnp.any(temps > 0.0), _sampled,
                        lambda _: greedy, None)


def sampling_key_data(seed):
    """Host-side uint32[2] PRNG key data for `seed` (the threefry key
    layout jax.random.PRNGKey produces) — no device op at submit."""
    seed = int(seed)
    return np.array([(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF],
                    np.uint32)


def _remat_policy(scan_remat):
    """Map cfg.scan_remat to a jax.checkpoint policy. True → full
    recompute (policy None). "dots" → save non-batch matmul outputs.
    "names" → save exactly the three big per-block matmul outputs (qkv,
    attn out, ffn up — tagged via checkpoint_name below), recompute the
    cheap rest; unlike "dots" this skips the flash-attention internals
    and keeps HBM bounded at ~10*B*T*H bf16 per block."""
    import jax
    if scan_remat == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if scan_remat == "names":
        return jax.checkpoint_policies.save_only_these_names(
            "gpt_qkv", "gpt_attn_out", "gpt_ffn_in")
    return None


def _ckpt_name(t, name):
    """Tag a traced activation as a named remat save point. No-op in
    eager mode (concrete arrays go through the tape; re-wrapping would
    orphan them from it)."""
    import jax
    if isinstance(t.value, jax.core.Tracer):
        from jax.ad_checkpoint import checkpoint_name
        return Tensor(checkpoint_name(t.value, name))
    return t


class GPTAttention(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        h, nh = cfg.hidden_size, cfg.num_heads
        self.num_heads = nh
        self.head_dim = h // nh
        w_init = nn.initializer.Normal(0.0, cfg.initializer_range)
        battr = None if cfg.use_bias else False
        self.qkv_proj = nn.Linear(h, 3 * h,
                                  weight_attr=w_init, bias_attr=battr)
        self.out_proj = nn.Linear(h, h, weight_attr=w_init, bias_attr=battr)
        self.dropout = cfg.dropout
        self.sequence_parallel = cfg.sequence_parallel

    def forward(self, x, cache=None):
        B, T, H = x.shape
        qkv = _ckpt_name(self.qkv_proj(x), "gpt_qkv")
        qkv = qkv.reshape([B, T, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(axis=2)
        if isinstance(cache, StaticCacheSlot):
            return self._forward_static_cache(x, q, k, v, cache)
        if isinstance(cache, RaggedJitSlot):
            return self._forward_paged_ragged(x, q, k, v, cache)
        if isinstance(cache, PagedJitSlot):
            return self._forward_paged_jit(x, q, k, v, cache)
        if isinstance(cache, PagedCacheSlot):
            return self._forward_paged_cache(x, q, k, v, cache)
        if cache is not None:  # legacy growing (k, v) protocol
            from ..tensor.manipulation import concat
            k = concat([cache[0], k], axis=1)
            v = concat([cache[1], v], axis=1)
            cache = (k, v)
        if self.sequence_parallel and cache is None:
            from ..ops.ring_attention import ring_attention
            out = ring_attention(q, k, v, causal=True)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True,
                dropout_p=self.dropout if self.training else 0.0)
        out = _ckpt_name(out.reshape([B, T, H]), "gpt_attn_out")
        out = self.out_proj(out)
        return (out, cache) if cache is not None else out

    def _forward_static_cache(self, x, q, k, v, cache):
        """Decode/prefill against a preallocated [B, L, H, D] KV buffer:
        write the T new keys/values at position `pos` (dynamic slice
        update), attend q over the full buffer with a `col <= pos + row`
        mask. Static shapes throughout, so generate() compiles exactly
        two programs (prefill + scanned decode) regardless of length —
        replaces the per-token concat that recompiled every step."""
        import jax
        B, T, H = x.shape
        kb, vb, pos = cache.k.value, cache.v.value, cache.pos
        kb = jax.lax.dynamic_update_slice(kb, k.value, (0, pos, 0, 0))
        vb = jax.lax.dynamic_update_slice(vb, v.value, (0, pos, 0, 0))
        L = kb.shape[1]
        scale = 1.0 / math.sqrt(self.head_dim)
        s = jnp.einsum("bthd,blhd->bhtl", q.value.astype(jnp.float32),
                       kb.astype(jnp.float32)) * scale
        cols = jnp.arange(L)[None, None, None, :]
        rows = jnp.arange(T)[None, None, :, None]
        s = jnp.where(cols <= pos + rows, s, jnp.float32(-1e30))
        p = jax.nn.softmax(s, axis=-1).astype(vb.dtype)
        out = jnp.einsum("bhtl,blhd->bthd", p, vb)
        out = self.out_proj(Tensor(out.reshape(B, T, H).astype(
            x.value.dtype)))
        return out, StaticCacheSlot(Tensor(kb), Tensor(vb), pos)


    def _forward_paged_jit(self, x, q, k, v, slot):
        """Traced decode step (T==1) over the paged pools: one batched
        scatter writes every sequence's new k/v row into its page, then
        one paged_attention gather reads each row's own history. All of
        it lives inside the caller's single jitted program."""
        from ..ops.paged_attention import paged_attention
        B, T, H = x.shape
        kd = slot.k.dtype
        slot.k = slot.k.at[slot.pages, slot.in_pages].set(
            k.value[:, 0].astype(kd))
        slot.v = slot.v.at[slot.pages, slot.in_pages].set(
            v.value[:, 0].astype(kd))
        out = paged_attention(q.value[:, 0], slot.k, slot.v, slot.pt,
                              slot.lens + 1)
        out = self.out_proj(Tensor(out.reshape(B, 1, H).astype(
            x.value.dtype)))
        return out, slot

    def _forward_paged_ragged(self, x, q, k, v, slot):
        """Traced RAGGED step over the paged pools: one batched scatter
        writes every token's k/v row into its planned (page, slot), then
        ONE Pallas ragged-paged-attention call reads each token's own
        history under its causal bound — decode rows and prefill chunks
        in the same program, pad tokens (bound 0) skipped outright."""
        from ..ops.pallas.paged_attention import ragged_paged_attention
        B, T, H = x.shape  # B == 1: the token axis carries the batch
        kd = slot.k.dtype
        slot.k = slot.k.at[slot.tok_pages, slot.tok_in_pages].set(
            k.value[0].astype(kd))
        slot.v = slot.v.at[slot.tok_pages, slot.tok_in_pages].set(
            v.value[0].astype(kd))
        plan = (None if slot.blk_pages is None else
                (slot.blk_pages, slot.blk_seq, slot.blk_start,
                 slot.blk_n))
        out = ragged_paged_attention(
            q.value[0], slot.k, slot.v, slot.page_table, slot.token_seq,
            slot.bounds, block_plan=plan)
        out = self.out_proj(Tensor(out.reshape(1, T, H).astype(
            x.value.dtype)))
        return out, slot

    def _forward_paged_cache(self, x, q, k, v, cache):
        """Continuous-batching path: write this step's k/v into the
        shared page pool, attend each row against its own paged history.
        Prefill (T>1) runs causal attention over the new tokens PLUS the
        paged history; decode (T==1) is one paged_attention gather."""
        from ..ops.paged_attention import paged_attention
        B, T, H = x.shape
        pc = cache.cache
        for i, sid in enumerate(cache.seq_ids):
            pc.extend(sid, cache.layer, k.value[i], v.value[i])
        # lengths are committed (advance) only after the LAST layer, so
        # batch_views here reports the pre-step history; the T tokens
        # this layer just wrote are added explicitly
        pt, old_lens = pc.batch_views(cache.seq_ids)
        if T == 1:
            out = paged_attention(q.value[:, 0], pc.k[cache.layer],
                                  pc.v[cache.layer], pt, old_lens + 1)
            out = out[:, None]
        else:
            # prefill: query position t sees history + new tokens <= t
            outs = [paged_attention(q.value[:, t], pc.k[cache.layer],
                                    pc.v[cache.layer], pt,
                                    old_lens + t + 1)
                    for t in range(T)]
            out = jnp.stack(outs, axis=1)
        if cache.layer == pc.n_layers - 1:
            for sid in cache.seq_ids:
                pc.advance(sid, T)
        out = self.out_proj(Tensor(out.reshape(B, T, H).astype(
            x.value.dtype)))
        return out, cache


class GPTMLP(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        w_init = nn.initializer.Normal(0.0, cfg.initializer_range)
        battr = None if cfg.use_bias else False
        self.fc_in = nn.Linear(cfg.hidden_size, cfg.intermediate_size,
                               weight_attr=w_init, bias_attr=battr)
        self.fc_out = nn.Linear(cfg.intermediate_size, cfg.hidden_size,
                                weight_attr=w_init, bias_attr=battr)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x):
        h = _ckpt_name(self.fc_in(x), "gpt_ffn_in")
        return self.drop(self.fc_out(F.gelu(h, approximate=True)))


class GPTBlock(nn.Layer):
    def __init__(self, cfg, use_moe=False):
        super().__init__()
        self.ln_1 = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)
        self.attn = GPTAttention(cfg)
        self.ln_2 = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)
        if use_moe:
            from ..incubate.moe import MoELayer
            self.mlp = MoELayer(cfg.hidden_size, cfg.intermediate_size,
                                num_experts=cfg.num_experts,
                                top_k=cfg.moe_top_k,
                                capacity_factor=cfg.moe_capacity_factor)
        else:
            self.mlp = GPTMLP(cfg)

    def forward(self, x, cache=None):
        if cache is not None:
            a, cache = self.attn(self.ln_1(x), cache)
            x = x + a
        else:
            x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return (x, cache) if cache is not None else x


class GPTModel(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        w_init = nn.initializer.Normal(0.0, cfg.initializer_range)
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=w_init)
        self.wpe = nn.Embedding(cfg.max_position_embeddings,
                                cfg.hidden_size, weight_attr=w_init)
        self.drop = nn.Dropout(cfg.dropout)
        self.h = nn.LayerList([
            GPTBlock(cfg, use_moe=(cfg.num_experts > 0
                                   and i % cfg.moe_every
                                   == cfg.moe_every - 1))
            for i in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None, caches=None):
        B, T = input_ids.shape
        if position_ids is None:
            if caches is not None and isinstance(caches[0],
                                                 StaticCacheSlot):
                pos_arr = caches[0].pos + jnp.arange(T, dtype=jnp.int32)
                position_ids = Tensor(pos_arr[None, :])
            elif caches is not None and isinstance(caches[0],
                                                   PagedJitSlot):
                # pre-write length IS the new token's position
                position_ids = Tensor(
                    caches[0].lens[:, None].astype(jnp.int32))
            elif caches is not None and isinstance(caches[0],
                                                   PagedCacheSlot):
                pc = caches[0].cache
                lens = np.array([pc.length(s)
                                 for s in caches[0].seq_ids])[:, None]
                position_ids = Tensor(jnp.asarray(
                    lens + np.arange(T), jnp.int64))
            else:
                from ..tensor.creation import arange
                start = 0 if caches is None else caches[0][0].shape[1]
                position_ids = arange(start, start + T, dtype="int64"
                                      ).unsqueeze(0)
        x = self.drop(self.wte(input_ids) + self.wpe(position_ids))
        if caches is None and self._use_scan(x):
            x = self._scan_blocks(x)
            return self.ln_f(x)
        new_caches = []
        remat_fn = self._unrolled_remat(x) if caches is None else None
        for i, block in enumerate(self.h):
            if caches is not None:
                x, c = block(x, caches[i])
                new_caches.append(c)
            elif remat_fn is not None:
                x = remat_fn(block, x)
            else:
                x = block(x)
        x = self.ln_f(x)
        return (x, new_caches) if caches is not None else x

    def _unrolled_remat(self, x):
        """Per-block jax.checkpoint for the unrolled (scan_layers=False)
        path, honoring cfg.scan_remat exactly like _scan_blocks — without
        it, unrolled deep models lose memory control entirely. Only under
        trace: the eager tape manages its own storage."""
        import jax
        if not self.cfg.scan_remat or not isinstance(x.value,
                                                     jax.core.Tracer):
            return None
        policy = _remat_policy(self.cfg.scan_remat)

        def call(block, h):
            if not isinstance(block.mlp, GPTMLP):
                # MoE block: MoELayer records its aux loss on the layer
                # as a side channel; under jax.checkpoint that tracer
                # would leak out of the inner trace — run it unwrapped
                # (same reason _use_scan excludes MoE stacks)
                return block(h)
            fn = jax.checkpoint(lambda hv: block(Tensor(hv)).value,
                                prevent_cse=False, policy=policy)
            return Tensor(fn(h.value))

        return call

    def _use_scan(self, x):
        """Scan only under trace (the eager tape can't see through a raw
        lax.scan) and only when blocks draw no per-layer RNG (dropout
        layers are inert in eval mode, so eval always qualifies)."""
        import jax
        return (self.cfg.scan_layers and self.cfg.num_layers > 1
                and self.cfg.num_experts == 0  # MoE blocks: not uniform
                and (self.cfg.dropout == 0.0 or not self.training)
                and isinstance(x.value, jax.core.Tracer))

    def _scan_blocks(self, x):
        # Params are stacked here, inside the trace, rather than stored
        # stacked at rest: that keeps state_dict/named_parameters layout
        # per-layer (paddle semantics) at the cost of one XLA gather of
        # block weights per step — ~1% of step time at bench scale.
        import jax
        from ..jit.api import _bind, _restore
        blocks = list(self.h)
        proto = blocks[0]
        dicts = [dict(b.named_parameters()) for b in blocks]
        stacked = {k: jnp.stack([d[k].value for d in dicts])
                   for k in dicts[0]}

        def step(h, layer_params):
            saved = _bind(proto, layer_params)
            try:
                return proto(Tensor(h)).value
            finally:
                _restore(saved)

        if self.cfg.scan_remat:
            # scan_remat=True: full recompute (lowest memory, +2N flops
            # per token). scan_remat="dots": selective — save matmul/
            # attention outputs, recompute only cheap elementwise ops
            # (near-full-checkpoint memory savings without paying the
            # recompute FLOPs of the matmuls). The scan's while-loop
            # already blocks unsound CSE.
            step = jax.checkpoint(step, prevent_cse=False,
                                  policy=_remat_policy(self.cfg.scan_remat))
        y, _ = jax.lax.scan(lambda h, p: (step(h, p), None), x.value,
                            stacked)
        return Tensor(y)


class GPTForCausalLM(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.gpt = GPTModel(cfg)
        self.cfg = cfg

    def forward(self, input_ids, position_ids=None, caches=None):
        out = self.gpt(input_ids, position_ids, caches)
        hidden = out[0] if isinstance(out, tuple) else out
        # weight-tied LM head: logits = h @ wte^T (one big MXU matmul)
        from ..tensor.linalg import matmul
        logits = matmul(hidden, self.gpt.wte.weight, transpose_y=True)
        if isinstance(out, tuple):
            return logits, out[1]
        return logits

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        V = logits.shape[-1]
        return F.cross_entropy(logits.reshape([-1, V]),
                               labels.reshape([-1]), ignore_index=-100)

    def fused_loss(self, input_ids, labels, chunk=2048):
        """LM loss WITHOUT materializing [B*T, V] logits: the weight-tied
        vocab projection and the softmax-xent run chunked under remat
        (ops/chunked_xent.py). The memory this frees is what lets 1.3B+
        single-chip configs raise their batch (see examples/
        bench_gpt_1p3b.py); numerics match .loss() to bf16 precision."""
        out = self.gpt(input_ids)
        hidden = out[0] if isinstance(out, tuple) else out
        from ..ops.chunked_xent import chunked_softmax_xent
        from ..framework.core import apply_op
        H = hidden.shape[-1]

        def fn(h, w, y):
            return chunked_softmax_xent(
                h.reshape(-1, H), w, y.reshape(-1), chunk=chunk)
        return apply_op(fn, hidden, self.gpt.wte.weight, labels)

    def make_paged_cache(self, n_pages, page_size=16, dtype=None):
        """Shared page pool sized for this model (continuous batching)."""
        from ..ops.paged_attention import PagedKVCache
        cfg = self.cfg
        return PagedKVCache(
            cfg.num_layers, n_pages, page_size, cfg.num_heads,
            cfg.hidden_size // cfg.num_heads,
            dtype or self.gpt.wte.weight.value.dtype)

    def paged_decode_step(self, cache, seq_ids, input_ids, pad_to=None):
        """One continuous-batching step over a shared PagedKVCache:
        prefill when input_ids has T>1 (new request joining the batch),
        decode when T==1. Rows are independent sequences; lengths may be
        ragged — each attends only its own paged history. Returns
        next-token logits [B, vocab].

        Decode runs as ONE jitted program (page pools donated, k/v rows
        scatter-written in batch) — the host only plans page ids; the
        per-layer host loop remains for prefill, where T varies.

        pad_to (decode only): pad the traced batch to a fixed size with
        rows targeting the reserved pad page (PagedKVCache.plan_decode),
        so a serving scheduler's decode program keeps ONE compiled shape
        while sequences join/leave; returned logits are sliced back to
        the real B."""
        B, T = input_ids.shape
        # poisoned-cache guard hoisted here so BOTH paths (T>1 prefill and
        # T==1 decode) fail with the explicit message instead of an opaque
        # NoneType error from the prefill slot plumbing
        if cache.k is None:
            raise RuntimeError(
                "this PagedKVCache was poisoned by an earlier failed "
                "step — rebuild it with make_paged_cache() and "
                "re-prefill in-flight sequences")
        # context-limit guard (both paths): inside jit the wpe gather
        # silently clamps out-of-range positions to the last row
        # (generate() raises for the same condition)
        limit = self.cfg.max_position_embeddings
        over = [s for s in seq_ids if cache.length(s) + T > limit]
        if over:
            raise ValueError(
                f"sequences {over!r} would exceed "
                f"max_position_embeddings={limit} after {T} token(s); "
                "free them or raise the limit")
        if T == 1:
            return self._paged_decode_jit(cache, seq_ids, input_ids,
                                          pad_to=pad_to)
        # the cache lock serializes allocator + pool mutations when a
        # second engine shares this pool (no-op cost when uncontended)
        with cache.lock:
            caches = [PagedCacheSlot(cache, l, list(seq_ids), None)
                      for l in range(self.cfg.num_layers)]
            logits, _ = self(input_ids, caches=caches)
            return logits[:, -1, :]

    def clear_decode_cache(self):
        """Refresh the decode param snapshot. Call after loading or
        mutating weights mid-serving (paged_decode_step reuses a frozen
        snapshot across steps). Compiled programs are kept — weights are
        traced arguments, so the executables stay valid."""
        self._paged_params = None

    def _paged_decode_jit(self, cache, seq_ids, input_ids, pad_to=None):
        import jax
        from ..jit.api import functional_call, state_arrays

        L = self.cfg.num_layers
        B = len(seq_ids)
        # params are frozen during serving: snapshot once (see
        # clear_decode_cache for mid-serving weight swaps)
        params = getattr(self, "_paged_params", None)
        if params is None:
            params = self._paged_params = state_arrays(self)[0]
        fn = getattr(self, "_paged_jit_fn", None)
        if fn is None:
            model = self

            def step(ps, kps, vps, toks, pages, in_pages, pt, lens):
                # Python side effects run at TRACE time only: this is
                # an exact count of decode executables compiled (one
                # per novel (B, table width) signature) — the serving
                # engine folds its delta into serve.retraces
                model._paged_decode_traces = getattr(
                    model, "_paged_decode_traces", 0) + 1
                slots = [PagedJitSlot(kps[l], vps[l], pages, in_pages,
                                      pt, lens) for l in range(L)]
                logits, out_slots = functional_call(
                    model, ps, {}, (Tensor(toks),),
                    kwargs={"caches": slots}, training=False)
                return (logits[:, -1, :], [s.k for s in out_slots],
                        [s.v for s in out_slots])

            # pools donated: page writes update HBM in place; jax.jit's
            # own cache keys on (B, table width) shapes
            fn = self._paged_jit_fn = jax.jit(step, donate_argnums=(1, 2))
        # the cache lock holds from the plan through the donated-pool
        # swap: a second engine sharing this pool (prefill/decode
        # disaggregation) must neither plan against pools this step is
        # about to donate nor interleave allocator mutations mid-plan
        with cache.lock:
            pages, in_pages, pt, lens = cache.plan_decode(seq_ids,
                                                          pad_to=pad_to)
            toks = input_ids.value.astype(jnp.int32)
            if pad_to is not None and pad_to > B:
                # pad rows decode token 0 at position 0 into the
                # reserved pad page — garbage by construction, sliced
                # off below
                toks = jnp.concatenate(
                    [toks, jnp.zeros((int(pad_to) - B, 1), jnp.int32)])
            try:
                logits, new_k, new_v = fn(
                    params, list(cache.k), list(cache.v), toks, pages,
                    in_pages, pt, lens)
            except Exception as e:
                # donation only consumes the pools once the compiled
                # program EXECUTES; a trace/compile failure leaves them
                # valid
                if not any(getattr(a, "is_deleted", lambda: False)()
                           for a in (*cache.k, *cache.v)):
                    raise
                # the pools were donated to the failed program — they
                # are gone; make the poisoned state loud instead of
                # letting the next step die with a bare "Array has been
                # deleted"
                cache.k = cache.v = None
                raise RuntimeError(
                    "jitted paged decode step failed AFTER its page "
                    "pools were donated — this PagedKVCache is "
                    "unrecoverable; rebuild it with make_paged_cache() "
                    "and re-prefill in-flight sequences") from e
            cache.k = list(new_k)
            cache.v = list(new_v)
            for sid in seq_ids:
                cache.advance(sid, 1)
        return Tensor(logits[:B])

    # ---- ragged mixed prefill+decode step ---------------------------
    RAGGED_TAG = "serve.ragged_step"

    def _ragged_jitted(self):
        """The one jax.jit wrapper every ragged signature lowers
        through (pools donated: page writes update HBM in place)."""
        fn = getattr(self, "_ragged_jit_fn", None)
        if fn is not None:
            return fn
        import jax
        from ..jit.api import functional_call

        model = self
        L = self.cfg.num_layers

        def step(ps, kps, vps, toks, pos, tok_seq, tok_pages,
                 tok_in_pages, bounds, pt, out_idx, temps, top_ks,
                 top_ps, rng_keys, blk_pages, blk_seq, blk_start,
                 blk_n):
            # trace-time side effect: exact count of ragged executables
            # traced (one per novel (T, B, W) signature) — the serving
            # engine folds the delta into serve.retraces
            model._ragged_traces = getattr(
                model, "_ragged_traces", 0) + 1
            slots = [RaggedJitSlot(kps[l], vps[l], tok_pages,
                                   tok_in_pages, pt, tok_seq, bounds,
                                   blk_pages, blk_seq, blk_start, blk_n)
                     for l in range(L)]
            logits, out_slots = functional_call(
                model, ps, {}, (Tensor(toks[None, :]),),
                kwargs={"caches": slots,
                        "position_ids": Tensor(pos[None, :])},
                training=False)
            last = logits[0][out_idx]          # [B, vocab]
            # sampling ON DEVICE, PER TOKEN: every slot t samples from
            # its own next-token logits under its OWNING ROW's config,
            # keyed fold_in(row_key, position[t]) — exactly the draw
            # the engine would make after consuming token t, which is
            # what lets a speculative verify row read the target's
            # sample at all k+1 positions from one step
            # (inference/speculative.py). Every op in sample_token_rows
            # is row-independent, so the out_idx gather reproduces the
            # old per-row result bit-exactly; the host still reads back
            # int32s, never vocab-sized logits
            nxt_tok = sample_token_rows(
                logits[0], temps[tok_seq], top_ks[tok_seq],
                top_ps[tok_seq], rng_keys[tok_seq], pos)
            nxt = nxt_tok[out_idx]
            return (last, nxt, nxt_tok, [s.k for s in out_slots],
                    [s.v for s in out_slots])

        fn = self._ragged_jit_fn = jax.jit(step, donate_argnums=(1, 2))
        return fn

    def ragged_arg_specs(self, cache, n_tokens, n_rows, width):
        """ShapeDtypeStructs of one ragged-step signature — what
        `warm_ragged` AOT-compiles ahead of traffic."""
        import jax
        from ..jit.api import state_arrays
        params = getattr(self, "_paged_params", None)
        if params is None:
            params = self._paged_params = state_arrays(self)[0]
        sds = jax.ShapeDtypeStruct
        pshape = (cache.n_pages, cache.page_size, cache.n_heads,
                  cache.head_dim)
        pools = [sds(pshape, cache.k[0].dtype)
                 for _ in range(self.cfg.num_layers)]
        i32 = jnp.int32
        B = int(n_rows)
        tok = lambda: sds((int(n_tokens),), i32)
        # the q-block plan's shapes derive from (T, B, W) through the
        # same choose_q_block the planner applies — still one
        # executable per (T, B, W) signature
        qb, s_cap = self._ragged_block_geometry(
            cache, n_tokens, n_rows, width)
        return (jax.tree.map(lambda a: sds(a.shape, a.dtype), params),
                pools, list(pools), tok(), tok(), tok(), tok(), tok(),
                tok(), sds((B, int(width)), i32), sds((B,), i32),
                # per-row sampling config: [B]-shaped like out_idx, so
                # the signature still keys on (T, B, W) only
                sds((B,), jnp.float32), sds((B,), i32),
                sds((B,), jnp.float32), sds((B, 2), jnp.uint32),
                sds((qb, s_cap), i32), sds((qb, s_cap), i32),
                sds((qb, s_cap), i32), sds((qb,), i32))

    def _ragged_block_geometry(self, cache, n_tokens, n_rows, width):
        """(QB, S) of the q-block plan arrays for one (T, B, W)
        signature — the shape contract between plan_ragged's host
        planner and the compiled step."""
        from ..ops.pallas.attention_core import MXU_ROWS, choose_q_block
        fold = max(self.cfg.num_heads // cache.n_heads, 1)
        q_block = choose_q_block(int(n_tokens),
                                 cap=max(MXU_ROWS // fold, 1))
        return int(n_tokens) // q_block, int(n_rows) * int(width)

    _RAGGED_ARG_NAMES = ("params", "k_pages", "v_pages", "tokens",
                         "positions", "token_seq", "tok_pages",
                         "tok_in_pages", "bounds", "page_table",
                         "out_idx", "temperatures", "top_ks", "top_ps",
                         "rng_keys", "blk_pages", "blk_seq",
                         "blk_start", "blk_n")

    @staticmethod
    def _ragged_sig(cache, n_tokens, n_rows, width):
        return (int(n_tokens), int(n_rows), int(width),
                int(cache.n_pages), int(cache.page_size),
                str(cache.k[0].dtype) if cache.k else "poisoned")

    def warm_ragged(self, cache, n_tokens, n_rows, width, inline=False):
        """Single-flight AOT compile of one ragged signature through
        the background warm pipeline (jit/warm.py). Returns the
        WarmHandle; `handle.result()` is the (compiled, info) entry. A
        dispatch racing this JOINS the in-flight compile."""
        from ..jit import warm as _warm
        from ..jit.api import aot_compile
        exec_cache = getattr(self, "_ragged_exec", None)
        if exec_cache is None:
            exec_cache = self._ragged_exec = {}
        # the pool geometry is part of the executable's signature: two
        # engines over one model with different page pools must not
        # collide on compiled programs
        sig = self._ragged_sig(cache, n_tokens, n_rows, width)
        specs = self.ragged_arg_specs(cache, n_tokens, n_rows, width)
        jitted = self._ragged_jitted()

        def thunk():
            return aot_compile(jitted, specs, tag=self.RAGGED_TAG,
                               arg_names=self._RAGGED_ARG_NAMES)

        return _warm.submit_cached(exec_cache, sig, self.RAGGED_TAG,
                                   thunk, inline=inline)

    def paged_ragged_step(self, cache, rows, pad_to_tokens=None,
                          pad_to_rows=None, sampling=None,
                          return_per_token=False):
        """ONE continuous-batching step over mixed rows: `rows` is a
        list of (seq_id, token_ids) where decode rows carry one token
        and prefill-chunk rows carry a slice of their prompt — all
        advanced in a single jitted program over the Pallas ragged
        kernel, each token attending only its own paged history (pad
        tokens do zero attention work).

        Returns (logits Tensor [n_rows, vocab] — each row's LAST
        token's next-token logits — and next_tokens, a device int32
        array sampled ON DEVICE per row: no vocab-sized host read).
        pad_to_tokens/pad_to_rows pin the compiled shape for a serving
        scheduler.

        `sampling` is an optional (temperatures, top_ks, top_ps,
        rng_keys) tuple of PADDED-row-shaped host arrays (f32 [B],
        i32 [B], f32 [B], u32 [B, 2] — see `sample_token_rows`); None
        means every row decodes greedily (temperature 0), bit-exact
        with the pre-sampling argmax path.

        `return_per_token=True` appends the full padded [T] int32
        device array of PER-TOKEN samples (slot t's draw from its own
        next-token logits under its owning row's config, keyed by slot
        t's absolute position) — what a speculative verify row reads to
        compare the target's sample at every draft position
        (inference/speculative.py). The same one executable serves both
        callers; the flag only changes what the host unpacks."""
        if cache.k is None:
            raise RuntimeError(
                "this PagedKVCache was poisoned by an earlier failed "
                "step — rebuild it with make_paged_cache() and "
                "re-prefill in-flight sequences")
        limit = self.cfg.max_position_embeddings
        over = [s for s, t in rows
                if cache.length(s) + len(t) > limit]
        if over:
            raise ValueError(
                f"sequences {over!r} would exceed "
                f"max_position_embeddings={limit}; free them or raise "
                "the limit")
        from ..jit.api import state_arrays
        params = getattr(self, "_paged_params", None)
        if params is None:
            params = self._paged_params = state_arrays(self)[0]
        # the cache lock holds from the plan through the donated-pool
        # swap (see _paged_decode_jit): with two engines sharing one
        # pool, the other engine's step must see either the pre- or
        # the post-step pool buffers, never the donated carcass
        with cache.lock:
            plan = cache.plan_ragged([(s, len(t)) for s, t in rows],
                                     pad_to_tokens=pad_to_tokens,
                                     pad_to_rows=pad_to_rows,
                                     q_heads=self.cfg.num_heads)
            T = plan["tok_pages"].shape[0]
            B, W = plan["page_table"].shape
            toks = np.zeros((T,), np.int32)
            off = 0
            for _, t in rows:
                toks[off:off + len(t)] = \
                    np.asarray(t, np.int32).reshape(-1)
                off += len(t)
            entry = getattr(self, "_ragged_exec", {}).get(
                self._ragged_sig(cache, T, B, W))
            if entry is None:
                # miss: compile inline (single-flight — a concurrent
                # warm of the same signature is joined, not duplicated)
                entry = self.warm_ragged(cache, T, B, W,
                                         inline=True).result()
            compiled, _ = entry
            if sampling is None:
                # greedy defaults: temp-0 rows take the argmax lane
                sampling = (np.zeros((B,), np.float32),
                            np.zeros((B,), np.int32),
                            np.ones((B,), np.float32),
                            np.zeros((B, 2), np.uint32))
            temps, top_ks, top_ps, rng_keys = sampling
            args = (params, list(cache.k), list(cache.v),
                    jnp.asarray(toks), jnp.asarray(plan["positions"]),
                    jnp.asarray(plan["token_seq"]),
                    jnp.asarray(plan["tok_pages"]),
                    jnp.asarray(plan["tok_in_pages"]),
                    jnp.asarray(plan["bounds"]),
                    jnp.asarray(plan["page_table"]),
                    jnp.asarray(plan["out_idx"]),
                    jnp.asarray(temps), jnp.asarray(top_ks),
                    jnp.asarray(top_ps), jnp.asarray(rng_keys),
                    jnp.asarray(plan["blk_pages"]),
                    jnp.asarray(plan["blk_seq"]),
                    jnp.asarray(plan["blk_start"]),
                    jnp.asarray(plan["blk_n"]))
            try:
                last, nxt, nxt_tok, new_k, new_v = compiled(*args)
            except Exception as e:
                # donation only consumes the pools once the program
                # EXECUTES; a dispatch failure before that leaves them
                # valid
                if not any(getattr(a, "is_deleted", lambda: False)()
                           for a in (*cache.k, *cache.v)):
                    raise
                cache.k = cache.v = None
                raise RuntimeError(
                    "jitted ragged step failed AFTER its page pools "
                    "were donated — this PagedKVCache is "
                    "unrecoverable; rebuild it with make_paged_cache() "
                    "and re-prefill in-flight sequences") from e
            cache.k = list(new_k)
            cache.v = list(new_v)
            for s, t in rows:
                cache.advance(s, len(t))
            n = plan["n_rows"]
        if return_per_token:
            return Tensor(last[:n]), nxt[:n], nxt_tok
        return Tensor(last[:n]), nxt[:n]

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k=None, top_p=None):
        """Top-k/temperature sampling over a STATIC KV cache.

        Exactly two compiled programs regardless of max_new_tokens: one
        prefill over the prompt (fills the [B, L, H, D] buffers in a
        single pass) and one lax.scan over the decode steps (each step
        writes its k/v at the current position and attends under a
        `col <= pos` mask). Replaces the per-token concat path that
        recompiled every step (ref generate() in PaddleNLP GPT; decode
        design per VERDICT r2 weak #5)."""
        import jax
        from ..jit.api import functional_call, state_arrays
        from ..framework.random import split_key

        cfg = self.cfg
        B, T = input_ids.shape
        L = T + max_new_tokens
        if L > cfg.max_position_embeddings:
            raise ValueError(
                f"prompt {T} + max_new_tokens {max_new_tokens} exceeds "
                f"max_position_embeddings {cfg.max_position_embeddings}")
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        params, _ = state_arrays(self)
        cache_dtype = self.gpt.wte.weight.value.dtype
        model = self

        def fwd(ps, ids, kbs, vbs, pos):
            caches = [StaticCacheSlot(Tensor(kbs[i]), Tensor(vbs[i]), pos)
                      for i in range(cfg.num_layers)]
            logits, new_caches = functional_call(
                model, ps, {}, (Tensor(ids),), kwargs={"caches": caches},
                training=False)
            kbs = jnp.stack([c.k.value for c in new_caches])
            vbs = jnp.stack([c.v.value for c in new_caches])
            return logits, kbs, vbs

        def sample(last, key, temp):
            arr = last.astype(jnp.float32) / jnp.maximum(temp, 1e-6)
            V = arr.shape[-1]
            # approx path: lax.approx_max_k thresholds 29x faster than
            # exact top_k over a 50k vocab (0.05 ms vs 1.6 ms at batch
            # 32) and is accurate to the nucleus/kth boundary. Default on
            # TPU for big vocabs; PADDLE_TPU_APPROX_SAMPLING=0/1 forces
            # it off/on (on works on every backend — tests compare the
            # two paths on CPU).
            force = os.environ.get("PADDLE_TPU_APPROX_SAMPLING")
            approx = (jax.default_backend() == "tpu" and V > 8192) \
                if force is None else force == "1"
            # one descending approx-top scan, sized to what's needed:
            # top-k alone only needs the kth value, the nucleus needs a
            # few thousand entries to cover top_p
            n_sub = min(V, 4096 if top_p is not None else (top_k or 0))
            subset = None
            if approx and n_sub > 0:
                subset, _ = jax.lax.approx_max_k(arr, n_sub,
                                                 recall_target=0.99)

            def nucleus_thresh(srt, p_srt):
                # keep the smallest prefix of the sorted probs reaching
                # top_p (a token stays iff the mass BEFORE it is < top_p)
                before = jnp.cumsum(p_srt, axis=-1) - p_srt
                keep = before < top_p
                return jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                               keepdims=True)

            if top_k is not None:
                if subset is not None and top_k <= n_sub:
                    kth = subset[:, top_k - 1:top_k]
                else:
                    kth = jax.lax.top_k(arr, top_k)[0][:, -1:]
                arr = jnp.where(arr < kth, -1e30, arr)
            if top_p is not None:
                if subset is not None:
                    # sort only the approx-top subset, normalized against
                    # the full-row softmax mass; if the subset doesn't
                    # cover top_p (near-uniform logits), keep everything
                    # rather than truncate at the subset edge
                    lse = jax.scipy.special.logsumexp(arr, axis=-1,
                                                      keepdims=True)
                    p_sub = jnp.exp(subset - lse)
                    thresh = nucleus_thresh(subset, p_sub)
                    covered = jnp.sum(p_sub, axis=-1,
                                      keepdims=True) >= top_p
                    thresh = jnp.where(covered, thresh, -jnp.inf)
                else:
                    srt = jnp.sort(arr, axis=-1)[:, ::-1]
                    thresh = nucleus_thresh(srt,
                                            jax.nn.softmax(srt, axis=-1))
                arr = jnp.where(arr >= thresh, arr, -1e30)
            return jax.random.categorical(key, arr)[:, None]

        def prefill(ps, ids, key, temp):
            kbs = jnp.zeros((cfg.num_layers, B, L, nh, hd), cache_dtype)
            vbs = jnp.zeros_like(kbs)
            logits, kbs, vbs = fwd(ps, ids, kbs, vbs, 0)
            return sample(logits[:, -1, :], key, temp), kbs, vbs

        def decode(ps, first_tok, kbs, vbs, key, temp):
            def step(carry, i):
                tok, kbs, vbs = carry
                logits, kbs, vbs = fwd(ps, tok, kbs, vbs, T + i)
                nxt = sample(logits[:, -1, :],
                             jax.random.fold_in(key, i), temp)
                return (nxt, kbs, vbs), nxt[:, 0]

            _, toks = jax.lax.scan(step, (first_tok, kbs, vbs),
                                   jnp.arange(max_new_tokens - 1))
            return jnp.concatenate([first_tok, toks.T], axis=1)

        sig = (B, T, max_new_tokens, top_k, top_p)
        cache = getattr(self, "_gen_jit", None)
        if cache is None:
            cache = self._gen_jit = {}
        if sig not in cache:
            cache[sig] = (jax.jit(prefill),
                          jax.jit(decode) if max_new_tokens > 1 else None)
        jit_prefill, jit_decode = cache[sig]

        ids = input_ids.value.astype(jnp.int32)
        temp = jnp.asarray(temperature, jnp.float32)
        first_tok, kbs, vbs = jit_prefill(params, ids, split_key(), temp)
        if jit_decode is None:
            new = first_tok
        else:
            new = jit_decode(params, first_tok, kbs, vbs, split_key(),
                             temp)
        out = jnp.concatenate([input_ids.value.astype(jnp.int64),
                               new.astype(jnp.int64)], axis=1)
        return Tensor(out)


def gpt_tiny(vocab=1024):
    return GPTConfig(vocab_size=vocab, hidden_size=64, num_layers=2,
                     num_heads=4, max_position_embeddings=128)


def gpt_small():
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12)


def gpt_medium():
    return GPTConfig(hidden_size=1024, num_layers=24, num_heads=16)


def gpt_1p3b():
    return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16,
                     max_position_embeddings=2048)


def gpt_6p7b():
    return GPTConfig(hidden_size=4096, num_layers=32, num_heads=32,
                     max_position_embeddings=2048)


def gpt_moe(num_experts=8, **kw):
    """MoE flagship: GPT-small trunk with every 2nd MLP an
    expert-parallel MoELayer (experts shard over 'ep')."""
    kw.setdefault("hidden_size", 768)
    kw.setdefault("num_layers", 12)
    kw.setdefault("num_heads", 12)
    return GPTConfig(num_experts=num_experts, **kw)
