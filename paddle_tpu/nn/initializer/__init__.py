"""Weight initializers. Parity: python/paddle/nn/initializer/ and
python/paddle/fluid/initializer.py.

Initializers are callables over Parameters: they draw from the global
functional PRNG (framework/random.py) and bind the fresh value.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Parameter, Tensor
from ...framework.random import split_key

__all__ = ["Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "Bilinear", "Dirac", "Orthogonal", "calculate_gain",
           "set_global_initializer"]

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
             "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0,
             "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 3.0 / 4.0}
    return gains[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *spatial] (paddle layout)
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class Initializer:
    def __call__(self, param, block=None):
        raise NotImplementedError

    def _set(self, param, arr):
        param.set_value(arr.astype(param.value.dtype))


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, param, block=None):
        self._set(param, jnp.full(tuple(param.shape), self.value,
                                  dtype=param.value.dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, param, block=None):
        v = self.mean + self.std * jax.random.normal(
            split_key(), tuple(param.shape), jnp.float32)
        self._set(param, v)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, param, block=None):
        v = self.mean + self.std * jax.random.truncated_normal(
            split_key(), -2.0, 2.0, tuple(param.shape), jnp.float32)
        self._set(param, v)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, param, block=None):
        v = jax.random.uniform(split_key(), tuple(param.shape), jnp.float32,
                               self.low, self.high)
        self._set(param, v)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fans(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        self._set(param, std * jax.random.normal(
            split_key(), tuple(param.shape), jnp.float32))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fans(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        self._set(param, jax.random.uniform(
            split_key(), tuple(param.shape), jnp.float32, -limit, limit))


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, param, block=None):
        fi, _ = _fans(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        self._set(param, std * jax.random.normal(
            split_key(), tuple(param.shape), jnp.float32))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0,
                 nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, param, block=None):
        fi, _ = _fans(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        self._set(param, jax.random.uniform(
            split_key(), tuple(param.shape), jnp.float32, -limit, limit))


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, param, block=None):
        v = self.value
        if isinstance(v, Tensor):
            v = v.value
        self._set(param, jnp.asarray(np.asarray(v)))


class Dirac(Initializer):
    """Identity-preserving conv kernel init (groups of delta filters)."""

    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, param, block=None):
        shape = tuple(param.shape)
        out_c, in_c = shape[0], shape[1]
        arr = np.zeros(shape, dtype=np.float32)
        centers = tuple(s // 2 for s in shape[2:])
        per_group = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per_group, in_c)):
                arr[(g * per_group + i, i) + centers] = 1.0
        self._set(param, jnp.asarray(arr))


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs
    (ref: python/paddle/fluid/initializer.py:767 BilinearInitializer).
    Every (out, in) channel pair gets the same separable triangle
    kernel, so a Conv2DTranspose initialised with it performs bilinear
    interpolation."""

    def __init__(self, name=None):
        pass

    def __call__(self, param, block=None):
        shape = tuple(param.shape)
        if len(shape) != 4:
            raise ValueError(
                "Bilinear init expects a 4-D Conv2DTranspose weight, "
                f"got shape {shape}")
        kh, kw = shape[-2], shape[-1]
        f_h, f_w = math.ceil(kh / 2.0), math.ceil(kw / 2.0)
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        i = np.arange(kh)[:, None]
        j = np.arange(kw)[None, :]
        k2d = ((1 - np.abs(i / f_h - c_h)) *
               (1 - np.abs(j / f_w - c_w))).astype(np.float32)
        arr = np.broadcast_to(k2d, shape).copy()
        self._set(param, jnp.asarray(arr))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, param, block=None):
        shape = tuple(param.shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(split_key(),
                                 (max(rows, cols), min(rows, cols)),
                                 jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        self._set(param, self.gain * q[:rows, :cols].reshape(shape))
