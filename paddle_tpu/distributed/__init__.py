"""paddle.distributed namespace.
Parity: python/paddle/distributed/__init__.py."""
from .env import (init_parallel_env, get_rank, get_world_size, barrier,
                  ParallelEnv, get_mesh, set_mesh, build_mesh,
                  is_initialized)
from .collective import (ReduceOp, all_reduce, all_gather, broadcast,
                         reduce, scatter, alltoall, send, recv,
                         reduce_scatter, split, new_group, wait,
                         psum, pmean, pmax, all_gather_axis, ppermute,
                         all_to_all_axis, axis_index)
from .parallel import DataParallel
from .spawn import spawn
from . import fleet
from . import auto_parallel
from .auto_parallel import shard_tensor, shard_op, ProcessMesh
from . import meta_parallel
from .fleet.utils.recompute import recompute
from . import checkpoint
from .checkpoint import save_sharded, load_sharded
from . import launch as launch_module


def launch():
    from .launch import main
    main()
