"""API-surface lock: every reference Tensor method must exist
(generated from the reference tensor_method_func list; SURVEY
section 2.1)."""
import paddle_tpu as paddle


TENSOR_METHODS = [
    'abs', 'acos', 'acosh', 'add', 'add_',
    'add_n', 'addmm', 'all', 'allclose', 'amax',
    'amin', 'angle', 'any', 'argmax', 'argmin',
    'argsort', 'as_complex', 'as_real', 'asin', 'asinh',
    'atan', 'atanh', 'bincount', 'bitwise_and', 'bitwise_not',
    'bitwise_or', 'bitwise_xor', 'bmm', 'broadcast_shape', 'broadcast_tensors',
    'broadcast_to', 'cast', 'ceil', 'ceil_', 'cholesky',
    'cholesky_solve', 'chunk', 'clip', 'clip_', 'concat',
    'cond', 'conj', 'cos', 'cosh', 'cov',
    'cross', 'cumprod', 'cumsum', 'deg2rad', 'diagonal',
    'diff', 'digamma', 'dist', 'divide', 'dot',
    'eig', 'eigvals', 'eigvalsh', 'equal', 'equal_all',
    'erf', 'erfinv', 'erfinv_', 'exp', 'exp_',
    'expand', 'expand_as', 'exponential_', 'flatten', 'flatten_',
    'flip', 'floor', 'floor_', 'floor_divide', 'floor_mod',
    'fmax', 'fmin', 'gather', 'gather_nd', 'gcd',
    'greater_equal', 'greater_than', 'histogram', 'imag', 'increment',
    'index_sample', 'index_select', 'inner', 'inverse', 'is_complex',
    'is_empty', 'is_floating_point', 'is_integer', 'is_tensor', 'isclose',
    'isfinite', 'isinf', 'isnan', 'kron', 'kthvalue',
    'lcm', 'lerp', 'lerp_', 'less_equal', 'less_than',
    'lgamma', 'log', 'log10', 'log1p', 'log2',
    'logical_and', 'logical_not', 'logical_or', 'logical_xor', 'logit',
    'logsumexp', 'lstsq', 'lu', 'lu_unpack', 'masked_select',
    'matmul', 'matrix_power', 'max', 'maximum', 'mean',
    'median', 'min', 'minimum', 'mm', 'mod',
    'moveaxis', 'multi_dot', 'multiplex', 'multiply', 'mv',
    'nansum', 'neg', 'nonzero', 'norm', 'not_equal',
    'numel', 'outer', 'pow', 'prod', 'put_along_axis',
    'put_along_axis_', 'qr', 'quantile', 'rad2deg', 'rank',
    'real', 'reciprocal', 'reciprocal_', 'remainder', 'repeat_interleave',
    'reshape', 'reshape_', 'reverse', 'roll', 'rot90',
    'round', 'round_', 'rsqrt', 'rsqrt_', 'scale',
    'scale_', 'scatter', 'scatter_', 'scatter_nd', 'scatter_nd_add',
    'shape', 'shard_index', 'sign', 'sin', 'sinh',
    'slice', 'solve', 'sort', 'split', 'sqrt',
    'sqrt_', 'square', 'squeeze', 'squeeze_', 'stack',
    'stanh', 'std', 'strided_slice', 'subtract', 'subtract_',
    'sum', 't', 'take_along_axis', 'tanh', 'tanh_',
    'tensordot', 'tile', 'topk', 'trace', 'transpose',
    'triangular_solve', 'trunc', 'unbind', 'uniform_', 'unique',
    'unique_consecutive', 'unsqueeze', 'unsqueeze_', 'unstack', 'var',
    'where',
]


def test_tensor_methods_present():
    t = paddle.to_tensor([1.0])
    missing = [n for n in TENSOR_METHODS if not hasattr(t, n)]
    assert not missing, missing
