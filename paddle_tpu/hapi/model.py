"""paddle.Model high-level API. Parity: python/paddle/hapi/model.py.

fit/evaluate/predict drive the jitted TrainStep (single XLA computation
per step) rather than per-op dygraph — the reference's DynamicGraphAdapter
replaced by the functional path.
"""
import os

import numpy as np

from ..framework.core import Tensor, no_grad
from ..io import DataLoader
from ..metric import Metric
from . import callbacks as cb_mod

__all__ = ["Model"]


class _InputSpecList(list):
    pass


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self.stop_training = False

    # -- setup ---------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        return self

    def _loss_fn(self, outputs, labels):
        if callable(self._loss):
            return self._loss(outputs, labels)
        raise RuntimeError("Model.prepare(loss=...) required")

    def _ensure_train_step(self):
        if self._train_step is None:
            from ..jit import TrainStep
            self._train_step = TrainStep(self.network, self._loss_fn,
                                         self._optimizer)

    # -- steps ---------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self._ensure_train_step()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labs = labels if isinstance(labels, (list, tuple)) else [labels]
        loss = self._train_step(*ins, labs[0])
        return [float(loss.item())]

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labs = labels if isinstance(labels, (list, tuple)) else [labels]
        if self._train_step is not None:
            self._train_step.sync_to_model()
            self._train_step = None
        self.network.eval()
        out = self.network(*ins)
        loss = self._loss_fn(out, labs[0]) if self._loss else None
        metrics = []
        for m in self._metrics:
            res = m.compute(out, labs[0])
            m.update(res)
            metrics.append(m.accumulate())
        self.network.train()
        return ([float(loss.item())] if loss is not None else []), metrics

    @no_grad()
    def predict_batch(self, inputs):
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if self._train_step is not None:
            self._train_step.sync_to_model()
            self._train_step = None
        self.network.eval()
        out = self.network(*ins)
        self.network.train()
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [o.numpy() for o in outs]

    # -- loops ---------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                       drop_last=drop_last, num_workers=num_workers)
        cbks = cb_mod.config_callbacks(callbacks, self, epochs, None,
                                       verbose, log_freq, save_dir,
                                       save_freq, self._metrics)
        cbks.on_begin("train")
        steps_done = 0
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                ins, labs = batch[:-1], batch[-1]
                cbks.on_batch_begin("train", step, logs)
                losses = self.train_batch(list(ins), labs)
                logs = {"loss": losses, "step": step}
                cbks.on_batch_end("train", step, logs)
                steps_done += 1
                if num_iters is not None and steps_done >= num_iters:
                    break
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eres = self.evaluate(eval_data, batch_size=batch_size,
                                     verbose=0, num_workers=num_workers)
                logs.update({"eval_" + k: v for k, v in eres.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if self.stop_training:
                break
            if num_iters is not None and steps_done >= num_iters:
                break
        cbks.on_end("train")

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else \
            DataLoader(eval_data, batch_size=batch_size,
                       num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            ins, labs = batch[:-1], batch[-1]
            l, _ = self.eval_batch(list(ins), labs)
            losses.extend(l)
        out = {"loss": [float(np.mean(losses))] if losses else []}
        for m in self._metrics:
            out[m.name()] = m.accumulate()
        return out

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size,
                       num_workers=num_workers)
        outputs = []
        for batch in loader:
            ins = batch if not isinstance(batch, (list, tuple)) else batch
            if isinstance(ins, (list, tuple)) and len(ins) > 1:
                ins = ins[:-1]
            outputs.append(self.predict_batch(list(ins)
                                              if isinstance(ins, (list,
                                                                  tuple))
                                              else [ins]))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- persistence ---------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as psave
        if self._train_step is not None:
            self._train_step.sync_to_model()
        if training:
            psave(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                psave(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from ..jit import save as jit_save
            if not self._inputs:
                raise ValueError("inference save needs Model(inputs=...)")
            jit_save(self.network, path, input_spec=self._inputs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as pload
        state = pload(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if os.path.exists(opt_path) and self._optimizer is not None \
                and not reset_optimizer:
            self._optimizer.set_state_dict(pload(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        ins = self._inputs
        if ins is not None and not isinstance(ins, (list, tuple)):
            ins = [ins]  # single InputSpec is valid (ref hapi/model.py)
        return summary(self.network, input_size or
                       [tuple(s.shape) for s in (ins or [])])
