#!/usr/bin/env python
"""Pack / seed the persistent XLA compile cache — the donated-artifact
workflow that kills the cold-compile wall across machines and processes
(docs/PERFORMANCE.md "Killing the compile wall"; ROADMAP open item 3).

A compiled cache directory is a portable artifact: any machine that has
paid a workload's cold compiles can `pack` them, and a fresh machine
(or a CI runner, or a bench round under a hard compile budget) can
`seed` them — its first compiles then LOAD in seconds instead of
recompiling for minutes. The cache key includes the HLO fingerprint and
jax/backend versions, so a stale or mismatched artifact degrades to
ordinary cold compiles, never to wrong results.

Usage:
  python tools/seed_compile_cache.py pack DEST [--cache DIR]
      Copy the active cache's entries (PADDLE_TPU_COMPILE_CACHE or the
      default user cache; --cache overrides) into DEST with a
      MANIFEST.json naming them.

  python tools/seed_compile_cache.py seed SOURCE [--cache DIR]
      Copy SOURCE's entries (a pack artifact or any raw cache dir) into
      the active cache, skipping entries already present.

bench.py seeds automatically when BENCH_CACHE_SEED names an artifact
dir; in-process, `paddle_tpu.framework.compile_cache.seed_from()` does
the same and emits a `kind:"seed"` metrics record.

Exit 0 on success, 2 on a bad source/cache.
"""
import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_compile_cache():
    """Load framework/compile_cache.py as a standalone module — it only
    needs stdlib + jax, so the CLI skips the full framework import (and
    its backend-init weight)."""
    path = os.path.join(REPO, "paddle_tpu", "framework",
                        "compile_cache.py")
    spec = importlib.util.spec_from_file_location("_compile_cache", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    ap = argparse.ArgumentParser(
        "seed_compile_cache",
        description="pack/seed the persistent XLA compile cache")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("pack", help="copy cache entries to a portable "
                                    "artifact dir")
    p.add_argument("dest")
    p.add_argument("--cache", default=None,
                   help="source cache dir (default: the active cache)")
    s = sub.add_parser("seed", help="pre-populate the cache from an "
                                    "artifact dir")
    s.add_argument("source")
    s.add_argument("--cache", default=None,
                   help="destination cache dir (default: the active "
                        "cache)")
    args = ap.parse_args(argv)

    cc = _load_compile_cache()
    try:
        if args.cmd == "pack":
            if args.cache is None:
                cc.enable_compile_cache()
            out = cc.pack(args.dest, source=args.cache)
            print(json.dumps({"packed": out}))
        else:
            out = cc.seed_from(args.source, dest=args.cache)
            print(json.dumps({"seeded": out}))
    except ValueError as e:
        print(f"seed_compile_cache: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
