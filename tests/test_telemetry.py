"""Framework-wide telemetry (ISSUE 2): host span statistics, the metrics
registry + JSONL export, XLA cost-analysis FLOPs/MFU, and the
launch-env satellites.

Proof points:
- RecordEvent spans nest and aggregate correctly (counts, parent paths,
  thread merging).
- The metrics JSONL is valid one-object-per-line, rank-tagged, and
  passes tools/check_metrics_schema.py (the bench/driver contract).
- Profiler.summary() contains the framework-emitted span rows (compile,
  step, dataloader, collective, memory) after a jit train step.
- cost_analysis FLOPs for a known matmul match the 2·M·N·K closed form.
- load_profiler_result returns a queryable object (no more
  NotImplementedError).
- launch: no forced coordinator env for a 1-process world; --devices
  partitions per local rank.
"""
import importlib.util
import json
import os
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu import profiler
from paddle_tpu.jit import TrainStep
from paddle_tpu.profiler import statistic, monitor, cost

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_schema_tool():
    path = os.path.join(REPO, "tools", "check_metrics_schema.py")
    spec = importlib.util.spec_from_file_location("check_metrics_schema",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    statistic.reset_statistics()
    monitor.reset_metrics()
    yield


def _make_step():
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
    step = TrainStep(m, nn.CrossEntropyLoss(), o)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(8, 8).astype(np.float32))
    y = paddle.to_tensor(np.arange(8, dtype=np.int64) % 4)
    return step, x, y


# --------------------------------------------------- span statistics
def test_spans_nest_and_aggregate():
    with profiler.RecordEvent("outer"):
        with profiler.RecordEvent("inner"):
            pass
        with profiler.RecordEvent("inner"):
            pass
    with profiler.RecordEvent("outer"):
        pass
    outer = statistic.get_events("outer")
    inner = statistic.get_events("inner")
    assert len(outer) == 1 and outer[0]["count"] == 2
    assert len(inner) == 1 and inner[0]["count"] == 2
    assert inner[0]["path"] == "outer/inner"
    # parent total covers children
    assert outer[0]["total_s"] >= inner[0]["total_s"]


def test_record_span_merges_threads():
    def worker():
        with statistic.span("shared"):
            statistic.record_span("leaf", 0.001)

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with statistic.span("shared"):
        statistic.record_span("leaf", 0.002)
    shared = statistic.get_events("shared")[0]
    leaf = statistic.get_events("leaf")[0]
    assert shared["count"] == 4 and leaf["count"] == 4
    assert leaf["path"] == "shared/leaf"
    # the snapshot tree keeps the set of thread idents that hit a node
    # (a finished thread's ident may be reused, so >= 2 not == 4)
    tree = {n["name"]: n for n in statistic.snapshot()}
    assert len(tree["shared"]["threads"]) >= 2


def test_summary_table_renders_sorted():
    statistic.record_span("big", 1.0)
    statistic.record_span("small", 0.1)
    table = statistic.summary_table(time_unit="ms")
    assert "Total(ms)" in table
    assert table.index("big") < table.index("small")  # sorted by total
    assert "100" in table  # small = 100 ms


# --------------------------------------------------- metrics registry
def test_metrics_registry_kinds():
    monitor.counter("t.calls").inc()
    monitor.counter("t.calls").inc(4)
    monitor.gauge("t.gauge").set(2.5)
    for v in (0.1, 0.3):
        monitor.histogram("t.hist").observe(v)
    snap = monitor.metrics_snapshot()
    assert snap["t.calls"] == 5
    assert snap["t.gauge"] == 2.5
    assert snap["t.hist"]["count"] == 2
    assert abs(snap["t.hist"]["avg"] - 0.2) < 1e-9
    with pytest.raises(TypeError):
        monitor.gauge("t.calls")  # kind conflict must be loud


def test_rank_comes_from_launch_env(tmp_path, monkeypatch):
    path = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(path))
    monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
    assert monitor.export_step({"k": 1}, kind="custom")
    rec = json.loads(path.read_text().strip())
    assert rec["rank"] == 3 and rec["kind"] == "custom" and rec["k"] == 1
    monkeypatch.delenv("PADDLE_TPU_METRICS_FILE")
    assert not monitor.export_step({"k": 1})  # off without the env var


# --------------------------------------------- per-step JSONL export
def test_train_step_emits_valid_schema_jsonl(tmp_path, monkeypatch):
    path = tmp_path / "metrics.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(path))
    step, x, y = _make_step()
    for _ in range(3):
        float(step(x, y).item())
    lines = [l for l in path.read_text().splitlines() if l.strip()]
    all_recs = [json.loads(l) for l in lines]
    # one step record per optimizer step, plus exactly one
    # kind:"compile" ledger record for the single cold compile
    # (profiler/compile_observatory.py)
    recs = [r for r in all_recs if r["kind"] == "step"]
    assert len(recs) == 3
    compiles = [r for r in all_recs if r["kind"] == "compile"]
    assert len(compiles) == 1 and compiles[0]["tag"] == "train.step"
    for i, rec in enumerate(recs):
        assert rec["kind"] == "step" and rec["rank"] == 0
        assert rec["step"] == i + 1
        assert rec["flops"] > 0          # XLA cost analysis on CPU works
        assert rec["peak_bytes"] > 0
    assert recs[0]["compile_s"] > 0 and not recs[0]["cache_hit"]
    assert recs[1]["compile_s"] == 0.0 and recs[1]["cache_hit"]
    # the contract's enforcement point: the documented schema tool
    tool = _load_schema_tool()
    assert tool.validate_file(str(path)) == []
    assert tool.main([str(path)]) == 0


def test_schema_tool_rejects_drift(tmp_path):
    tool = _load_schema_tool()
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"ts": 1, "rank": 0, "kind": "step", "step": 1}\n'
                   "not json\n")
    errors = tool.validate_file(str(bad))
    assert any("step_time_s" in e for e in errors)
    assert any("not valid JSON" in e for e in errors)
    assert tool.main([str(bad)]) == 1
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert tool.validate_file(str(empty))


# ------------------------------------------- summary after a jit step
def test_summary_contains_framework_spans():
    import paddle_tpu.distributed as dist
    from paddle_tpu.io import DataLoader, TensorDataset

    step, x, y = _make_step()
    prof = profiler.Profiler(timer_only=True)
    prof.start()
    ds = TensorDataset([np.arange(16, dtype=np.float32).reshape(8, 2)])
    for _ in DataLoader(ds, batch_size=4):
        pass
    dist.all_reduce(paddle.to_tensor(np.ones(4, np.float32)))
    float(step(x, y).item())
    paddle.device.max_memory_allocated()
    prof.step()
    prof.stop()
    text = prof.summary()
    for span_name in ("train.step", "jit.trace_lower", "jit.compile",
                      "dataloader.next", "collective.all_reduce",
                      "device.memory"):
        assert span_name in text, f"summary missing {span_name}:\n{text}"
    # registry section rides along
    assert "jit.retraces" in text and "train.flops_per_step" in text


# ------------------------------------------------------ cost analysis
def test_matmul_flops_match_closed_form():
    import jax
    import jax.numpy as jnp
    M, N, K = 16, 32, 64
    compiled = jax.jit(lambda a, b: a @ b).lower(
        jnp.ones((M, K), jnp.float32),
        jnp.ones((K, N), jnp.float32)).compile()
    ca = cost.cost_analysis(compiled)
    assert ca["flops"] == 2 * M * N * K
    assert cost.executable_flops(compiled) == 2 * M * N * K
    assert cost.executable_bytes(compiled) > 0


def test_train_step_cost_analysis_free_after_run():
    step, x, y = _make_step()
    float(step(x, y).item())
    retraces = step.retraces
    ca = step.cost_analysis(x, y)     # cached executable: no new compile
    assert step.retraces == retraces
    assert ca["flops"] > 0 and step.flops(x, y) > 0


def test_mfu_helper():
    assert cost.mfu(0.0, 1.0, 1e12) == 0.0
    assert cost.mfu(5e11, 1.0, 1e12) == 0.5
    assert cost.mfu(5e11, 0.0, 1e12) == 0.0
    assert cost.mfu(5e11, 1.0, 0.0) == 0.0  # unknown peak (CPU)


# --------------------------------------------- load_profiler_result
def test_load_profiler_result_roundtrip(tmp_path):
    with profiler.RecordEvent("phase_a"):
        with profiler.RecordEvent("phase_b"):
            pass
    monitor.counter("c").inc(7)
    prof = profiler.Profiler(timer_only=True)
    prof.start()
    prof.step()
    prof.stop()
    path = prof.export_host_stats(str(tmp_path / "host_stats.json"))
    result = profiler.load_profiler_result(path)
    assert result.get("phase_b")[0]["count"] == 1
    assert result.get("phase_b")[0]["path"] == "phase_a/phase_b"
    assert result.total_s("phase_a") > 0
    assert result.metrics["c"] == 7
    assert "phase_a" in result.summary()


def test_load_profiler_result_reads_metrics_jsonl(tmp_path, monkeypatch):
    path = tmp_path / "metrics.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(path))
    step, x, y = _make_step()
    float(step(x, y).item())
    float(step(x, y).item())
    result = profiler.load_profiler_result(str(path))
    assert len(result.steps) == 2
    assert result.steps[1]["cache_hit"] is True


# --------------------------------------------------- launch satellites
def _launch_args(**kw):
    from paddle_tpu.distributed.launch import _parse
    argv = []
    for k, v in kw.items():
        argv += [f"--{k}", str(v)]
    return _parse(argv + ["train.py"])


def test_single_rank_gang_gets_no_coordinator_env():
    """nnodes*nproc == 1 must keep the single-controller init path: no
    forced PADDLE_TPU_COORDINATOR/NUM_PROCESSES (round-5 advisor)."""
    from paddle_tpu.distributed.launch import _rank_env
    env = _rank_env(_launch_args(), "127.0.0.1:5000", 0, 0)
    assert "PADDLE_TPU_COORDINATOR" not in env
    assert "PADDLE_TPU_NUM_PROCESSES" not in env
    assert "PADDLE_TPU_PROCESS_ID" not in env
    assert env["PADDLE_TRAINER_ID"] == "0"      # reference env still set
    assert env["PADDLE_TRAINERS_NUM"] == "1"


def test_multi_rank_gang_keeps_coordinator_env():
    from paddle_tpu.distributed.launch import _rank_env
    env = _rank_env(_launch_args(nproc_per_node=2), "127.0.0.1:5000", 1, 0)
    assert env["PADDLE_TPU_COORDINATOR"] == "127.0.0.1:5000"
    assert env["PADDLE_TPU_NUM_PROCESSES"] == "2"
    assert env["PADDLE_TPU_PROCESS_ID"] == "1"


def test_devices_partition_per_local_rank():
    from paddle_tpu.distributed.launch import _rank_env
    args = _launch_args(nproc_per_node=2, devices="0,1,2,3")
    env0 = _rank_env(args, "127.0.0.1:5000", 0, 0)
    env1 = _rank_env(args, "127.0.0.1:5000", 1, 0)
    assert env0["PADDLE_VISIBLE_DEVICES"] == "0,1"
    assert env1["PADDLE_VISIBLE_DEVICES"] == "2,3"


def test_devices_indivisible_is_loud():
    from paddle_tpu.distributed.launch import _rank_devices
    with pytest.raises(SystemExit):
        _rank_devices("0,1,2", 2, 0)


def test_visible_devices_consumed_before_backend_init(monkeypatch):
    from paddle_tpu.distributed.env import _apply_visible_devices
    monkeypatch.setenv("PADDLE_VISIBLE_DEVICES", "2,3")
    monkeypatch.delenv("TPU_VISIBLE_CHIPS", raising=False)
    monkeypatch.delenv("CUDA_VISIBLE_DEVICES", raising=False)
    _apply_visible_devices()
    assert os.environ["TPU_VISIBLE_CHIPS"] == "2,3"
    assert os.environ["CUDA_VISIBLE_DEVICES"] == "2,3"
    # an explicitly set backend var wins over the paddle one
    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "0")
    _apply_visible_devices()
    assert os.environ["TPU_VISIBLE_CHIPS"] == "0"
