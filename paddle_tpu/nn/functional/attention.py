"""Attention functionals.

Parity: python/paddle/nn/functional/sparse_attention.py + the attention
core of python/paddle/nn/layer/transformer.py. On TPU the hot path is the
Pallas flash-attention kernel (paddle_tpu/ops/pallas/flash_attention.py);
this module exposes the framework-level API and falls back to the XLA
softmax(QK^T)V composition when the kernel is unavailable (CPU tests).
"""
import math

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply_op


def _sdpa_reference(q, k, v, mask=None, dropout_p=0.0, is_causal=False,
                    scale=None):
    # q,k,v: [B, T, H, D] (paddle layout)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    qh = jnp.swapaxes(q, 1, 2)  # B,H,T,D
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32) * s
    if is_causal:
        Tq, Tk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None, name=None):
    """Flash attention on TPU; XLA reference composition elsewhere.

    Layout follows paddle incubate fused attention: [batch, seq, heads, dim].
    """
    from ...ops import flash_attention_available, flash_attention

    use_flash = (flash_attention_available() and dropout_p == 0.0
                 and attn_mask is None)
    if use_flash:
        return flash_attention(query, key, value, causal=is_causal,
                               scale=scale)

    def fn(q, k, v, *rest):
        m = rest[0] if rest else None
        return _sdpa_reference(q, k, v, m, dropout_p, is_causal, scale)

    args = [query, key, value]
    if attn_mask is not None:
        args.append(attn_mask)
    return apply_op(fn, *args)


def sparse_attention(query, key, value, sparse_csr_offset=None,
                     sparse_csr_columns=None, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention (reference: nn/functional/sparse_attention.py,
    CUDA-only there). TPU design: we compute dense flash attention with the
    sparsity pattern applied as a mask — XLA/Pallas tiles skip fully-masked
    blocks. CSR pattern is converted to a dense boolean mask."""
    if sparse_csr_offset is None:
        return scaled_dot_product_attention(query, key, value,
                                            attn_mask=attn_mask)

    def fn(q, k, v, off, cols):
        import jax
        T = q.shape[1]

        def row_mask(off_bh, cols_bh):
            # entry j lives in row r iff off[r] <= j < off[r+1]; invalid
            # tail entries (j >= nnz) are routed to row T and dropped by
            # the scatter's out-of-bounds rule. One vectorized scatter —
            # no host loop, works under jit.
            nnz = cols_bh.shape[0]
            j = jnp.arange(nnz)
            rows = jnp.searchsorted(off_bh.astype(jnp.int32), j,
                                    side="right") - 1
            rows = jnp.where(j < off_bh[-1], rows, T)
            return jnp.zeros((T, T), bool).at[rows, cols_bh].set(
                True, mode="drop")

        mask = jax.vmap(jax.vmap(row_mask))(off, cols)
        return _sdpa_reference(q, k, v, mask)
    return apply_op(fn, query, key, value, sparse_csr_offset,
                    sparse_csr_columns)
