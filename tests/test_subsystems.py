"""Feature tests: io, amp, jit/static/inference, vision, hapi, metric,
distribution, fft/signal, runtime (SURVEY.md §2.5–2.13)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick commit gate no


class TestIO:
    def test_dataloader_order_and_coverage(self):
        from paddle_tpu.io import Dataset, DataLoader

        class DS(Dataset):
            def __getitem__(self, i):
                return np.full((3,), i, np.float32), np.int64(i)

            def __len__(self):
                return 25

        dl = DataLoader(DS(), batch_size=4, num_workers=2)
        seen = sorted(int(v) for b in dl for v in b[1].numpy())
        assert seen == list(range(25))

    def test_samplers(self):
        from paddle_tpu.io import (BatchSampler, RandomSampler,
                                   WeightedRandomSampler,
                                   DistributedBatchSampler, TensorDataset)
        ds = TensorDataset([paddle.arange(10)])
        bs = BatchSampler(ds, batch_size=3, drop_last=True)
        assert len(bs) == 3
        ws = WeightedRandomSampler([0.0, 1.0, 0.0], 10)
        assert all(i == 1 for i in ws)
        dbs = DistributedBatchSampler(ds, batch_size=2, num_replicas=2,
                                      rank=0)
        batches = list(dbs)
        assert all(len(b) <= 2 for b in batches)

    def test_save_load_roundtrip(self):
        m = nn.Linear(3, 2)
        d = tempfile.mkdtemp()
        path = os.path.join(d, "model.pdparams")
        paddle.save(m.state_dict(), path)
        sd = paddle.load(path)
        m2 = nn.Linear(3, 2)
        m2.set_state_dict(sd)
        np.testing.assert_array_equal(m2.weight.numpy(), m.weight.numpy())

    def test_random_split_concat(self):
        from paddle_tpu.io import TensorDataset, random_split, ConcatDataset
        ds = TensorDataset([paddle.arange(10)])
        a, b = random_split(ds, [7, 3])
        assert len(a) == 7 and len(b) == 3
        cc = ConcatDataset([a, b])
        assert len(cc) == 10


class TestAMP:
    def test_autocast_state(self):
        from paddle_tpu.amp import auto_cast, is_auto_cast_enabled, amp_cast
        import jax.numpy as jnp
        assert not is_auto_cast_enabled()
        with auto_cast(level="O2"):
            assert is_auto_cast_enabled()
            x = paddle.ones([2, 2])
            y = amp_cast(x, "matmul")
            assert y.value.dtype == jnp.bfloat16
            z = amp_cast(x, "softmax")
            assert z.value.dtype == jnp.float32
        assert not is_auto_cast_enabled()

    def test_grad_scaler_fp16_skip(self):
        from paddle_tpu.amp import GradScaler
        p = paddle.framework.Parameter(np.array([1.0], np.float32))
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        scaler = GradScaler(init_loss_scaling=4.0,
                            decr_every_n_nan_or_inf=1)
        loss = (p * 2).sum()
        scaler.scale(loss).backward()
        scaler.step(o)
        scaler.update()
        np.testing.assert_allclose(p.numpy(), [1.0 - 0.1 * 2], rtol=1e-6)
        # inf grad → step skipped, scale halved
        o.clear_grad()
        p.grad = paddle.to_tensor(np.array([np.inf], np.float32))
        before = p.numpy().copy()
        scaler._found_inf = False
        scaler.unscale_(o)
        assert scaler._found_inf
        scaler._unscaled = True
        scaler.step(o)
        scaler.update()
        np.testing.assert_array_equal(p.numpy(), before)
        assert scaler.get_loss_scaling() == 2.0

    def test_decorate_o2(self):
        import jax.numpy as jnp
        m = nn.Linear(2, 2)
        m2, o2 = paddle.amp.decorate(
            m, opt.SGD(parameters=m.parameters()), level="O2")
        assert m2.weight.value.dtype == jnp.bfloat16


class TestJitStaticInference:
    def test_jit_save_load_predictor(self):
        from paddle_tpu import jit, inference
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        d = tempfile.mkdtemp()
        prefix = os.path.join(d, "inf")
        jit.save(m, prefix, input_spec=[jit.InputSpec([None, 4],
                                                      "float32")])
        cfg = inference.Config(prefix)
        pred = inference.create_predictor(cfg)
        x = np.random.RandomState(0).rand(5, 4).astype(np.float32)
        out = pred.run([x])
        np.testing.assert_allclose(
            out[0], m(paddle.to_tensor(x)).numpy(), rtol=1e-5)

    def test_predictor_handles(self):
        from paddle_tpu import jit, inference
        m = nn.Linear(3, 2)
        d = tempfile.mkdtemp()
        prefix = os.path.join(d, "h")
        jit.save(m, prefix, input_spec=[jit.InputSpec([None, 3],
                                                      "float32")])
        pred = inference.create_predictor(inference.Config(prefix))
        names = pred.get_input_names()
        h = pred.get_input_handle(names[0])
        x = np.ones((2, 3), np.float32)
        h.copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0])
        np.testing.assert_allclose(out.copy_to_cpu(),
                                   m(paddle.to_tensor(x)).numpy(),
                                   rtol=1e-5)

    def test_static_program(self):
        import paddle_tpu.static as static
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")

        def builder(x):
            return paddle.matmul(x, paddle.ones([4, 2]))
        prog.set_builder(builder)
        exe = static.Executor()
        out = exe.run(prog, feed={"x": np.ones((3, 4), np.float32)},
                      fetch_list=None)
        np.testing.assert_allclose(out[0], np.full((3, 2), 4.0))

    def test_to_static_consistency(self):
        from paddle_tpu import jit
        paddle.seed(1)
        m = nn.Sequential(nn.Linear(4, 4), nn.Tanh())
        sm = jit.to_static(m)
        x = paddle.randn([3, 4])
        np.testing.assert_allclose(sm(x).numpy(), m(x).numpy(), rtol=1e-5)


class TestVision:
    @pytest.mark.parametrize("factory,shape", [
        ("resnet18", (1, 3, 64, 64)),
        pytest.param("mobilenet_v2", (1, 3, 64, 64),
                     marks=pytest.mark.heavy),
        pytest.param("vgg11", (1, 3, 224, 224),
                     marks=pytest.mark.heavy),
    ])
    def test_models_forward(self, factory, shape):
        import paddle_tpu.vision.models as vm
        m = getattr(vm, factory)(num_classes=7)
        m.eval()
        out = m(paddle.randn(list(shape)))
        assert out.shape == [1, 7]

    def test_lenet(self):
        from paddle_tpu.vision.models import LeNet
        m = LeNet()
        assert m(paddle.randn([2, 1, 28, 28])).shape == [2, 10]

    def test_transforms(self):
        from paddle_tpu.vision import transforms as T
        img = (np.random.RandomState(0).rand(32, 48, 3) * 255).astype(
            np.uint8)
        out = T.Compose([T.Resize(16), T.CenterCrop(12), T.ToTensor()])(img)
        assert list(out.shape) == [3, 12, 12]
        norm = T.Normalize(mean=[0.5] * 3, std=[0.5] * 3)(out)
        assert abs(float(norm.numpy().mean())) < 5
        flipped = T.functional_hflip if False else T.hflip(img)
        np.testing.assert_array_equal(flipped, img[:, ::-1])

    def test_fake_data_pipeline(self):
        from paddle_tpu.vision.datasets import FakeData
        from paddle_tpu.io import DataLoader
        ds = FakeData(size=8, image_shape=(3, 8, 8), num_classes=3)
        dl = DataLoader(ds, batch_size=4)
        xb, yb = next(iter(dl))
        assert xb.shape == [4, 3, 8, 8]

    def test_nms(self):
        from paddle_tpu.vision.ops import nms
        boxes = paddle.to_tensor(np.array(
            [[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
            np.float32))
        scores = paddle.to_tensor(np.array([0.9, 0.8, 0.7], np.float32))
        keep = nms(boxes, 0.5, scores)
        assert list(keep.numpy()) == [0, 2]

    def test_roi_align_shape(self):
        from paddle_tpu.vision.ops import roi_align
        x = paddle.randn([1, 4, 16, 16])
        rois = paddle.to_tensor(np.array([[0, 0, 8, 8], [4, 4, 12, 12]],
                                         np.float32))
        num = paddle.to_tensor(np.array([2], np.int32))
        out = roi_align(x, rois, num, output_size=4)
        assert out.shape == [2, 4, 4, 4]


class TestHapi:
    def test_model_fit_eval_predict(self):
        from paddle_tpu.io import Dataset
        from paddle_tpu.metric import Accuracy

        class DS(Dataset):
            def __init__(self, n=32):
                rng = np.random.RandomState(0)
                self.x = rng.rand(n, 4).astype(np.float32)
                self.y = (self.x.sum(1) > 2).astype(np.int64)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

            def __len__(self):
                return len(self.x)

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
        model = paddle.Model(net)
        model.prepare(opt.Adam(learning_rate=0.05,
                               parameters=net.parameters()),
                      nn.CrossEntropyLoss(), Accuracy())
        model.fit(DS(), epochs=12, batch_size=16, verbose=0)
        res = model.evaluate(DS(), batch_size=16, verbose=0)
        assert res["acc"] > 0.8
        preds = model.predict(DS(), batch_size=16, stack_outputs=True)
        assert preds[0].shape == (32, 2)

    def test_summary_and_flops(self):
        net = nn.Sequential(nn.Linear(8, 4), nn.ReLU(), nn.Linear(4, 2))
        info = paddle.summary(net, (1, 8))
        assert info["total_params"] == 8 * 4 + 4 + 4 * 2 + 2
        fl = paddle.flops(net, (1, 8))
        assert fl > 0


class TestMetric:
    def test_accuracy(self):
        from paddle_tpu.metric import Accuracy
        m = Accuracy()
        pred = paddle.to_tensor(np.array([[0.1, 0.9], [0.8, 0.2]],
                                         np.float32))
        lab = paddle.to_tensor(np.array([[1], [1]]))
        m.update(m.compute(pred, lab))
        assert abs(m.accumulate() - 0.5) < 1e-6

    def test_precision_recall_auc(self):
        from paddle_tpu.metric import Precision, Recall, Auc
        p, r, a = Precision(), Recall(), Auc()
        preds = np.array([0.9, 0.8, 0.2, 0.1], np.float32)
        labels = np.array([1, 0, 1, 0])
        for m in (p, r, a):
            m.update(preds, labels)
        assert abs(p.accumulate() - 0.5) < 1e-6
        assert abs(r.accumulate() - 0.5) < 1e-6
        assert 0 <= a.accumulate() <= 1


class TestDistribution:
    def test_normal(self):
        from paddle_tpu.distribution import Normal, kl_divergence
        from scipy import stats
        d = Normal(0.0, 1.0)
        lp = d.log_prob(paddle.to_tensor([0.5])).numpy()
        np.testing.assert_allclose(lp, stats.norm.logpdf([0.5]), rtol=1e-5)
        paddle.seed(0)
        s = d.sample([5000])
        assert abs(float(s.numpy().mean())) < 0.1
        kl = kl_divergence(Normal(0.0, 1.0), Normal(1.0, 2.0))
        ref = np.log(2) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(kl.numpy(), ref, rtol=1e-5)

    def test_categorical_uniform(self):
        from paddle_tpu.distribution import Categorical, Uniform
        # reference Categorical takes unnormalized probability WEIGHTS
        # (categorical.py probs doc example), so uniform = equal weights
        c = Categorical(logits=paddle.to_tensor([1.0, 1.0]))
        np.testing.assert_allclose(c.entropy().numpy(), np.log(2),
                                   rtol=1e-5)
        u = Uniform(0.0, 2.0)
        np.testing.assert_allclose(
            u.log_prob(paddle.to_tensor([1.0])).numpy(), [-np.log(2)],
            rtol=1e-5)


class TestFFTSignal:
    def test_fft_roundtrip(self):
        x = paddle.randn([8])
        y = paddle.fft.ifft(paddle.fft.fft(x))
        np.testing.assert_allclose(y.numpy().real, x.numpy(), atol=1e-5)

    def test_rfft_matches_numpy(self):
        a = np.random.RandomState(0).rand(16).astype(np.float32)
        got = paddle.fft.rfft(paddle.to_tensor(a)).numpy()
        np.testing.assert_allclose(got, np.fft.rfft(a), rtol=1e-4,
                                   atol=1e-4)

    def test_stft_istft_roundtrip(self):
        sig = np.sin(np.linspace(0, 20 * np.pi, 512)).astype(np.float32)
        x = paddle.to_tensor(sig)
        spec = paddle.signal.stft(x, n_fft=64, hop_length=16)
        rec = paddle.signal.istft(spec, n_fft=64, hop_length=16,
                                  length=512)
        np.testing.assert_allclose(rec.numpy(), sig, atol=1e-3)

    def test_frame_overlap_add(self):
        x = paddle.arange(0, 16).astype("float32")
        f = paddle.signal.frame(x, 4, 2)
        assert f.shape == [4, 7]


class TestRuntime:
    def test_native_ring_buffer(self):
        from paddle_tpu.runtime import get_lib
        import ctypes
        lib = get_lib()
        assert lib is not None, "native runtime must build in this image"
        rb = lib.rb_create(4)
        assert lib.rb_push(rb, 42, 0) == 0
        out = ctypes.c_uint64()
        assert lib.rb_pop(rb, ctypes.byref(out), 0) == 0
        assert out.value == 42
        lib.rb_close(rb)
        assert lib.rb_pop(rb, ctypes.byref(out), 0) == -1
        lib.rb_destroy(rb)

    def test_fast_collate(self):
        from paddle_tpu.runtime import fast_collate_numpy
        arrs = [np.random.rand(128, 128).astype(np.float32)
                for _ in range(16)]
        np.testing.assert_array_equal(fast_collate_numpy(arrs),
                                      np.stack(arrs))


class TestText:
    def test_viterbi(self):
        from paddle_tpu.text import viterbi_decode
        emis = paddle.to_tensor(np.array(
            [[[1.0, 0.0], [0.0, 1.0], [1.0, 0.0]]], np.float32))
        trans = paddle.to_tensor(np.array([[0.5, 0.0], [0.0, 0.5]],
                                          np.float32))
        score, path = viterbi_decode(emis, trans)
        assert path.shape == [1, 3]


class TestIncubate:
    def test_segment_ops(self):
        from paddle_tpu.incubate import segment_sum, segment_mean
        data = paddle.to_tensor(np.array([1., 2., 3., 4.], np.float32))
        ids = paddle.to_tensor(np.array([0, 0, 1, 1]))
        np.testing.assert_allclose(segment_sum(data, ids).numpy(), [3., 7.])
        np.testing.assert_allclose(segment_mean(data, ids).numpy(),
                                   [1.5, 3.5])

    def test_lookahead(self):
        from paddle_tpu.incubate import optimizer as iopt
        p = paddle.framework.Parameter(np.array([1.0], np.float32))
        inner = opt.SGD(learning_rate=0.1, parameters=[p])
        la = iopt.LookAhead(inner, alpha=0.5, k=2)
        for _ in range(4):
            (p * p).sum().backward()
            la.step()
            la.clear_grad()
        assert p.numpy()[0] < 1.0


class TestStaticExecutorTraining:
    """Executor.run executes ONE optimizer step per call (reference
    executor semantics): params update, loss decreases across run()
    calls — the round-4 review repro showed loss frozen before this."""

    def test_loss_decreases_across_runs(self):
        import paddle_tpu.static as static
        paddle.enable_static()
        try:
            main_prog = static.Program()
            start_prog = static.Program()
            with static.program_guard(main_prog, start_prog):
                x = static.data(name="x", shape=[None, 8])
                y = static.data(name="y", shape=[None, 1])
                pred = static.nn.fc(x, 1)
                loss = paddle.mean(
                    paddle.nn.functional.square_error_cost(pred, y))
                sgd = opt.SGD(learning_rate=0.1)
                sgd.minimize(loss)

            exe = static.Executor()
            exe.run(start_prog)
            rs = np.random.RandomState(0)
            X = rs.randn(16, 8).astype("float32")
            Y = (X @ rs.randn(8, 1)).astype("float32")
            losses = [float(exe.run(main_prog, feed={"x": X, "y": Y},
                                    fetch_list=[loss])[0])
                      for _ in range(10)]
            assert losses[-1] < losses[0] * 0.7, losses
            # fetch-by-unnamed-name resolves to the minimized loss
            out = exe.run(main_prog, feed={"x": X, "y": Y},
                          fetch_list=loss.name)
            assert np.asarray(out[0]).shape == ()
        finally:
            paddle.disable_static()

    def test_unresolvable_fetch_raises(self):
        import paddle_tpu.static as static
        paddle.enable_static()
        try:
            prog = static.Program()
            with static.program_guard(prog):
                x = static.data(name="x", shape=[None, 4])
                z = static.nn.fc(x, 2)
            exe = static.Executor()
            with pytest.raises(ValueError, match="cannot resolve"):
                exe.run(prog, feed={"x": np.zeros((2, 4), np.float32)},
                        fetch_list=["not_a_var"])
        finally:
            paddle.disable_static()
