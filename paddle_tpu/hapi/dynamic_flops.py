"""paddle.flops. Parity: python/paddle/hapi/dynamic_flops.py."""
import numpy as np

from ..framework.core import Tensor

__all__ = ["flops"]


def _conv_flops(layer, ins, out):
    k = int(np.prod(layer._kernel_size))
    cin = layer._in_channels // layer._groups
    out_elems = out.size
    return out_elems * (2 * cin * k - 1)


def _linear_flops(layer, ins, out):
    return out.size * (2 * layer._in_features - 1)


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .. import zeros
    total = [0]
    hooks = []
    custom_ops = custom_ops or {}

    def make_hook(layer):
        def hook(l, ins, out):
            ty = type(l).__name__
            if type(l) in custom_ops:
                total[0] += custom_ops[type(l)](l, ins, out)
            elif ty.startswith("Conv"):
                total[0] += _conv_flops(l, ins, out)
            elif ty == "Linear":
                total[0] += _linear_flops(l, ins, out)
            elif "Norm" in ty or ty.startswith("ReLU"):
                total[0] += out.size if isinstance(out, Tensor) else 0
        return hook

    for _, layer in net.named_sublayers():
        if not layer._sub_layers:
            hooks.append(layer.register_forward_post_hook(make_hook(layer)))
    x = zeros(list(input_size))
    was_training = net.training
    net.eval()
    try:
        net(x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"Total FLOPs: {total[0]:,}")
    return int(total[0])
