"""Recurrent layers. Parity: python/paddle/nn/layer/rnn.py.

The reference dispatches to cuDNN RNN kernels; on TPU the recurrence is a
lax.scan whose per-step cell math is MXU matmuls — XLA pipelines the scan,
and multi-layer/bidirectional stacks compose functionally.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply_op
from .. import functional as F
from .. import initializer as I
from .layers import Layer
from .container import LayerList

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        B = batch_ref.shape[batch_dim_idx]
        from ...tensor.creation import full
        state_shape = shape or self.state_shape
        if isinstance(state_shape, tuple):
            return tuple(full([B] + list(s), init_value,
                              dtype or "float32") for s in state_shape)
        return full([B] + list(state_shape), init_value,
                    dtype or "float32")


def _uniform_init(hidden_size):
    k = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-k, k)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _uniform_init(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)
        self.activation = activation

    @property
    def state_shape(self):
        return [self.hidden_size]

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def fn(x, h, wih, whh, bih, bhh):
            out = act(x @ wih.T + bih + h @ whh.T + bhh)
            return out
        h = apply_op(fn, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _uniform_init(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)

    @property
    def state_shape(self):
        return ([self.hidden_size], [self.hidden_size])

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(
                inputs, shape=self.state_shape)
        h, c = states
        H = self.hidden_size

        def fn(x, hh, cc, wih, whh, bih, bhh):
            gates = x @ wih.T + bih + hh @ whh.T + bhh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = f * cc + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h_new, c_new = apply_op(fn, inputs, h, c, self.weight_ih,
                                self.weight_hh, self.bias_ih, self.bias_hh)
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _uniform_init(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True,
            default_initializer=init)

    @property
    def state_shape(self):
        return [self.hidden_size]

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, h, wih, whh, bih, bhh):
            gi = x @ wih.T + bih
            gh = h @ whh.T + bhh
            i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
            h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n + r * h_n)
            return (1 - z) * n + z * h
        h = apply_op(fn, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh)
        return h, h


def _cell_scan(cell, xs, init_states, reverse=False):
    """Run a cell over [T, B, I] with lax.scan on raw arrays."""
    wih, whh = cell.weight_ih.value, cell.weight_hh.value
    bih, bhh = cell.bias_ih.value, cell.bias_hh.value
    is_lstm = isinstance(cell, LSTMCell)
    is_gru = isinstance(cell, GRUCell)
    act = jnp.tanh if getattr(cell, "activation", "tanh") == "tanh" \
        else jax.nn.relu

    def step(carry, x):
        if is_lstm:
            h, c = carry
            gates = x @ wih.T + bih + h @ whh.T + bhh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
            return (h_new, c_new), h_new
        if is_gru:
            h = carry
            gi = x @ wih.T + bih
            gh = h @ whh.T + bhh
            i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
            h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n + r * h_n)
            h_new = (1 - z) * n + z * h
            return h_new, h_new
        h = carry
        h_new = act(x @ wih.T + bih + h @ whh.T + bhh)
        return h_new, h_new

    final, ys = jax.lax.scan(step, init_states, xs, reverse=reverse)
    return final, ys


class RNN(Layer):
    """Wraps a cell into a full sequence loop (lax.scan)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        cell = self.cell
        is_lstm = isinstance(cell, LSTMCell)
        tm = self.time_major
        rev = self.is_reverse

        tensors = [inputs, cell.weight_ih, cell.weight_hh, cell.bias_ih,
                   cell.bias_hh]
        init_given = initial_states is not None
        if init_given:
            if is_lstm:
                tensors += [initial_states[0], initial_states[1]]
            else:
                tensors += [initial_states]

        def fn(x, wih, whh, bih, bhh, *init):
            xs = x if tm else jnp.swapaxes(x, 0, 1)   # [T,B,I]
            B = xs.shape[1]
            H = cell.hidden_size
            if init:
                carry = (init[0], init[1]) if is_lstm else init[0]
            else:
                z = jnp.zeros((B, H), xs.dtype)
                carry = (z, z) if is_lstm else z
            final, ys = _cell_scan(cell, xs, carry, reverse=rev)
            out = ys if tm else jnp.swapaxes(ys, 0, 1)
            if is_lstm:
                return out, final[0], final[1]
            return out, final

        res = apply_op(fn, *tensors)
        if is_lstm:
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_fw = st_bw = None
        if initial_states is not None:
            st_fw, st_bw = initial_states
        out_fw, s_fw = self.rnn_fw(inputs, st_fw)
        out_bw, s_bw = self.rnn_bw(inputs, st_bw)
        from ...tensor.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (s_fw, s_bw)


class _RNNBase(Layer):
    CELL = SimpleRNNCell

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation=None, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirect else 1

        def make_cell(in_size):
            if self.CELL is SimpleRNNCell:
                return SimpleRNNCell(in_size, hidden_size,
                                     activation or "tanh", weight_ih_attr,
                                     weight_hh_attr, bias_ih_attr,
                                     bias_hh_attr)
            return self.CELL(in_size, hidden_size, weight_ih_attr,
                             weight_hh_attr, bias_ih_attr, bias_hh_attr)

        self.layers_fw = LayerList()
        self.layers_bw = LayerList() if self.bidirect else None
        for l in range(num_layers):
            in_size = input_size if l == 0 else hidden_size * num_dir
            self.layers_fw.append(make_cell(in_size))
            if self.bidirect:
                self.layers_bw.append(make_cell(in_size))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat, stack
        is_lstm = self.CELL is LSTMCell
        x = inputs
        finals_h, finals_c = [], []
        for l in range(self.num_layers):
            fw = RNN(self.layers_fw[l], time_major=self.time_major)
            states_l = None
            if initial_states is not None:
                states_l = self._slice_states(initial_states, l, 0, is_lstm)
            out_fw, s_fw = fw(x, states_l)
            if self.bidirect:
                bw = RNN(self.layers_bw[l], is_reverse=True,
                         time_major=self.time_major)
                states_lb = None
                if initial_states is not None:
                    states_lb = self._slice_states(initial_states, l, 1,
                                                   is_lstm)
                out_bw, s_bw = bw(x, states_lb)
                x = concat([out_fw, out_bw], axis=-1)
                if is_lstm:
                    finals_h += [s_fw[0], s_bw[0]]
                    finals_c += [s_fw[1], s_bw[1]]
                else:
                    finals_h += [s_fw, s_bw]
            else:
                x = out_fw
                if is_lstm:
                    finals_h.append(s_fw[0])
                    finals_c.append(s_fw[1])
                else:
                    finals_h.append(s_fw)
            if self.dropout and l < self.num_layers - 1:
                x = F.dropout(x, self.dropout, training=self.training)
        h = stack(finals_h, axis=0)
        if is_lstm:
            c = stack(finals_c, axis=0)
            return x, (h, c)
        return x, h

    def _slice_states(self, initial_states, layer, direction, is_lstm):
        num_dir = 2 if self.bidirect else 1
        idx = layer * num_dir + direction
        if is_lstm:
            h, c = initial_states
            return h[idx], c[idx]
        return initial_states[idx]


class SimpleRNN(_RNNBase):
    CELL = SimpleRNNCell


class LSTM(_RNNBase):
    CELL = LSTMCell


class GRU(_RNNBase):
    CELL = GRUCell
