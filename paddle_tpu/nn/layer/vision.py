"""Vision layers. Parity: python/paddle/nn/layer/vision.py."""
from .. import functional as F
from .layers import Layer

__all__ = ["PixelShuffle", "PixelUnshuffle", "ChannelShuffle"]


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._factor = upscale_factor
        self._data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self._factor, self._data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self._factor = downscale_factor
        self._data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self._factor, self._data_format)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self._groups = groups
        self._data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self._groups, self._data_format)
