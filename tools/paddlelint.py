#!/usr/bin/env python
"""paddlelint — unified concurrency + tracing-safety static analysis.

One driver, five passes (tools/lint/ — docs/STATIC_ANALYSIS.md):
lock-order (static deadlock detection over the cross-module
lock-acquisition graph), blocking-under-lock (file I/O, device reads,
waits, JSONL export while holding a lock; unbounded explicit
acquire()), unlocked-shared-state (thread-written fields read
elsewhere lock-free), use-after-donate (reads of donated buffers
after dispatch), and hot-sync (the check_no_hot_sync fence, now a
framework pass — the old CLI is a thin shim over it).

Runs from tier-1 like the other gates (tests/test_static_analysis.py)
and inside the canonical workload (tools/_gate_common.py), emitting
machine-readable `kind:"lint"` findings JSONL — schema enforced by
tools/check_metrics_schema.py, rendered by tools/obs_report.py.

Suppressions: `# lint-ok: <why>` (any pass) or
`# lint-ok[pass-name]: <why>` on the finding's line; a marker without
a reason is itself a finding. Pass-level region tables
(hot_sync.HOT_REGIONS, blocking_under_lock.ALLOWED_BLOCKING) emit
SUPPRESSED findings with the table's reason. LINT_BASELINE.json
ratchets the per-pass suppressed counts: unsuppressed findings always
fail; growth in suppressions fails until the baseline is raised BY
HAND in the diff; `--update` only ever ratchets counts down.

Usage:
  python tools/paddlelint.py [ROOT] [--select p1,p2] [--jsonl OUT]
                             [--baseline PATH] [--update] [--list]

ROOT defaults to the repo; pointing it at a fixture corpus
(tools/lint/fixtures/<pass>, with --select) must exit 1 — the linter
proving it still catches its known-bad snippets. Exit 0 clean, 1
findings/ratchet regression, 2 usage error.
"""
import argparse
import json
import os
import sys

_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from lint import ALL_PASSES, PASS_NAMES, get_pass  # noqa: E402
from lint import core  # noqa: E402

REPO = os.path.dirname(_TOOLS)
BASELINE_NAME = "LINT_BASELINE.json"


def _rank():
    """Process rank from the launch env (tools stay framework-free —
    mirror of profiler/monitor.rank)."""
    for var in ("PADDLE_TPU_PROCESS_ID", "PADDLE_TRAINER_ID"):
        v = os.environ.get(var)
        if v:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def run_passes(root=None, select=None):
    """(findings, ctx) for the selected passes over `root` — the
    library entry tools/_gate_common.py and the tests use. Findings
    arrive suppression-applied, in pass-registration order."""
    root = os.path.abspath(root or REPO)
    # layout detection, not path identity: any repo-shaped checkout —
    # symlinked spelling, git worktree, CI copy — gets the curated
    # fileset (linter fixtures excluded); anything else (a fixture
    # corpus dir) is walked whole
    if os.path.isdir(os.path.join(root, "paddle_tpu")) and \
            os.path.isdir(os.path.join(root, "tools", "lint")):
        rels = core.default_fileset(root)
    else:
        rels = core.walk_fileset(root)
    ctx = core.ProjectContext(root, rels)
    findings = []
    names = list(select) if select else list(PASS_NAMES)
    for name in names:
        findings.extend(get_pass(name).run(ctx))
    findings = core.apply_suppressions(ctx, findings)
    order = {n: i for i, n in enumerate(names + ["suppression"])}
    findings.sort(key=lambda f: (order.get(f.pass_name, 99), f.file,
                                 f.line))
    return findings, ctx


def records(findings):
    """The `kind:"lint"` JSONL dicts for a finding list (suppressed
    findings included — the ledger accounts for every deliberate
    exemption)."""
    rank = _rank()
    return [f.record(rank=rank) for f in findings]


def write_jsonl(path, findings):
    with open(path, "a") as f:
        for rec in records(findings):
            f.write(json.dumps(rec) + "\n")


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="paddlelint", description=__doc__.splitlines()[0])
    ap.add_argument("root", nargs="?", default=REPO,
                    help="analysis root (default: the repo; point at "
                         "a fixture corpus to prove a pass stays red)")
    ap.add_argument("--select", default=None,
                    help="comma-separated pass subset (default: all)")
    ap.add_argument("--jsonl", default=None,
                    help="append kind:'lint' findings JSONL here "
                         "(PADDLE_TPU_METRICS_FILE is appended too "
                         "when set)")
    ap.add_argument("--baseline", default=None,
                    help="ratchet file (default: ROOT/LINT_BASELINE."
                         "json when present)")
    ap.add_argument("--update", action="store_true",
                    help="ratchet the baseline DOWN to the current "
                         "suppressed counts (never up)")
    ap.add_argument("--list", action="store_true",
                    help="list passes and exit")
    args = ap.parse_args(argv)

    if args.list:
        for cls in ALL_PASSES:
            doc = (sys.modules[cls.__module__].__doc__ or
                   "").strip().splitlines()[0]
            print(f"{cls.name:<24} {doc}")
        return 0

    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
        unknown = [s for s in select if s not in PASS_NAMES]
        if unknown:
            print(f"unknown pass(es): {', '.join(unknown)} "
                  f"(known: {', '.join(PASS_NAMES)})", file=sys.stderr)
            return 2

    findings, _ctx = run_passes(args.root, select)

    for f in findings:
        print(f.render())

    out = args.jsonl
    envfile = os.environ.get("PADDLE_TPU_METRICS_FILE")
    for path in {p for p in (out, envfile) if p}:
        try:
            write_jsonl(path, findings)
        except OSError as e:
            print(f"warning: could not write findings JSONL to "
                  f"{path}: {e}", file=sys.stderr)

    unsuppressed = [f for f in findings if not f.suppressed]
    counts = core.suppressed_counts(findings)
    selected = select or list(PASS_NAMES)

    bl_path = args.baseline or os.path.join(args.root, BASELINE_NAME)
    ratchet_errors = []
    baseline = core.load_baseline(bl_path)
    if baseline is None and os.path.exists(bl_path):
        # a PRESENT but unreadable baseline must fail closed — a
        # truncated/mangled file silently disabling the ratchet is
        # exactly the regression the gate exists to prevent
        ratchet_errors.append(
            f"baseline {bl_path} exists but is not a valid "
            f"{core.BASELINE_SCHEMA} file — fix or regenerate it")
    if baseline is not None:
        if args.update:
            wrote, refused = core.update_baseline(
                bl_path, baseline, counts, selected)
            for name in refused:
                ratchet_errors.append(
                    f"--update refused for pass {name!r}: current "
                    f"suppressed count "
                    f"{counts.get(name, 0)} exceeds the baseline — "
                    "the ratchet only tightens; raise the baseline "
                    "by hand if the new suppression is justified")
            if wrote:
                print(f"baseline ratcheted: {bl_path}")
        else:
            ratchet_errors = core.check_baseline(
                baseline, counts, selected)
    elif args.baseline:
        # an EXPLICITLY requested baseline that is missing fails
        # closed, same as a corrupt one: a typo'd --baseline flag in a
        # CI invocation must not silently disable the ratchet forever
        ratchet_errors.append(
            f"baseline {bl_path} was requested with --baseline but "
            "does not exist — fix the path or create the baseline "
            "with --update")

    for err in ratchet_errors:
        print(f"RATCHET: {err}")

    n_sup = sum(counts.values())
    if unsuppressed or ratchet_errors:
        print(f"FAIL: {len(unsuppressed)} finding(s), "
              f"{n_sup} suppressed, "
              f"{len(ratchet_errors)} ratchet error(s)")
        return 1
    print(f"OK: 0 findings ({n_sup} suppressed with reasons) across "
          f"{len(selected)} pass(es)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
