"""The compilation observatory: a per-executable compile/HLO ledger and
retrace forensics, fed from the ONE choke point every AOT compile flows
through (`jit/api.aot_compile` — the TrainStep / HybridTrainStep /
run_steps / accumulate / serving-bucket dispatch paths all use it).

Why this exists: the repo's standing failure mode is the compile-time
wall (ROADMAP open item 3 — five bench rounds dead at "stage: compile"
with no evidence of *which* executable ate the budget or *why* a step
retraced). Aggregated counters (`jit.retraces`, `jit.compile_s`) say how
much; this module keeps the per-executable WHAT:

- **one `kind:"compile"` record per (tag, signature)** — lower_s /
  compile_s split, persistent-cache hit vs cold compile, the abstract
  argument signature, and HLO-derived stats from the compiled
  executable itself: instruction counts by op kind, fusion count, bytes
  accessed + FLOPs (`cost_analysis()`, per *Operator Fusion in XLA*,
  arxiv 2301.13062 — XLA's own analysis is the fusion-accounting source
  of truth), and a peak-memory estimate (`memory_analysis()`). Records
  land in the flight-recorder ring (always) and the metrics JSONL
  (when `PADDLE_TPU_METRICS_FILE` is set; schema enforced by
  tools/check_metrics_schema.py).

- **retrace forensics** — when a tag that already compiled sees a NEW
  abstract signature, the observatory diffs it against the cached
  signatures *before* the expensive recompile starts and emits a
  structured `kind:"event"` (`event: "retrace"`) naming exactly which
  argument changed and how (shape / dtype / sharding / static value),
  so a retrace storm is a one-line diagnosis instead of archaeology.

- **the ratchet feedstock** — `tools/check_compile_budget.py` and
  `tools/check_fusion.py` compare ledger records against the checked-in
  `BASELINE_HLO.json` and fail CI on compile-seconds / fusion-count /
  bytes-accessed regressions (the *Neptune*-style locality/fusion cost
  framing, arxiv 2510.08726).

Listeners (`add_listener`) observe compile start/done live — bench.py
streams per-executable compile progress over its `bench-phase:` stderr
channel with one, so even a timed-out round names the executable that
was compiling when the budget died.

See docs/OBSERVABILITY.md "The compilation observatory".
"""
import collections
import hashlib
import re
import threading

__all__ = ["abstract_signature", "signature_key", "signature_str",
           "diff_signatures", "compile_started", "record_compile",
           "hlo_stats", "peak_memory_bytes", "ledger", "ledger_by_tag",
           "ledger_signatures", "aggregate", "add_listener",
           "remove_listener", "reset", "LEDGER_RING"]

LEDGER_RING = 256   # compile records kept in process (a debug bundle
                    # carries them all; steady jobs compile a handful)
TAG_SIGS = 32       # distinct signatures remembered per tag
MAX_TAGS = 64       # tags tracked for forensics
MAX_CHANGES = 8     # changes named per retrace event

_lock = threading.RLock()
_ledger = collections.deque(maxlen=LEDGER_RING)
_tag_sigs = collections.OrderedDict()   # tag -> OrderedDict(key -> sig)
_listeners = []


# -- abstract signatures -------------------------------------------------

def _leaf_desc(path, leaf):
    """One leaf of an argument as a hashable descriptor. Arrays (and
    ShapeDtypeStructs) keep shape/dtype/sharding — the things a retrace
    can hinge on; Python scalars keep only their type, mirroring jax's
    weak-typed aval semantics (a new VALUE of a traced Python int does
    NOT retrace, so it must not change the signature either)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        sh = getattr(leaf, "sharding", None)
        return (path, "array", tuple(int(d) for d in shape), str(dtype),
                str(sh) if sh is not None else None)
    return (path, "py", type(leaf).__name__)


def abstract_signature(args, static=None):
    """The (args_part, static_part) signature of one compile: per
    positional argument a tuple of leaf descriptors (pytrees flattened
    with paths), plus the caller-declared STATIC values that are baked
    into the traced program rather than passed as arrays (e.g.
    run_steps' segment length `n` — invisible in `args`, decisive for
    the executable)."""
    import jax
    arg_descs = []
    for a in args:
        flat, _ = jax.tree_util.tree_flatten_with_path(a)
        arg_descs.append(tuple(
            _leaf_desc(jax.tree_util.keystr(kp), leaf)
            for kp, leaf in flat))
    static_part = tuple(sorted(
        (str(k), repr(v)) for k, v in (static or {}).items()))
    return (tuple(arg_descs), static_part)


def signature_key(sig):
    """Stable short id of a signature (the `signature` field of the
    compile record — grep it across JSONL / traces / bundles)."""
    return hashlib.sha1(repr(sig).encode()).hexdigest()[:12]


def _arg_name(arg_names, i):
    if arg_names and i < len(arg_names):
        return str(arg_names[i])
    return f"arg{i}"


def signature_str(sig, arg_names=None, limit=400):
    """Compact human rendering: single-array args as `name=dtype[shape]`,
    pytrees as leaf counts, static values verbatim."""
    args_part, static_part = sig
    parts = []
    for i, leaves in enumerate(args_part):
        name = _arg_name(arg_names, i)
        if len(leaves) == 1 and not leaves[0][0]:
            d = leaves[0]
            if d[1] == "array":
                parts.append(f"{name}={d[3]}{list(d[2])}")
            else:
                parts.append(f"{name}:{d[2]}")
        else:
            parts.append(f"{name}={{{len(leaves)} leaves}}")
    for k, v in static_part:
        parts.append(f"{k}={v}")
    out = ", ".join(parts)
    return out if len(out) <= limit else out[:limit - 3] + "..."


def _render_leaf(d):
    if d is None:
        return "<absent>"
    if d[1] == "array":
        return f"{d[3]}{list(d[2])}"
    return d[2]


def diff_signatures(old, new, arg_names=None):
    """What changed between two signatures of one tag: a list of
    {"arg", "change", "from", "to"} dicts, `change` one of
    static / shape / dtype / sharding / structure / type / arity.
    Empty list = identical signatures."""
    changes = []
    old_args, old_static = old
    new_args, new_static = new
    os_, ns_ = dict(old_static), dict(new_static)
    for k in sorted(set(os_) | set(ns_)):
        if os_.get(k) != ns_.get(k):
            changes.append({"arg": k, "change": "static",
                            "from": os_.get(k, "<absent>"),
                            "to": ns_.get(k, "<absent>")})
    for i in range(max(len(old_args), len(new_args))):
        name = _arg_name(arg_names, i)
        if i >= len(old_args) or i >= len(new_args):
            changes.append({
                "arg": name, "change": "arity",
                "from": "<absent>" if i >= len(old_args) else "present",
                "to": "<absent>" if i >= len(new_args) else "present"})
            continue
        ol = {d[0]: d for d in old_args[i]}
        nl = {d[0]: d for d in new_args[i]}
        for path in sorted(set(ol) | set(nl)):
            o, n = ol.get(path), nl.get(path)
            label = f"{name}{path}" if path else name
            if o == n:
                continue
            if o is None or n is None:
                changes.append({"arg": label, "change": "structure",
                                "from": _render_leaf(o),
                                "to": _render_leaf(n)})
            elif o[1] != n[1]:
                changes.append({"arg": label, "change": "type",
                                "from": _render_leaf(o),
                                "to": _render_leaf(n)})
            elif o[1] == "py":
                changes.append({"arg": label, "change": "type",
                                "from": o[2], "to": n[2]})
            else:
                if o[2] != n[2]:
                    changes.append({"arg": label, "change": "shape",
                                    "from": str(list(o[2])),
                                    "to": str(list(n[2]))})
                if o[3] != n[3]:
                    changes.append({"arg": label, "change": "dtype",
                                    "from": o[3], "to": n[3]})
                if o[4] != n[4]:
                    changes.append({"arg": label, "change": "sharding",
                                    "from": str(o[4]), "to": str(n[4])})
    return changes


# -- HLO-derived stats ---------------------------------------------------

# an HLO instruction line is `%name = <type> <opcode>(...)`; opcodes are
# lowercase (add, fusion, all-reduce, custom-call...), which is what
# keeps TPU layout/tiling annotations like `{1,0:T(8,128)}` from
# miscounting as ops. Anchored to line start (MULTILINE) so finditer
# counts at most one opcode per line in a single C-level pass — the
# first `... = <type> opcode(` per line, same as a per-line search.
_OPCODE_RE = re.compile(r"^[^\n]*? = [^\n]*?([a-z][a-z0-9_-]*)\(",
                        re.MULTILINE)


def hlo_stats(compiled):
    """Instruction counts by op kind + fusion count from the compiled
    executable's optimized HLO text. {} -shaped zeros when the backend
    exposes no text — stats must never fail a compile."""
    try:
        text = compiled.as_text()
    except Exception:
        return {"instructions": 0, "fusion_count": 0, "op_counts": {}}
    counts = {}
    for m in _OPCODE_RE.finditer(text):
        op = m.group(1)
        counts[op] = counts.get(op, 0) + 1
    top = dict(sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[:32])
    return {"instructions": sum(counts.values()),
            "fusion_count": counts.get("fusion", 0),
            "op_counts": top}


def peak_memory_bytes(compiled):
    """Compile-time peak-memory estimate: arguments + outputs + temps
    minus aliased (donated) bytes, from the executable's own memory
    analysis. 0.0 when the backend exposes none."""
    try:
        ma = compiled.memory_analysis()
        total = 0.0
        for k in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes"):
            total += float(getattr(ma, k, 0) or 0)
        total -= float(getattr(ma, "alias_size_in_bytes", 0) or 0)
        return max(total, 0.0)
    except Exception:
        return 0.0


# -- the ledger ----------------------------------------------------------

def compile_started(tag, sig, arg_names=None):
    """Register a compile ABOUT to run (called before lowering, so the
    forensics land even when the compile itself then hangs past a
    timeout). When `tag` has compiled before under a different
    signature, diff against the closest cached one and emit the
    structured retrace event. Returns (signature key, changes)."""
    key = signature_key(sig)
    with _lock:
        sigs = _tag_sigs.get(tag)
        first = sigs is None
        if first:
            sigs = _tag_sigs[tag] = collections.OrderedDict()
            while len(_tag_sigs) > MAX_TAGS:
                _tag_sigs.popitem(last=False)
        known = key in sigs
        cached = [] if known else list(sigs.values())
        if not known:
            sigs[key] = sig
            while len(sigs) > TAG_SIGS:
                sigs.popitem(last=False)
    retrace = bool(cached)  # a NEW signature for an already-seen tag
    changes = []
    if retrace:
        # closest cached signature = fewest differences: the event
        # names the MINIMAL change that forced this recompile
        best = None
        for old in cached:
            d = diff_signatures(old, sig, arg_names=arg_names)
            if best is None or len(d) < len(best):
                best = d
        changes = (best or [])[:MAX_CHANGES]
        summary = "; ".join(
            f"{c['arg']}: {c['change']} {c['from']} -> {c['to']}"
            for c in changes) or "signature changed"
        try:
            from . import flight_recorder as _flight
            from . import monitor as _monitor
            _flight.record_event(
                "retrace", tag=str(tag), signature=key,
                n_signatures=len(cached) + 1, changes=changes,
                summary=summary[:400])
            _monitor.counter("jit.retrace_events").inc()
        except Exception:
            pass
    _notify({"phase": "start", "tag": str(tag), "signature": key,
             "retrace": retrace, "changes": changes})
    return key, changes


def record_compile(tag, sig, sig_key, lower_s, compile_s, cache_hit,
                   compiled, cost=None, arg_names=None,
                   cache_entries_added=0):
    """One finished compile -> one ledger entry + one `kind:"compile"`
    record (flight-recorder ring always; metrics JSONL when configured).
    Returns the record. Never raises — the ledger is telemetry."""
    try:
        stats = hlo_stats(compiled)
        cost = cost or {}
        rec = {
            "tag": str(tag),
            "signature": sig_key,
            "args": signature_str(sig, arg_names=arg_names),
            "lower_s": round(max(float(lower_s), 0.0), 6),
            "compile_s": round(max(float(compile_s), 0.0), 6),
            "cache_hit": bool(cache_hit),
            "instructions": int(stats["instructions"]),
            "fusion_count": int(stats["fusion_count"]),
            "op_counts": stats["op_counts"],
            # cost_analysis can answer -1 for "unknown"; the schema (and
            # the ratchet math) want "unknown" as 0
            "flops": max(float(cost.get("flops", 0.0)), 0.0),
            "bytes_accessed": max(
                float(cost.get("bytes accessed", 0.0)), 0.0),
            "peak_memory_bytes": peak_memory_bytes(compiled),
            "cache_entries_added": int(cache_entries_added),
        }
        with _lock:
            _ledger.append(dict(rec))
        from . import monitor as _monitor
        _monitor.export_step(rec, kind="compile")
        _notify({"phase": "done", "tag": str(tag), "record": rec})
        return rec
    except Exception:
        return None


def ledger():
    """All compile records this process holds (ring-bounded), oldest
    first — the table a debug bundle and bench.py's `compile_ledger`
    key render."""
    with _lock:
        return [dict(r) for r in _ledger]


def ledger_by_tag():
    """{tag: [records]} view of the ledger."""
    out = {}
    for r in ledger():
        out.setdefault(r["tag"], []).append(r)
    return out


def ledger_signatures():
    """The set of (tag, signature-key) pairs compiled so far — the
    executable-sharing warmup contract's comparand: snapshot after
    `warm()`/`jit.warm.join`, snapshot again after steady-state traffic,
    and an EQUAL set proves warming added zero executables beyond the
    steady-state set (tests/test_warm_pipeline.py asserts exactly
    this; tools/_gate_common.py enforces it on the canonical
    workload)."""
    with _lock:
        return {(r["tag"], r["signature"]) for r in _ledger}


def aggregate(records=None):
    """Per-tag rollup of compile records (`ledger()` when None):
    lower_s/compile_s sums across the tag's signatures, cache_hit only
    when EVERY compile hit, max fusion/bytes/instructions (the gate
    comparands — with one signature per tag, max == the value)."""
    out = {}
    for r in (ledger() if records is None else records):
        if r.get("kind", "compile") != "compile":
            continue
        t = out.setdefault(r.get("tag", "?"), {
            "lower_s": 0.0, "compile_s": 0.0, "cache_hit": True,
            "signatures": 0, "fusion_count": 0, "bytes_accessed": 0.0,
            "instructions": 0, "peak_memory_bytes": 0.0})
        t["lower_s"] += float(r.get("lower_s", 0.0))
        t["compile_s"] += float(r.get("compile_s", 0.0))
        t["cache_hit"] = t["cache_hit"] and bool(r.get("cache_hit"))
        t["signatures"] += 1
        t["fusion_count"] = max(t["fusion_count"],
                                int(r.get("fusion_count", 0)))
        t["bytes_accessed"] = max(t["bytes_accessed"],
                                  float(r.get("bytes_accessed", 0.0)))
        t["instructions"] = max(t["instructions"],
                                int(r.get("instructions", 0)))
        t["peak_memory_bytes"] = max(t["peak_memory_bytes"],
                                     float(r.get("peak_memory_bytes",
                                                 0.0)))
    return out


# -- listeners -----------------------------------------------------------

def add_listener(fn):
    """Observe compiles live: fn(event) with event["phase"] "start"
    ({tag, signature, retrace, changes}) or "done" ({tag, record}).
    Listener exceptions are swallowed — telemetry consumers must not
    break compiles."""
    with _lock:
        if fn not in _listeners:
            _listeners.append(fn)
    return fn


def remove_listener(fn):
    with _lock:
        if fn in _listeners:
            _listeners.remove(fn)


def _notify(event):
    with _lock:
        fns = list(_listeners)
    for fn in fns:
        try:
            fn(event)
        except Exception:
            pass


def reset():
    """Drop the ledger + forensic state (tests). Listeners persist."""
    with _lock:
        _ledger.clear()
        _tag_sigs.clear()
