"""fleet role makers / util / data generators + PS-side dataset and
entry configs added for distributed namespace parity."""
import io
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu import optimizer as opt
import paddle_tpu.nn as nn

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick commit gate no


def _loss_fn():
    def f(out, y):
        return nn.functional.cross_entropy(
            out.reshape([-1, out.shape[-1]]), y.reshape([-1]))
    return f


def test_role_makers(monkeypatch):
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
    monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                       "a:1,b:2,c:3,d:4")
    rm = fleet.PaddleCloudRoleMaker(is_collective=True)
    assert rm.worker_index() == 2
    assert rm.worker_num() == 4
    assert rm.is_worker() and not rm.is_server()
    assert len(rm.get_trainer_endpoints()) == 4

    urm = fleet.UserDefinedRoleMaker(
        current_id=1, role=fleet.Role.WORKER,
        worker_endpoints=["x:1", "y:2"])
    assert urm.worker_index() == 1
    assert urm.worker_num() == 2
    assert not urm.is_first_worker()


def test_fleet_class_and_util():
    f = fleet.Fleet().init()
    assert f.is_initialized()
    assert f.is_worker() and not f.is_server()
    assert f.worker_index() == 0 and f.is_first_worker()
    files = [f"part-{i}" for i in range(5)]
    assert fleet.util.get_file_shard(files) == files  # world size 1
    assert fleet.util.all_reduce(np.array([3.0])) == 3.0
    assert fleet.util.all_gather(7) == [7]
    fleet.util.barrier()


def test_multislot_data_generator():
    class G(fleet.MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                vals = [int(x) for x in line.split()]
                yield [("words", vals[:-1]), ("label", [vals[-1]])]
            return it

    g = G()
    g.set_batch(2)
    out = io.StringIO()
    g._run_lines(["1 2 3 1", "4 5 6 0"], out)
    lines = out.getvalue().splitlines()
    assert lines == ["3 1 2 3 1 1", "3 4 5 6 1 0"]

    sg = fleet.MultiSlotStringDataGenerator()
    assert sg._gen_str([("w", ["a", "b"]), ("l", ["1"])]) == "2 a b 1 1\n"


def test_ps_datasets(tmp_path):
    data = tmp_path / "part-0"
    data.write_text("2 10 20 1 1\n2 30 40 1 0\n2 50 60 1 1\n")
    ds = dist.InMemoryDataset()
    ds.init(batch_size=2, use_var=["ids", "label"])
    ds.set_filelist([str(data)])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 3
    ds.local_shuffle()
    batches = list(ds)
    assert len(batches) == 2
    assert batches[0]["ids"].shape == (2, 2)
    ds.release_memory()
    assert ds.get_memory_data_size() == 0

    qs = dist.QueueDataset()
    qs.init(batch_size=3, use_var=["ids", "label"])
    qs.set_filelist([str(data)])
    (batch,) = list(qs)
    assert batch["label"].shape == (3, 1)
    assert batch["label"].dtype == np.int64


def test_entry_attrs():
    assert dist.ProbabilityEntry(0.5)._to_attr() == "probability_entry:0.5"
    assert dist.CountFilterEntry(10)._to_attr() == "count_filter_entry:10"
    assert dist.ShowClickEntry("show", "click")._to_attr() == \
        "show_click_entry:show:click"
    with pytest.raises(ValueError):
        dist.ProbabilityEntry(2.0)
    with pytest.raises(ValueError):
        dist.CountFilterEntry(1.5)


def test_gloo_api_and_get_group():
    dist.gloo_init_parallel_env(0, 1, "127.0.0.1:6170")
    dist.gloo_barrier()
    dist.gloo_release()
    with pytest.raises(RuntimeError):
        dist.gloo_barrier()
    g = dist.new_group(ranks=[0])
    assert dist.get_group(g.id) is g


class TestHonestStrategy:
    """Strategy flags must do what they claim or refuse loudly (VERDICT
    r2 missing #7 / next #10)."""

    def test_unimplemented_flags_raise(self):
        import pytest
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
        for flag in ("dgc", "asp"):  # out of scope on TPU, SURVEY §3
            strategy = fleet.DistributedStrategy()
            setattr(strategy, flag, True)
            fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(0)
            m = GPTForCausalLM(gpt_tiny())
            o = opt.SGD(learning_rate=0.01, parameters=m.parameters())
            with pytest.raises(NotImplementedError, match="SURVEY"):
                fleet.build_train_step(m, _loss_fn(), o)

    def test_localsgd_trains_and_syncs(self):
        """strategy.localsgd: k-1 of k steps run psum-free on per-device
        replicas; the k-th pmean-averages them back into agreement
        (ref meta_optimizers/localsgd_optimizer.py)."""
        import paddle_tpu.nn as nn
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["dp_degree"] = 8
        strategy.localsgd = True
        strategy.localsgd_configs["k_steps"] = 2
        strategy.localsgd_configs["begin_step"] = 0
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 1))
        o = opt.SGD(learning_rate=0.05, parameters=m.parameters())
        loss_fn = lambda out, tgt: paddle.mean(
            paddle.nn.functional.square_error_cost(out, tgt))
        step = fleet.build_train_step(m, loss_fn, o)
        from paddle_tpu.distributed.fleet.localsgd import LocalSGDTrainStep
        assert isinstance(step, LocalSGDTrainStep)
        rs = np.random.RandomState(0)
        X = rs.randn(32, 16).astype("float32")
        w = rs.randn(16, 1).astype("float32")
        Y = X @ w
        losses = []
        for i in range(6):
            losses.append(float(step(paddle.to_tensor(X),
                                     paddle.to_tensor(Y))))
            if i % 2 == 0:  # odd call count -> local step, replicas differ
                assert step.replica_spread() > 0.0
            else:          # even call count -> sync step, replicas agree
                assert step.replica_spread() < 1e-6
        assert losses[-1] < losses[0]
        step.sync_to_model()  # averages back into the eager Layer

    @pytest.mark.heavy
    def test_lars_swaps_optimizer(self):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
        strategy = fleet.DistributedStrategy()
        strategy.lars = True
        strategy.lars_configs["lars_coeff"] = 0.002
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = GPTForCausalLM(gpt_tiny())
        o = opt.Momentum(learning_rate=0.01, momentum=0.9,
                         parameters=m.parameters())
        step = fleet.build_train_step(m, _loss_fn(), o)
        from paddle_tpu.optimizer import LarsMomentum
        assert isinstance(step.optimizer, LarsMomentum)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 1024, size=(8, 16)))
        l0 = step(ids, ids).item()
        for _ in range(3):
            l = step(ids, ids).item()
        assert np.isfinite(l) and l < l0

    @pytest.mark.heavy
    def test_gradient_merge_flag_accumulates(self):
        """strategy.gradient_merge k_steps=2 must match explicit
        accumulate_steps=2 exactly."""
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 1024, size=(8, 16)))

        def run(**kw):
            strategy = fleet.DistributedStrategy()
            for k, v in kw.items():
                if k == "k_steps":
                    strategy.gradient_merge = True
                    strategy.gradient_merge_configs["k_steps"] = v
            fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(0)
            m = GPTForCausalLM(gpt_tiny())
            o = opt.SGD(learning_rate=0.01, parameters=m.parameters())
            step = fleet.build_train_step(
                m, _loss_fn(), o,
                accumulate_steps=kw.get("accumulate_steps"))
            return [step(ids, ids).item() for _ in range(2)]

        np.testing.assert_allclose(run(k_steps=2),
                                   run(accumulate_steps=2),
                                   rtol=1e-5, atol=1e-6)

    def test_amp_flag_casts_params(self):
        """strategy.amp must change the compute dtype, not sit inert."""
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
        import jax.numpy as jnp
        strategy = fleet.DistributedStrategy()
        strategy.amp = True
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = GPTForCausalLM(gpt_tiny())
        o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
        step = fleet.build_train_step(m, _loss_fn(), o)
        pk = "gpt.h.0.attn.qkv_proj.weight"
        assert step.params[pk].dtype == jnp.bfloat16
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 1024, size=(8, 16)))
        l0 = step(ids, ids).item()
        l1 = step(ids, ids).item()
        assert np.isfinite(l0) and np.isfinite(l1)

    def test_lamb_swaps_optimizer(self):
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
        strategy = fleet.DistributedStrategy()
        strategy.lamb = True
        fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        m = GPTForCausalLM(gpt_tiny())
        o = opt.Adam(learning_rate=1e-3, parameters=m.parameters())
        step = fleet.build_train_step(m, _loss_fn(), o)
        from paddle_tpu.optimizer import Lamb
        assert isinstance(step.optimizer, Lamb)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 1024, size=(8, 16)))
        l0 = step(ids, ids).item()
        l1 = step(ids, ids).item()
        assert np.isfinite(l0) and l1 < l0
