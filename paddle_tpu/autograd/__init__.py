"""paddle.autograd namespace.

Parity: python/paddle/autograd/__init__.py — backward/grad (tape), PyLayer
(py_layer.py), and the functional transforms (functional.py) which here are
direct jax transforms over Tensor-level functions.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op, no_grad, enable_grad, \
    is_grad_enabled, set_grad_enabled
from .backward_engine import grad, run_backward

__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext", "vjp", "jvp",
           "jacobian", "hessian"]


def backward(tensors, grad_tensors=None, retain_graph=False):
    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    for t, g in zip(tensors, grad_tensors):
        run_backward(t, g, retain_graph)


class PyLayerContext:
    """Parity: python/paddle/autograd/py_layer.py PyLayerContext —
    `saved_tensor` is a METHOD there (`y, = ctx.saved_tensor()`, py_layer.py:88),
    so it is one here; arbitrary attributes may also be stashed on ctx."""

    def __init__(self):
        self._saved = ()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayer:
    """Custom autograd op. Parity: python/paddle/autograd/py_layer.py.

    Subclass with @staticmethod forward(ctx, ...) and backward(ctx, *grads)
    operating on Tensors. Wired into the tape via jax.custom_vjp semantics:
    the recorded node's vjp calls the user's backward.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = list(out) if multi else [out]

        tensor_args = [a for a in args if isinstance(a, Tensor)]
        needs_grad = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_args)
        if not needs_grad:
            return out

        from ..framework.core import _Node
        diff_in = [t for t in tensor_args if not t.stop_gradient]
        # the user's backward returns one grad per TENSOR input of forward
        # (reference py_layer.py contract); only the requires-grad subset
        # feeds the tape, so record which positions those are
        diff_pos = tuple(i for i, t in enumerate(tensor_args)
                         if not t.stop_gradient)

        node = _PyLayerNode(cls, ctx, [t._slot for t in diff_in],
                            [o._slot for o in outs], multi, diff_pos,
                            len(tensor_args))
        for o in outs:
            o._slot.node = node
            o.stop_gradient = False
        return out


class _PyLayerNode:
    """Tape node whose vjp is the user's backward()."""
    __slots__ = ("cls", "ctx", "in_slots", "out_slots", "multi", "fn",
                 "diff_pos", "n_tensor_args")

    def __init__(self, cls, ctx, in_slots, out_slots, multi, diff_pos,
                 n_tensor_args):
        self.cls = cls
        self.ctx = ctx
        self.in_slots = in_slots
        self.out_slots = out_slots
        self.multi = multi
        self.diff_pos = diff_pos
        self.n_tensor_args = n_tensor_args
        self.fn = None  # engine checks fn only through run_vjp below

    def _select(self, grads):
        """Align the user's backward return with the requires-grad inputs.
        Reference contract (py_layer.py): one grad per TENSOR input of
        forward; grads for stop_gradient inputs are discarded. A return of
        exactly one grad per requires-grad input is also accepted. Any
        other count is an error — never silently dropped."""
        if len(grads) == self.n_tensor_args:
            return tuple(grads[i] for i in self.diff_pos)
        if len(grads) == len(self.diff_pos):
            return tuple(grads)
        raise ValueError(
            f"{self.cls.__name__}.backward returned {len(grads)} "
            f"gradient(s) but forward took {self.n_tensor_args} tensor "
            f"input(s) ({len(self.diff_pos)} requiring grad)")

    def run_vjp(self, cots):
        grads = self.cls.backward(
            self.ctx, *[Tensor(c) for c in cots]) if self.multi else \
            self.cls.backward(self.ctx, Tensor(cots[0]))
        grads = grads if isinstance(grads, (tuple, list)) else (grads,)
        return tuple(g.value if isinstance(g, Tensor) else g
                     for g in self._select(grads))

    def run_vjp_taped(self, cot_tensors):
        """create_graph path: the user's backward runs WITH the tape on, so
        the ops it performs (over saved forward tensors and the taped
        cotangents) record nodes — the returned grads are differentiable.
        Parity: reference PyLayer supports higher-order grad
        (py_layer.py:30 backward composes with the dygraph engine)."""
        grads = self.cls.backward(self.ctx, *cot_tensors) if self.multi \
            else self.cls.backward(self.ctx, cot_tensors[0])
        grads = grads if isinstance(grads, (tuple, list)) else (grads,)
        return self._select(grads)


# ---- functional transforms (jax-native) ------------------------------
def _functionalize(func):
    """Lift a Tensor->Tensor python function to a jax-array function."""
    def jf(*arrays):
        with no_grad():
            out = func(*[Tensor(a) for a in arrays])
        if isinstance(out, (tuple, list)):
            return tuple(o.value for o in out)
        return out.value
    return jf


def vjp(func, xs, v=None):
    xs_list = xs if isinstance(xs, (tuple, list)) else [xs]
    jf = _functionalize(func)
    out, vjp_fn = jax.vjp(jf, *[x.value for x in xs_list])
    if v is None:
        seed = jax.tree.map(jnp.ones_like, out)
    else:
        vl = v if isinstance(v, (tuple, list)) else [v]
        seed = tuple(t.value for t in vl) if isinstance(out, tuple) \
            else vl[0].value
    grads = vjp_fn(seed)
    wrap = lambda o: jax.tree.map(Tensor, o) if isinstance(o, tuple) \
        else Tensor(o)
    gout = [Tensor(g) for g in grads]
    return wrap(out), gout if len(gout) > 1 else gout[0]


def jvp(func, xs, v=None):
    xs_list = xs if isinstance(xs, (tuple, list)) else [xs]
    jf = _functionalize(func)
    prim = [x.value for x in xs_list]
    if v is None:
        tang = [jnp.ones_like(p) for p in prim]
    else:
        vl = v if isinstance(v, (tuple, list)) else [v]
        tang = [t.value for t in vl]
    out, jv = jax.jvp(jf, prim, tang)
    wrap = lambda o: tuple(Tensor(x) for x in o) if isinstance(o, tuple) \
        else Tensor(o)
    return wrap(out), wrap(jv)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    xs_list = xs if isinstance(xs, (tuple, list)) else [xs]
    jf = _functionalize(func)
    jac = jax.jacobian(jf, argnums=tuple(range(len(xs_list))))(
        *[x.value for x in xs_list])
    out = jax.tree.map(Tensor, jac)
    if not isinstance(xs, (tuple, list)):
        return out[0] if isinstance(out, tuple) else out
    return out


def hessian(func, xs, create_graph=False, allow_unused=False):
    xs_list = xs if isinstance(xs, (tuple, list)) else [xs]
    jf = _functionalize(func)
    hes = jax.hessian(jf, argnums=tuple(range(len(xs_list))))(
        *[x.value for x in xs_list])
    out = jax.tree.map(Tensor, hes)
    if not isinstance(xs, (tuple, list)):
        return out[0][0] if isinstance(out, tuple) else out
    return out
