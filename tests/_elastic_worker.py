"""Worker for test_elastic_drill.py: deterministic training under
ElasticController in three modes —

  baseline N   : run N steps uninterrupted, dump all losses
  crash K      : run under the controller, hard-die (os._exit) after K
                 steps — simulating host preemption mid-training
  resume N     : ElasticController.maybe_resume() from the newest async
                 checkpoint, continue to step N, dump resumed losses

The model is dropout-free so the loss trajectory is a pure function of
(params, opt state, step) — exact-replay is the assertion.
"""
import json
import os
import sys

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def build():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer as opt
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["dp_degree"] = 8
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 1))
    o = opt.AdamW(learning_rate=1e-2, parameters=m.parameters())

    def loss_fn(out, y):
        return paddle.mean(paddle.nn.functional.square_error_cost(out, y))

    step = fleet.build_train_step(m, loss_fn, o)
    rs = np.random.RandomState(0)
    X = rs.randn(32, 16).astype("float32")
    Y = (X @ rs.randn(16, 1)).astype("float32")
    return step, paddle.to_tensor(X), paddle.to_tensor(Y)


def main():
    mode, arg, ckpt_dir, out_path = (sys.argv[1], int(sys.argv[2]),
                                     sys.argv[3], sys.argv[4])
    from paddle_tpu.distributed.elastic import ElasticController

    step, X, Y = build()
    ctl = ElasticController(step, ckpt_dir, save_every_steps=2,
                            watchdog_timeout_s=3600)
    start = ctl.maybe_resume()
    losses = {}
    target = arg if mode != "crash" else 10 ** 9
    i = start
    while i < target:
        loss = float(step(X, Y))
        i = step._step_i
        ctl.on_step()
        losses[i] = loss
        if mode == "crash" and i >= arg:
            # let the async checkpoint writer drain, then die like a
            # preempted host — no cleanup, no stop()
            ctl.wait()
            os._exit(17)
    ctl.stop()
    with open(out_path, "w") as f:
        json.dump({"start": start, "losses": losses}, f)


if __name__ == "__main__":
    main()
