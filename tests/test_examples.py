"""The examples/ scripts must actually run (tiny configs, CPU pin)."""
import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.heavy]  # multi-minute: out of tier-1 and the quick gate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *extra):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "host_platform_device_count" not in f]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=8"])
    env["PYTHONPATH"] = REPO
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *extra],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=280)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


@pytest.mark.heavy
def test_train_gpt():
    out = _run("train_gpt.py", "--size", "tiny", "--steps", "4",
               "--batch", "2", "--seq", "32")
    assert "loss" in out and "tokens/s" in out


@pytest.mark.heavy
def test_train_gpt_hybrid():
    out = _run("train_gpt_hybrid.py", "--dp", "2", "--mp", "2",
               "--zero", "2", "--steps", "2", "--seq", "32")
    assert "mesh" in out and "loss" in out


@pytest.mark.heavy
def test_train_gpt_hybrid_sequence_parallel():
    out = _run("train_gpt_hybrid.py", "--dp", "2", "--sep", "4",
               "--mp", "1", "--zero", "1", "--steps", "2", "--seq", "64")
    assert "'sp'" in out or "sp" in out

@pytest.mark.heavy
def test_generate_gpt():
    out = _run("generate_gpt.py", "--tokens", "8")
    assert "warm" in out


@pytest.mark.heavy
def test_train_vision_hapi():
    out = _run("train_vision_hapi.py", "--model", "resnet18",
               "--epochs", "1", "--batch", "32")
    assert "loss" in out or "acc" in out


@pytest.mark.heavy
def test_bench_decode():
    out = _run("bench_decode.py")
    assert "decode_tok_per_s" in out


@pytest.mark.heavy
def test_bench_bert():
    out = _run("bench_bert.py")
    assert "sequences_per_sec" in out


@pytest.mark.heavy
def test_bench_gpt_1p3b():
    out = _run("bench_gpt_1p3b.py")
    assert "tokens_per_sec" in out
