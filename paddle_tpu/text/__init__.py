"""paddle.text. Parity: python/paddle/text/ — dataset classes read local
files (zero-egress); ViterbiDecoder is implemented natively."""
import os

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op
from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens", "Conll05",
           "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"]

DATA_HOME = os.path.expanduser("~/.cache/paddle/dataset")


class _LocalDataset(Dataset):
    NAME = "dataset"

    def __init__(self, data_file=None, mode="train", **kwargs):
        if data_file is None or not os.path.exists(data_file):
            raise FileNotFoundError(
                f"{self.NAME}: no network access — pass data_file= with a "
                f"local copy (expected under {DATA_HOME})")
        self.data_file = data_file
        self.mode = mode


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train", download=True):
        data_file = data_file or os.path.join(DATA_HOME, "uci_housing",
                                              "housing.data")
        if not os.path.exists(data_file):
            raise FileNotFoundError(
                f"UCIHousing data not found at {data_file} (zero egress)")
        raw = np.loadtxt(data_file)
        x, y = raw[:, :-1].astype(np.float32), raw[:, -1:].astype(
            np.float32)
        x = (x - x.mean(0)) / (x.std(0) + 1e-8)
        split = int(len(x) * 0.8)
        if mode == "train":
            self.x, self.y = x[:split], y[:split]
        else:
            self.x, self.y = x[split:], y[split:]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class Imdb(_LocalDataset):
    NAME = "imdb"


class Imikolov(_LocalDataset):
    NAME = "imikolov"


class Movielens(_LocalDataset):
    NAME = "movielens"


class Conll05(_LocalDataset):
    NAME = "conll05"


class WMT14(_LocalDataset):
    NAME = "wmt14"


class WMT16(_LocalDataset):
    NAME = "wmt16"


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decoding via lax.scan (reference:
    paddle/fluid/operators/viterbi_decode_op.h)."""
    def fn(emis, trans):
        B, T, N = emis.shape

        def step(alpha, e_t):
            scores = alpha[:, :, None] + trans[None]
            best = jnp.max(scores, axis=1) + e_t
            back = jnp.argmax(scores, axis=1)
            return best, back

        alpha0 = emis[:, 0]
        alphas, backs = jax.lax.scan(step, alpha0,
                                     jnp.moveaxis(emis[:, 1:], 1, 0))
        last_best = jnp.argmax(alphas, -1)
        score = jnp.max(alphas, -1)

        def backtrack(carry, back_t):
            idx = carry
            prev = jnp.take_along_axis(back_t, idx[:, None], 1)[:, 0]
            return prev, prev

        _, path_rev = jax.lax.scan(backtrack, last_best,
                                   jnp.flip(backs, 0))
        path = jnp.concatenate(
            [jnp.flip(path_rev, 0), last_best[None]], 0)
        return score, jnp.moveaxis(path, 0, 1).astype(jnp.int64)
    scores, path = apply_op(fn, potentials, transition_params)
    return scores, path


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


Conll05st = Conll05  # reference name (python/paddle/text/datasets/conll05.py)
__all__ += ["Conll05st"]
