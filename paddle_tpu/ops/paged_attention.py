"""Paged KV-cache attention for continuous-batching inference.

Beyond-parity (the reference era predates it; see PAPERS.md "Ragged
Paged Attention ... for TPU"): decode-time KV memory is allocated in
fixed-size PAGES shared by all sequences, so a batch of requests with
wildly different lengths wastes no HBM on padding and sequences can
join/leave the batch without reshaping anything static.

TPU-native formulation: the page pool is one [n_pages, page_size, H, D]
array per layer; a per-sequence page table [B, max_pages] turns decode
attention into ONE XLA gather (pages → [B, max_pages*page_size, H, D])
plus a masked flash-style softmax — static shapes, jit-stable across
steps, no per-token recompilation. The allocator is host-side Python
(free-list of page ids), exactly the part that should not be traced.
"""
import functools
import math

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["PagedKVCache", "paged_attention"]


@functools.partial(jax.jit, donate_argnums=(0,))
def _write_block(pool, block, page, in_page):
    """In-place page write: the pool buffer is DONATED, so XLA updates
    it without copying the whole [n_pages, page_size, H, D] array (an
    eager dynamic_update_slice would copy the pool per token). page/
    in_page are traced, so one program serves every position."""
    return jax.lax.dynamic_update_slice(
        pool, block, (page, in_page,
                      jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32)))


def paged_attention(q, k_pages, v_pages, page_table, lengths, scale=None):
    """q: [B, H, D] (one decode token per sequence);
    k_pages/v_pages: [n_pages, page_size, H, D];
    page_table: [B, max_pages] int32 page ids (0-padded);
    lengths: [B] int32 — tokens currently stored per sequence.
    Returns [B, H, D]."""
    B, H, D = q.shape
    P = k_pages.shape[1]
    max_pages = page_table.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    # one gather: each sequence's pages, flattened to a token axis
    k = k_pages[page_table].reshape(B, max_pages * P, H, D)
    v = v_pages[page_table].reshape(B, max_pages * P, H, D)
    s = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    t = jnp.arange(max_pages * P)[None, None, :]
    s = jnp.where(t < lengths[:, None, None], s, jnp.float32(-1e30))
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bht,bthd->bhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


class PagedKVCache:
    """Host-side page allocator + device-side page pools (per layer).

    write()/extend() copy new k/v into pages with one dynamic_update per
    page touched; sequences allocate pages lazily and release them on
    free() — the pool is shared, so peak HBM tracks the TOTAL tokens in
    flight, not batch * max_len."""

    def __init__(self, n_layers, n_pages, page_size, n_heads, head_dim,
                 dtype=jnp.float32):
        self.n_layers = n_layers
        self.n_pages = n_pages
        self.page_size = page_size
        self.n_heads = n_heads
        self.head_dim = head_dim
        shape = (n_pages, page_size, n_heads, head_dim)
        self.k = [jnp.zeros(shape, dtype) for _ in range(n_layers)]
        self.v = [jnp.zeros(shape, dtype) for _ in range(n_layers)]
        # page 0 is reserved as the pad page so 0-padded tables are safe
        self._free = list(range(1, n_pages))
        self._tables = {}   # seq_id -> list of page ids
        self._len = {}      # seq_id -> tokens stored

    # ---- allocator ----------------------------------------------------
    def add_sequence(self, seq_id):
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already present")
        self._tables[seq_id] = []
        self._len[seq_id] = 0

    def free_sequence(self, seq_id):
        self._free.extend(self._tables.pop(seq_id))
        self._len.pop(seq_id)

    def length(self, seq_id):
        return self._len[seq_id]

    def n_free_pages(self):
        return len(self._free)

    def pages_needed(self, n_tokens):
        """Pages a FRESH sequence of n_tokens would consume (pages are
        never shared across sequences)."""
        return -(-int(n_tokens) // self.page_size)

    def pages_held(self, seq_id):
        """Pages currently allocated to a sequence. Allocation is lazy
        (pages are drawn as tokens arrive), so a scheduler reserving
        worst cases must count each active sequence's outstanding claim
        (reservation - held), not just n_free_pages()."""
        return len(self._tables[seq_id])

    def can_allocate(self, n_tokens, reserved=0):
        """Admission control: True when a new sequence of n_tokens fits
        the free list AFTER `reserved` pages of outstanding claims.
        Allocation is lazy, so the free list alone overstates what is
        safely available: a scheduler reserving each request's worst
        case (prompt + max_new_tokens) must pass the sum of
        (reservation - pages_held) over its active sequences — with
        that term a mid-decode out-of-pages is impossible (see
        GenerationEngine._admit)."""
        return self.pages_needed(n_tokens) + int(reserved) \
            <= len(self._free)

    def _ensure_capacity(self, seq_id, n_new):
        need = self._len[seq_id] + n_new
        have = len(self._tables[seq_id]) * self.page_size
        n_pages = -(-max(need - have, 0) // self.page_size)
        if n_pages > len(self._free):
            # atomic: raise BEFORE touching the free list, so a caught
            # allocation failure leaves the pool consistent (a scheduler
            # can defer this sequence and admit a smaller one)
            raise RuntimeError(
                f"PagedKVCache out of pages (need {n_pages}, free "
                f"{len(self._free)}) — free finished sequences or grow "
                f"n_pages")
        for _ in range(n_pages):
            self._tables[seq_id].append(self._free.pop())

    # ---- writes -------------------------------------------------------
    def extend(self, seq_id, layer, k_new, v_new):
        """Append k/v [T, H, D] for one layer. Call for every layer with
        the same T before advance()."""
        self._ensure_capacity(seq_id, k_new.shape[0])
        k_new = k_new.astype(self.k[layer].dtype)
        v_new = v_new.astype(self.v[layer].dtype)
        pos = self._len[seq_id]
        T = k_new.shape[0]
        P = self.page_size
        table = self._tables[seq_id]
        off = 0
        while off < T:
            page = table[(pos + off) // P]
            in_page = (pos + off) % P
            n = min(P - in_page, T - off)
            self.k[layer] = _write_block(
                self.k[layer], k_new[off:off + n][None],
                jnp.int32(page), jnp.int32(in_page))
            self.v[layer] = _write_block(
                self.v[layer], v_new[off:off + n][None],
                jnp.int32(page), jnp.int32(in_page))
            off += n

    def advance(self, seq_id, n_tokens):
        """Commit n_tokens appended to EVERY layer."""
        self._len[seq_id] += n_tokens

    def plan_decode(self, seq_ids, pad_to=None):
        """Host-side plan for ONE fully-jitted decode step: allocate
        capacity for one new token per sequence and return
        (pages [B], in_pages [B], page_table [B, width], lengths [B])
        — the write coordinates and read views the jitted step needs.
        Lengths are the PRE-write token counts; call advance(sid, 1)
        after the step commits.

        pad_to > B pads the plan with rows that scatter into the
        reserved pad page 0 (in_page 0, empty table, length 0): a
        continuous-batching scheduler keeps the decode step's compiled
        shape FIXED while sequences join and leave the batch — pad-row
        outputs are garbage by construction and must be sliced off."""
        if len(set(seq_ids)) != len(seq_ids):
            # duplicates would scatter two rows to the same (page,
            # in_page) — one silently lost — then advance twice
            raise ValueError(f"duplicate seq_ids in decode batch: "
                             f"{seq_ids!r}")
        for s in seq_ids:
            self._ensure_capacity(s, 1)
        P = self.page_size
        B = len(seq_ids)
        n_pad = 0
        if pad_to is not None:
            if pad_to < B:
                raise ValueError(f"pad_to={pad_to} < batch size {B}")
            n_pad = int(pad_to) - B
        pages = np.asarray(
            [self._tables[s][self._len[s] // P] for s in seq_ids]
            + [0] * n_pad, np.int32)
        in_pages = np.asarray([self._len[s] % P for s in seq_ids]
                              + [0] * n_pad, np.int32)
        pt, lens = self.batch_views(seq_ids)
        if n_pad:
            pt = jnp.concatenate(
                [pt, jnp.zeros((n_pad, pt.shape[1]), jnp.int32)])
            lens = jnp.concatenate([lens, jnp.zeros((n_pad,), jnp.int32)])
        return jnp.asarray(pages), jnp.asarray(in_pages), pt, lens

    # ---- reads --------------------------------------------------------
    def batch_views(self, seq_ids):
        """(page_table [B, width] i32, lengths [B] i32) for a decode
        batch — tables pad with the reserved page 0 and width rounds up
        to the next power of two, so the jitted attention compiles once
        per bucket instead of every time the longest sequence crosses a
        page boundary. Build ONCE per decode step and pass to attend()
        for every layer (the views are layer-independent)."""
        if not seq_ids:
            raise ValueError("batch_views() needs at least one sequence")
        tables = [self._tables[s] for s in seq_ids]
        width = max(1, max(len(t) for t in tables))
        width = 1 << (width - 1).bit_length()  # bucket: power of two
        pt = np.zeros((len(seq_ids), width), np.int32)
        for i, t in enumerate(tables):
            pt[i, :len(t)] = t
        lens = np.asarray([self._len[s] for s in seq_ids], np.int32)
        return jnp.asarray(pt), jnp.asarray(lens)

    def attend(self, layer, q, seq_ids=None, views=None):
        """Decode attention for one layer: q [B, H, D] against each
        sequence's paged history. Pass `views=batch_views(seq_ids)`
        (computed once per step) to avoid rebuilding the host-side
        tables + H2D transfer per layer."""
        if views is None:
            views = self.batch_views(seq_ids)
        pt, lens = views
        return paged_attention(q, self.k[layer], self.v[layer], pt, lens)
