"""paddle.static — static graph API.

Parity: python/paddle/static/ (Program/Executor/program_guard/data/
save_inference_model). TPU-native design: a Program records python
calls building symbolic Tensors (tracer placeholders); Executor.run
traces+jits the recorded computation against the feed shapes — the
"ProgramDesc" is a jaxpr and the "InterpreterCore" is the XLA executable
cache, so static-graph user code from the reference runs unchanged with
compiled-once performance.
"""
import collections

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, no_grad
from ..framework.dtype import convert_dtype
from ..jit.save_load import InputSpec

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "scope_guard",
           "global_scope", "name_scope", "save_inference_model",
           "load_inference_model", "InputSpec", "gradients",
           "append_backward", "cpu_places", "cuda_places", "xpu_places",
           "npu_places", "mlu_places", "device_guard", "py_func", "nn",
           "BuildStrategy", "CompiledProgram", "ExecutionStrategy",
           "ParallelExecutor", "ipu_shard_guard", "IpuCompiledProgram",
           "IpuStrategy", "Print", "WeightNormParamAttr",
           "ExponentialMovingAverage", "save", "load", "serialize_program",
           "serialize_persistables", "save_to_file", "deserialize_program",
           "deserialize_persistables", "load_from_file",
           "normalize_program", "load_program_state", "set_program_state",
           "create_global_var", "create_parameter", "accuracy", "auc",
           "Variable"]


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from .nn import _make_param
    return _make_param(list(shape), dtype, attr, default_initializer)


class Variable(Tensor):
    """Symbolic placeholder living in a Program."""

    def __init__(self, name, shape, dtype):
        shape_c = tuple(1 if (s is None or s == -1) else int(s)
                        for s in shape)
        super().__init__(jnp.zeros(shape_c, convert_dtype(dtype)),
                         stop_gradient=False, name=name)
        self.spec_shape = tuple(shape)
        self.is_placeholder = True


class Program:
    def __init__(self):
        self.placeholders = collections.OrderedDict()
        self.outputs = []
        self._build_fns = []  # (fn, placeholders_order) recorded builders
        self.random_seed = 0
        self._builder = None
        self._params = []  # params created by static.nn under this program
        self._train_hooks = []  # (loss, optimizer, [(param, build_slot)])
        # registered by optimizer.minimize; Executor.run steps them

    def global_block(self):
        return self

    def list_vars(self):
        """All variables in the program: feed placeholders plus the
        parameters created by static.nn layers (reference
        framework/io.py doc example iterates these to pick a weight)."""
        return list(self.placeholders.values()) + list(self._params)

    def state_dict(self, mode="all", scope=None):
        """Name → value for the program's parameters (reference
        static Program.state_dict; mode 'param'/'opt'/'all' — optimizer
        state lives in the Optimizer here, so 'opt' returns empty)."""
        if mode == "opt":
            return {}
        return {p.name: p for p in self._params}

    def set_state_dict(self, state_dict, scope=None):
        by_name = {p.name: p for p in self._params}
        for k, v in state_dict.items():
            if k in by_name:
                by_name[k].set_value(
                    v.value if hasattr(v, "value") else v)

    def clone(self, for_test=False):
        import copy
        return self

    def set_builder(self, fn):
        self._builder = fn

    # -- pickling (paddle.save(program, path)) -------------------------
    # The reference serializes a ProgramDesc proto; our Program is a
    # recorded trace whose build closures can't pickle. What round-trips
    # is the program's DATA: feed specs + parameter values. Builders and
    # train hooks are rebuilt by re-running the user's construction code.
    def __getstate__(self):
        import numpy as _np
        return {
            "placeholders": [(v.name, v.spec_shape,
                              str(_np.dtype(v.dtype)))
                             for v in self.placeholders.values()],
            "params": [(p.name, _np.asarray(p.value))
                       for p in self._params],
            "random_seed": self.random_seed,
        }

    def __setstate__(self, st):
        from ..framework.core import Parameter
        self.__init__()
        self.random_seed = st.get("random_seed", 0)
        for name, spec_shape, dt in st.get("placeholders", []):
            self.placeholders[name] = Variable(name, spec_shape, dt)
        for name, arr in st.get("params", []):
            self._params.append(Parameter(arr, name=name))


_program_stack = [Program()]
_startup = Program()


def default_main_program():
    return _program_stack[-1]


def default_startup_program():
    return _startup


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program

    def __enter__(self):
        _program_stack.append(self.main)
        return self.main

    def __exit__(self, *exc):
        _program_stack.pop()
        return False


def data(name, shape, dtype="float32", lod_level=0):
    prog = default_main_program()
    var = Variable(name, shape, dtype)
    prog.placeholders[name] = var
    return var


class _Scope(dict):
    def var(self, name):
        return self.setdefault(name, None)

    def find_var(self, name):
        return self.get(name)


_scope = _Scope()
Scope = _Scope  # paddle.static.Scope parity


def global_scope():
    return _scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class Executor:
    """Recorded-trace executor. run() rebinds feeds into the placeholder
    slots, replays the recorded op tape forward to the fetches (each op
    is an XLA-compiled jnp call; the per-op python dispatch is the cost
    of eager-static parity — the performant path is jit/TrainStep), and
    executes one optimizer step per call for every minimize()-declared
    objective."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    @staticmethod
    def _replay(fetch_tensors):
        """Recompute fetch values forward through the recorded tape —
        producers before consumers — so fresh feed values flow to the
        fetches (the recorded-trace analogue of the reference executor
        re-running the Program's ops)."""
        from ..autograd.backward_engine import _topo_nodes
        nodes = _topo_nodes([t._slot for t in fetch_tensors
                             if isinstance(t, Tensor)])
        for node in nodes:
            if node.fn is None:
                continue
            vals = node.fn(*[s.val for s in node.in_slots])
            outs = vals if isinstance(vals, (tuple, list)) else [vals]
            for s, v in zip(node.out_slots, outs):
                s.val = v

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or program.outputs
        if not isinstance(fetch_list, (list, tuple)):
            fetch_list = [fetch_list]

        # bind feeds by MUTATING the placeholder's slot value in place:
        # recorded tape nodes reference these slot objects, so the replay
        # below sees fresh values (a rebind would orphan them)
        for name, value in feed.items():
            ph = program.placeholders.get(name)
            if ph is None:
                continue
            arr = value.value if isinstance(value, Tensor) else \
                jnp.asarray(np.asarray(value))
            ph._slot.val = arr
        # resolve fetch entries: Tensor | placeholder name | unnamed
        # (None, e.g. `fetch_list=loss.name` on an auto-created var).
        # Unnamed entries map positionally onto the program's declared
        # outputs (populated by optimizer.minimize/save_inference_model);
        # anything unresolvable raises — silent garbage corrupts runs.
        resolved = []
        unnamed_i = 0
        for e in fetch_list:
            if isinstance(e, Tensor):
                resolved.append(e)
            elif isinstance(e, str) and e in program.placeholders:
                resolved.append(program.placeholders[e])
            elif isinstance(e, str) and Tensor._name_registry is not None \
                    and e in Tensor._name_registry:
                resolved.append(Tensor._name_registry[e])
            elif e is None and unnamed_i < len(program.outputs):
                resolved.append(program.outputs[unnamed_i])
                unnamed_i += 1
            else:
                raise ValueError(
                    f"Executor.run cannot resolve fetch entry {e!r}: pass "
                    "the Tensor itself, a placeholder name, or declare "
                    "outputs via optimizer.minimize")
        if program._builder is not None:
            outs = program._builder(
                **{k: program.placeholders[k] for k in program.placeholders})
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            results = outs
        else:
            if feed:
                # replay fetches AND the registered train losses forward
                replay_roots = list(resolved) + [h[0] for h in
                                                 program._train_hooks]
                self._replay(replay_roots)
            # one optimizer step per run() over each minimize()-declared
            # objective (reference executor semantics), then sync the
            # updated params back into the recorded tape's slots so the
            # NEXT replay computes with the new weights
            if feed and program._train_hooks:
                for loss_t, opt, slots in program._train_hooks:
                    loss_t.backward(retain_graph=True)
                    opt.step()
                    for p, build_slot in slots:
                        build_slot.val = p.value
                        p.clear_grad()
            results = resolved
        out_vals = []
        for r in results:
            v = r.numpy() if isinstance(r, Tensor) else np.asarray(r)
            out_vals.append(v if return_numpy else Tensor(v))
        return out_vals

    def close(self):
        pass


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd import grad as agrad
    return agrad(targets, inputs, grad_outputs=target_gradients,
                 retain_graph=True, allow_unused=True)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Reference semantics (static/backward.py): with no parameter_list,
    return (param, grad) for every trainable parameter the current
    program's static.nn layers created."""
    loss.backward(retain_graph=True)
    if parameter_list is None:
        parameter_list = [p for p in default_main_program()._params
                          if getattr(p, "trainable", True)]
    no_grad = set(no_grad_set or ())
    return [(p, p.grad) for p in parameter_list
            if p.grad is not None and getattr(p, "name", None)
            not in no_grad]


def cpu_places(device_count=None):
    return ["cpu"]


def cuda_places(device_ids=None):
    return ["tpu"]


def xpu_places(device_ids=None):
    return ["tpu"]


class device_guard:
    def __init__(self, device=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    ins = x if isinstance(x, (list, tuple)) else [x]
    res = func(*ins)
    if isinstance(out, (list, tuple)):
        for o, r in zip(out, res if isinstance(res, (list, tuple)) else [res]):
            o._bind(r._slot)
        return out
    out._bind(res._slot)
    return out


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kwargs):
    """Serialize via the jit/StableHLO path (jit/save_load.py)."""
    from ..jit import save as jit_save
    from ..nn.layer.layers import Layer

    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    program = program or default_main_program()
    builder = program._builder
    if builder is None:
        raise RuntimeError(
            "save_inference_model requires Program.set_builder(fn) "
            "(the traced graph builder) in the TPU backend")

    class _ProgLayer(Layer):
        def forward(self, *xs):
            outs = builder(**{v.name: x for v, x in zip(feed_vars, xs)})
            return outs
    specs = [InputSpec(v.spec_shape, str(np.dtype(v.dtype)), v.name)
             for v in feed_vars]
    jit_save(_ProgLayer(), path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor, **kwargs):
    from ..jit import load as jit_load
    tl = jit_load(path_prefix)
    return [tl, [], []]


from . import nn  # noqa: E402  (paddle.static.nn submodule)


# -------------------------------------------------- strategy/executor shims
# BuildStrategy / ExecutionStrategy / ParallelExecutor / CompiledProgram
# configure graph passes and multi-stream scheduling in the reference
# (python/paddle/static/__init__.py, fluid/compiler.py). Under XLA the
# compiler owns fusion/scheduling, so these are accepted-and-recorded
# configuration objects that feed the same Executor path.

class BuildStrategy:
    def __init__(self):
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_optimizer_ops = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_broadcast_ops = False
        self.enable_auto_fusion = False
        self.build_cinn_pass = False
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0
        self.debug_graphviz_path = ""


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class CompiledProgram:
    """XLA compiles the traced program; with_data_parallel is recorded so
    Executor.run can shard the batch over devices if requested."""

    def __init__(self, program_or_graph, build_strategy=None):
        self.program = program_or_graph
        self.build_strategy = build_strategy or BuildStrategy()
        self._data_parallel = False

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._data_parallel = True
        if build_strategy is not None:
            self.build_strategy = build_strategy
        return self

    # Executor.run duck-types on .placeholders/._builder via .program
    @property
    def placeholders(self):
        return self.program.placeholders

    @property
    def _builder(self):
        return self.program._builder

    @property
    def outputs(self):
        return self.program.outputs


class ParallelExecutor:
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self._exe = Executor()
        self._program = main_program or default_main_program()

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


class ipu_shard_guard:
    def __init__(self, index=-1, stage=-1):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class IpuStrategy:
    def __init__(self):
        raise RuntimeError(
            "IPU backend is not available in paddle_tpu (TPU-only build); "
            "use the default TPU place")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise RuntimeError(
            "IPU backend is not available in paddle_tpu (TPU-only build); "
            "use the default TPU place")


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase='both'):
    import jax.debug
    prefix = (message or input.name or "var")
    jax.debug.print(prefix + ": {x}", x=input.value)
    return input


class WeightNormParamAttr:
    """ParamAttr that applies weight normalization (dim-wise reparam).
    Parity: python/paddle/static/__init__.py WeightNormParamAttr."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


class ExponentialMovingAverage:
    """EMA of trainable parameters with bias-corrected apply/restore.
    Parity: fluid/optimizer.py ExponentialMovingAverage."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = decay
        self._ema = {}
        self._backup = {}
        self._step = 0
        self._params = []

    def _track(self, params):
        self._params = list(params)
        for p in self._params:
            if id(p) not in self._ema:
                # zero-init so the 1/(1-decay^t) bias correction in
                # apply() is exact (Adam-style debiasing)
                self._ema[id(p)] = jnp.zeros_like(p.value)

    def update(self, params=None):
        if params is not None or not self._params:
            self._track(params or [])
        self._step += 1
        d = self.decay
        for p in self._params:
            self._ema[id(p)] = d * self._ema[id(p)] + (1 - d) * p.value

    def apply(self, executor=None, need_restore=True):
        ema_self = self

        class _Guard:
            def __enter__(gs):
                for p in ema_self._params:
                    ema_self._backup[id(p)] = p.value
                    corr = 1.0 - ema_self.decay ** max(1, ema_self._step)
                    p._bind(Tensor(ema_self._ema[id(p)] / corr)._slot)
                return gs

            def __exit__(gs, *exc):
                if need_restore:
                    ema_self.restore()
                return False
        return _Guard()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._bind(Tensor(self._backup[id(p)])._slot)
        self._backup.clear()


# ------------------------------------------------------- program save/load

def serialize_program(feed_vars, fetch_vars, program=None):
    import pickle
    program = program or default_main_program()
    meta = {"feed": [getattr(v, 'name', str(i))
                     for i, v in enumerate(feed_vars or [])],
            "fetch": [getattr(v, 'name', str(i))
                      for i, v in enumerate(fetch_vars or [])]}
    return pickle.dumps(meta)


def serialize_persistables(feed_vars, fetch_vars, program=None):
    import pickle
    return pickle.dumps({
        k: np.asarray(v.numpy() if isinstance(v, Tensor) else v)
        if v is not None else None
        for k, v in global_scope().items()})


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    import pickle
    meta = pickle.loads(data)
    prog = Program()
    prog._meta = meta
    return prog


def deserialize_persistables(program, data, executor=None):
    import pickle
    state = pickle.loads(data)
    global_scope().update(state)
    return state


def normalize_program(program, feed_vars, fetch_vars):
    program.outputs = list(fetch_vars) if isinstance(
        fetch_vars, (list, tuple)) else [fetch_vars]
    return program


def save(program, model_path, protocol=4, **configs):
    """Save program state (parameters) as <model_path>.pdparams +
    program meta as .pdmodel. Parity: python/paddle/static/io.py save."""
    import pickle
    state = dict(getattr(program, "state", None) or global_scope())
    arrs = {k: np.asarray(v.numpy() if isinstance(v, Tensor) else v)
            for k, v in state.items() if v is not None}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(arrs, f, protocol=protocol)
    with open(model_path + ".pdmodel", "wb") as f:
        f.write(serialize_program([], program.outputs, program))


def load(program, model_path, executor=None, var_list=None):
    import pickle
    with open(model_path + ".pdparams", "rb") as f:
        arrs = pickle.load(f)
    global_scope().update(arrs)
    return arrs


def load_program_state(model_path, var_list=None):
    import pickle
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    global_scope().update(state_dict)


def npu_places(device_ids=None):
    return ["tpu"]


def mlu_places(device_ids=None):
    return ["tpu"]


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    var = Tensor(jnp.full(tuple(int(s) for s in shape), value,
                          convert_dtype(dtype)), name=name)
    global_scope()[name or f"gvar_{len(global_scope())}"] = var
    return var


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve='ROC', num_thresholds=4095, topk=1,
        slide_steps=1):
    from ..metric import Auc
    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(input.numpy() if isinstance(input, Tensor) else input,
             label.numpy() if isinstance(label, Tensor) else label)
    v = m.accumulate()
    return Tensor(jnp.asarray(v, jnp.float32)), None, None
