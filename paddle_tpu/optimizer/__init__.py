"""paddle.optimizer namespace. Parity: python/paddle/optimizer/__init__.py."""
from . import lr
from .optimizer import (Optimizer, SGD, Momentum, LarsMomentum, Adam,
                        AdamW, Adamax, Adagrad, Adadelta, RMSProp, Lamb)
