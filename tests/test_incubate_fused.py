"""incubate.nn fused transformer layers. Parity:
python/paddle/incubate/nn/layer/fused_transformer.py — same layer
semantics (attention/FFN with residual + layer norm folded in), fused on
TPU via flash attention + Pallas layer norm.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate as incubate
FusedMultiHeadAttention = incubate.nn.FusedMultiHeadAttention
FusedFeedForward = incubate.nn.FusedFeedForward


def _mha(**kw):
    paddle.seed(0)
    m = incubate.nn.FusedMultiHeadAttention(
        64, 4, dropout_rate=0.0, attn_dropout_rate=0.0, **kw)
    m.eval()
    return m


class TestFusedMultiHeadAttention:
    def test_post_ln_output_is_normalized(self):
        m = _mha()
        out = m(paddle.randn([2, 8, 64])).numpy()
        assert out.shape == (2, 8, 64)
        assert abs(out.mean()) < 0.1 and abs(out.std() - 1.0) < 0.2

    def test_pre_ln_keeps_residual_scale(self):
        m = _mha(normalize_before=True)
        x = paddle.randn([2, 8, 64])
        out = m(x)
        assert out.shape == x.shape
        # pre-norm: out = x + attn(ln(x)) — correlated with input
        a, b = out.numpy().ravel(), x.numpy().ravel()
        assert np.corrcoef(a, b)[0, 1] > 0.5

    def test_matches_unfused_composition(self):
        m = _mha(normalize_before=True)
        x = paddle.randn([2, 8, 64])
        from paddle_tpu.nn import functional as F
        h = m.ln(x)
        B, T, E = h.shape
        qkv = m.qkv_proj(h).reshape([B, T, 3, 4, 16])
        q, k, v = qkv.unbind(axis=2)
        ref = x + m.out_proj(
            F.scaled_dot_product_attention(q, k, v).reshape([B, T, E]))
        np.testing.assert_allclose(m(x).numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)


class TestFusedFeedForward:
    @pytest.mark.heavy
    def test_forward_and_grad(self):
        paddle.seed(1)
        ff = incubate.nn.FusedFeedForward(32, 64, dropout_rate=0.0,
                                          activation="gelu")
        x = paddle.randn([4, 6, 32])
        out = ff(x)
        assert out.shape == x.shape
        out.sum().backward()
        assert ff.linear1.weight.grad is not None

    def test_matches_unfused_composition(self):
        paddle.seed(2)
        from paddle_tpu.nn import functional as F
        ff = incubate.nn.FusedFeedForward(32, 64, dropout_rate=0.0,
                                          activation="relu")
        ff.eval()
        x = paddle.randn([2, 4, 32])
        ref = ff.ln(x + ff.linear2(F.relu(ff.linear1(x))))
        np.testing.assert_allclose(ff(x).numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)


class TestReferenceStateDictLayout:
    """Reference fused-op checkpoints (qkv_weight [3,H,hd,E], ...) must
    load into the sublayer-structured fused layers (ADVICE r1 layout
    divergence; ref incubate/nn/layer/fused_transformer.py)."""

    def test_fused_mha_loads_reference_layout(self):
        paddle.seed(0)
        E, H = 8, 2
        m = FusedMultiHeadAttention(E, H, dropout_rate=0.0,
                                    attn_dropout_rate=0.0)
        rng = np.random.RandomState(0)
        qkv_w = rng.randn(3, H, E // H, E).astype(np.float32)
        ref_sd = {
            "qkv_weight": qkv_w,
            "qkv_bias": rng.randn(3, H, E // H).astype(np.float32),
            "linear_weight": rng.randn(E, E).astype(np.float32),
            "linear_bias": rng.randn(E).astype(np.float32),
            "ln_scale": np.ones(E, np.float32),
            "ln_bias": np.zeros(E, np.float32),
        }
        missing, unexpected = m.set_state_dict(ref_sd)
        assert not missing and not unexpected, (missing, unexpected)
        # qkv_proj.weight is [E, 3E] (in,out); entry (i,h,d) of the ref
        # tensor must land at out column i*E + h*hd + d
        got = m.qkv_proj.weight.numpy()
        np.testing.assert_allclose(got[:, 0], qkv_w[0, 0, 0, :])
        np.testing.assert_allclose(got[:, E + 1], qkv_w[1, 0, 1, :])
        # forward runs with the loaded weights
        x = paddle.to_tensor(rng.randn(2, 4, E).astype(np.float32))
        m.eval()
        out = m(x)
        assert out.shape == [2, 4, E]
        assert np.isfinite(out.numpy()).all()

    def test_fused_ffn_loads_reference_layout(self):
        paddle.seed(0)
        m = FusedFeedForward(8, 16, dropout_rate=0.0)
        rng = np.random.RandomState(0)
        ref_sd = {
            "linear1_weight": rng.randn(8, 16).astype(np.float32),
            "linear1_bias": rng.randn(16).astype(np.float32),
            "linear2_weight": rng.randn(16, 8).astype(np.float32),
            "linear2_bias": rng.randn(8).astype(np.float32),
            "ln2_scale": np.ones(8, np.float32),
            "ln2_bias": np.zeros(8, np.float32),
        }
        missing, unexpected = m.set_state_dict(ref_sd)
        assert not missing and not unexpected, (missing, unexpected)
        np.testing.assert_allclose(m.linear1.weight.numpy(),
                                   ref_sd["linear1_weight"])
