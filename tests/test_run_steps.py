"""TrainStep.run_steps: n steps in one dispatch == n per-step calls."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.jit import TrainStep


def _mk(seed=0):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
    o = opt.AdamW(learning_rate=1e-2, parameters=m.parameters())
    loss_fn = lambda out, y: nn.functional.mse_loss(out, y)
    return m, TrainStep(m, loss_fn, o)


def test_run_steps_matches_per_step_calls():
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randn(4, 4).astype(np.float32))

    _, step_a = _mk()
    ref = [float(step_a(x, y).item()) for _ in range(4)]

    _, step_b = _mk()
    losses = step_b.run_steps(4, x, y)
    assert losses.shape == [4]
    got = [float(v) for v in np.asarray(losses.value)]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    # params advanced identically
    np.testing.assert_allclose(
        np.asarray(step_a.params["0.weight"]),
        np.asarray(step_b.params["0.weight"]), rtol=1e-5, atol=1e-6)


def test_run_steps_data_per_step():
    rng = np.random.RandomState(1)
    xs = paddle.to_tensor(rng.randn(3, 4, 8).astype(np.float32))
    ys = paddle.to_tensor(rng.randn(3, 4, 4).astype(np.float32))

    _, step_a = _mk(1)
    ref = [float(step_a(xs[i], ys[i]).item()) for i in range(3)]

    _, step_b = _mk(1)
    losses = step_b.run_steps(3, xs, ys, data_per_step=True)
    got = [float(v) for v in np.asarray(losses.value)]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_run_steps_then_call_interleave():
    rng = np.random.RandomState(2)
    x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randn(4, 4).astype(np.float32))
    _, st = _mk(2)
    l0 = st.run_steps(2, x, y)
    l1 = st(x, y)  # per-step path still works after a scanned segment
    assert float(l1.item()) < float(l0.value[0])
