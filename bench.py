"""Headline benchmark: tokens/sec/chip on a GPT train step (bf16).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline ratchets against BENCH_BASE.json (first run records the base;
BASELINE.json carries no published numbers to compare against directly).
"""
import json
import os
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer as opt
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        batch, seq = 8, 1024
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                        num_heads=16, max_position_embeddings=seq,
                        dropout=0.0)
    else:  # smoke-size on CPU so the script always runs
        batch, seq = 2, 128
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=seq,
                        dropout=0.0)

    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.bfloat16() if on_tpu else None

    o = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())

    def loss_fn(logits, labels):
        V = logits.shape[-1]
        return nn.functional.cross_entropy(
            logits.reshape([-1, V]), labels.reshape([-1]))

    step = TrainStep(model, loss_fn, o)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32))

    # warmup (compile)
    for _ in range(3):
        loss = step(ids, ids)
    float(loss.item())

    iters = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, ids)
    loss.value.block_until_ready()
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_BASE.json")
    vs = 1.0
    if on_tpu:
        if os.path.exists(base_path):
            with open(base_path) as f:
                base = json.load(f).get("tokens_per_sec", tokens_per_sec)
            vs = tokens_per_sec / base
        else:
            with open(base_path, "w") as f:
                json.dump({"tokens_per_sec": tokens_per_sec}, f)
    print(json.dumps({
        "metric": "gpt_medium_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
