"""incubate.nn fused transformer layers. Parity:
python/paddle/incubate/nn/layer/fused_transformer.py — same layer
semantics (attention/FFN with residual + layer norm folded in), fused on
TPU via flash attention + Pallas layer norm.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.incubate as incubate

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick commit gate no
FusedMultiHeadAttention = incubate.nn.FusedMultiHeadAttention
FusedFeedForward = incubate.nn.FusedFeedForward


def _mha(**kw):
    paddle.seed(0)
    m = incubate.nn.FusedMultiHeadAttention(
        64, 4, dropout_rate=0.0, attn_dropout_rate=0.0, **kw)
    m.eval()
    return m


class TestFusedMultiHeadAttention:
    def test_post_ln_output_is_normalized(self):
        m = _mha()
        out = m(paddle.randn([2, 8, 64])).numpy()
        assert out.shape == (2, 8, 64)
        assert abs(out.mean()) < 0.1 and abs(out.std() - 1.0) < 0.2

    def test_pre_ln_keeps_residual_scale(self):
        m = _mha(normalize_before=True)
        x = paddle.randn([2, 8, 64])
        out = m(x)
        assert out.shape == x.shape
        # pre-norm: out = x + attn(ln(x)) — correlated with input
        a, b = out.numpy().ravel(), x.numpy().ravel()
        assert np.corrcoef(a, b)[0, 1] > 0.5

    def test_matches_unfused_composition(self):
        m = _mha(normalize_before=True)
        x = paddle.randn([2, 8, 64])
        from paddle_tpu.nn import functional as F
        h = m.ln(x)
        B, T, E = h.shape
        qkv = m.qkv_proj(h).reshape([B, T, 3, 4, 16])
        q, k, v = qkv.unbind(axis=2)
        ref = x + m.out_proj(
            F.scaled_dot_product_attention(q, k, v).reshape([B, T, E]))
        np.testing.assert_allclose(m(x).numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)


class TestFusedFeedForward:
    @pytest.mark.heavy
    def test_forward_and_grad(self):
        paddle.seed(1)
        ff = incubate.nn.FusedFeedForward(32, 64, dropout_rate=0.0,
                                          activation="gelu")
        x = paddle.randn([4, 6, 32])
        out = ff(x)
        assert out.shape == x.shape
        out.sum().backward()
        assert ff.linear1.weight.grad is not None

    def test_matches_unfused_composition(self):
        paddle.seed(2)
        from paddle_tpu.nn import functional as F
        ff = incubate.nn.FusedFeedForward(32, 64, dropout_rate=0.0,
                                          activation="relu")
        ff.eval()
        x = paddle.randn([2, 4, 32])
        ref = ff.ln(x + ff.linear2(F.relu(ff.linear1(x))))
        np.testing.assert_allclose(ff(x).numpy(), ref.numpy(),
                                   rtol=1e-5, atol=1e-5)


class TestReferenceStateDictLayout:
    """Reference fused-op checkpoints (qkv_weight [3,H,hd,E], ...) must
    load into the sublayer-structured fused layers (ADVICE r1 layout
    divergence; ref incubate/nn/layer/fused_transformer.py)."""

    def test_fused_mha_loads_reference_layout(self):
        paddle.seed(0)
        E, H = 8, 2
        m = FusedMultiHeadAttention(E, H, dropout_rate=0.0,
                                    attn_dropout_rate=0.0)
        rng = np.random.RandomState(0)
        qkv_w = rng.randn(3, H, E // H, E).astype(np.float32)
        ref_sd = {
            "qkv_weight": qkv_w,
            "qkv_bias": rng.randn(3, H, E // H).astype(np.float32),
            "linear_weight": rng.randn(E, E).astype(np.float32),
            "linear_bias": rng.randn(E).astype(np.float32),
            "ln_scale": np.ones(E, np.float32),
            "ln_bias": np.zeros(E, np.float32),
        }
        missing, unexpected = m.set_state_dict(ref_sd)
        assert not missing and not unexpected, (missing, unexpected)
        # qkv_proj.weight is [E, 3E] (in,out); entry (i,h,d) of the ref
        # tensor must land at out column i*E + h*hd + d
        got = m.qkv_proj.weight.numpy()
        np.testing.assert_allclose(got[:, 0], qkv_w[0, 0, 0, :])
        np.testing.assert_allclose(got[:, E + 1], qkv_w[1, 0, 1, :])
        # forward runs with the loaded weights
        x = paddle.to_tensor(rng.randn(2, 4, E).astype(np.float32))
        m.eval()
        out = m(x)
        assert out.shape == [2, 4, E]
        assert np.isfinite(out.numpy()).all()

    def test_fused_ffn_loads_reference_layout(self):
        paddle.seed(0)
        m = FusedFeedForward(8, 16, dropout_rate=0.0)
        rng = np.random.RandomState(0)
        ref_sd = {
            "linear1_weight": rng.randn(8, 16).astype(np.float32),
            "linear1_bias": rng.randn(16).astype(np.float32),
            "linear2_weight": rng.randn(16, 8).astype(np.float32),
            "linear2_bias": rng.randn(8).astype(np.float32),
            "ln2_scale": np.ones(8, np.float32),
            "ln2_bias": np.zeros(8, np.float32),
        }
        missing, unexpected = m.set_state_dict(ref_sd)
        assert not missing and not unexpected, (missing, unexpected)
        np.testing.assert_allclose(m.linear1.weight.numpy(),
                                   ref_sd["linear1_weight"])


class TestFusedMHAFunctional:
    """incubate.nn.functional-style fused_multi_head_attention — parity
    with a hand composition (ref fused_transformer.py:215 pseudo code)."""

    def _manual(self, x, qkvw, lw, qb, lb, pre):
        import jax.numpy as jnp
        xv = x.numpy().astype(np.float32)
        B, S, E = xv.shape
        K, N, D, _ = qkvw.shape
        h = xv
        if pre:
            mu = h.mean(-1, keepdims=True)
            var = h.var(-1, keepdims=True)
            h = (h - mu) / np.sqrt(var + 1e-5)
        qkv = np.einsum("bse,knde->kbnsd", h, qkvw) + qb[:, None, :, None, :]
        q, k, v = qkv[0], qkv[1], qkv[2]
        s = np.einsum("bnsd,bntd->bnst", q, k) / np.sqrt(D)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        o = np.einsum("bnst,bntd->bnsd", p, v)
        o = o.transpose(0, 2, 1, 3).reshape(B, S, N * D) @ lw + lb
        res = xv + o
        if not pre:
            mu = res.mean(-1, keepdims=True)
            var = res.var(-1, keepdims=True)
            res = (res - mu) / np.sqrt(var + 1e-5)
        return res

    @pytest.mark.parametrize("pre", [True, False])
    def test_matches_manual(self, pre):
        from paddle_tpu.incubate.nn import fused_multi_head_attention
        rs = np.random.RandomState(0)
        B, S, E, N = 2, 8, 16, 4
        D = E // N
        x = paddle.to_tensor(rs.randn(B, S, E).astype("float32"),
                             stop_gradient=False)
        qkvw = rs.randn(3, N, D, E).astype("float32") * 0.1
        lw = rs.randn(E, E).astype("float32") * 0.1
        qb = rs.randn(3, N, D).astype("float32") * 0.1
        lb = rs.randn(E).astype("float32") * 0.1
        out = fused_multi_head_attention(
            x, paddle.to_tensor(qkvw), paddle.to_tensor(lw),
            pre_layer_norm=pre, qkv_bias=paddle.to_tensor(qb),
            linear_bias=paddle.to_tensor(lb), dropout_rate=0.0,
            attn_dropout_rate=0.0)
        want = self._manual(x, qkvw, lw, qb, lb, pre)
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-4,
                                   atol=1e-5)
        # and it is taped: grads reach the input
        out.sum().backward()
        assert x.grad is not None and x.grad.shape == [B, S, E]

    def test_bool_mask(self):
        from paddle_tpu.incubate.nn import fused_multi_head_attention
        rs = np.random.RandomState(1)
        x = paddle.to_tensor(rs.randn(1, 4, 8).astype("float32"))
        qkvw = paddle.to_tensor(rs.randn(3, 2, 4, 8).astype("float32") * .1)
        lw = paddle.to_tensor(rs.randn(8, 8).astype("float32") * .1)
        mask = np.ones((1, 2, 4, 4), bool)
        mask[..., -1] = False  # nobody attends the last position
        out = fused_multi_head_attention(
            x, qkvw, lw, attn_mask=paddle.to_tensor(mask),
            dropout_rate=0.0, attn_dropout_rate=0.0)
        assert np.isfinite(out.numpy()).all()

    def test_downscale_in_infer_mode(self):
        """training=False + mode='downscale_in_infer' scales by (1-p)
        (reference dropout-mode semantics); output must differ from the
        no-dropout result by exactly that factor on the attention/linear
        outputs (residual excluded, so check inequality + finiteness)."""
        from paddle_tpu.incubate.nn import fused_multi_head_attention
        rs = np.random.RandomState(2)
        x = paddle.to_tensor(rs.randn(1, 4, 8).astype("float32"))
        qkvw = paddle.to_tensor(rs.randn(3, 2, 4, 8).astype("float32") * .1)
        lw = paddle.to_tensor(rs.randn(8, 8).astype("float32") * .1)
        base = fused_multi_head_attention(
            x, qkvw, lw, pre_layer_norm=True, dropout_rate=0.0,
            attn_dropout_rate=0.0, training=False)
        scaled = fused_multi_head_attention(
            x, qkvw, lw, pre_layer_norm=True, dropout_rate=0.5,
            attn_dropout_rate=0.0, mode="downscale_in_infer",
            training=False)
        # pre_layer_norm=True: out = x + o; scaled attn output halves o
        np.testing.assert_allclose(
            scaled.numpy() - x.numpy(),
            (base.numpy() - x.numpy()) * 0.5, rtol=1e-5, atol=1e-6)


class TestFusedFFNFunctional:
    """incubate.nn.functional.fused_feedforward parity
    (ref fused_transformer.py:31 pseudo code)."""

    def test_matches_manual_pre_ln(self):
        from paddle_tpu.incubate.nn.functional import fused_feedforward
        rs = np.random.RandomState(3)
        x = paddle.to_tensor(rs.randn(2, 4, 8).astype("float32"),
                             stop_gradient=False)
        w1 = paddle.to_tensor(rs.randn(8, 16).astype("float32") * .1)
        w2 = paddle.to_tensor(rs.randn(16, 8).astype("float32") * .1)
        out = fused_feedforward(x, w1, w2, pre_layer_norm=True,
                                dropout1_rate=0.0, dropout2_rate=0.0)
        h = x.numpy()
        mu = h.mean(-1, keepdims=True)
        var = h.var(-1, keepdims=True)
        hn = (h - mu) / np.sqrt(var + 1e-5)
        want = np.maximum(hn @ w1.numpy(), 0) @ w2.numpy() + h
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-4,
                                   atol=1e-5)
        out.sum().backward()
        assert x.grad is not None

    def test_post_ln_and_gelu(self):
        from paddle_tpu.incubate.nn.functional import fused_feedforward
        rs = np.random.RandomState(4)
        x = paddle.to_tensor(rs.randn(1, 3, 8).astype("float32"))
        w1 = paddle.to_tensor(rs.randn(8, 16).astype("float32") * .1)
        w2 = paddle.to_tensor(rs.randn(16, 8).astype("float32") * .1)
        out = fused_feedforward(x, w1, w2, activation="gelu",
                                dropout1_rate=0.0, dropout2_rate=0.0)
        # post-LN output is normalized: per-position mean ~0, var ~1
        o = out.numpy()
        np.testing.assert_allclose(o.mean(-1), 0.0, atol=1e-5)
        np.testing.assert_allclose(o.var(-1), 1.0, atol=1e-3)

    def test_functional_namespace(self):
        import paddle_tpu.incubate.nn.functional as F
        assert callable(F.fused_multi_head_attention)
        assert callable(F.fused_feedforward)
