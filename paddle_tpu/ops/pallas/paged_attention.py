"""MXU-shaped ragged paged attention for TPU in Pallas.

The serving-side twin of flash_attention.py (PAPERS.md "Ragged Paged
Attention: A High-Performance and Flexible LLM Inference Kernel for
TPU"): ONE kernel call processes a batch of query tokens whose rows
belong to DIFFERENT sequences at DIFFERENT lengths — decode rows (one
token against a long history) and prefill-chunk rows (a slice of a
prompt against its own growing history) mix freely, under per-token
causal bounds.

Blocking (ops/pallas/attention_core.py owns the policy, shared with
the training kernel):

- tokens are grouped into Q-BLOCKS of `Bq` rows; for grouped-query
  models the `fold = H_q // H_kv` query heads sharing one kv head are
  folded into the row dimension, so every score dot is
  [Bq*fold, D] x [D, P] — M >= MIN_DOT_ROWS (target MXU_ROWS), where
  the seed-era kernel issued per-(token, head) [1, D] x [D, P] VPU
  dots. Rows of a q-block that don't own the current page are masked
  (and their probabilities explicitly zeroed), which costs nothing:
  they ride sublanes the narrow dot was wasting anyway.
- the kv pages each q-block must visit come from a host-side BLOCK
  PLAN (build_block_plan, grown in PagedKVCache.plan_ragged — no
  device round-trips in the serving scheduler): per q-block, the
  compacted list of (page id, owning row, kv start) slots any of its
  tokens' bounds reach, plus the real slot count. Shapes depend only
  on (T, B, W), so the serving executable's signature is unchanged.
- the page walk is DOUBLE-BUFFERED DMA (pallas_guide.md pattern): the
  kernel copies page i+1 into the alternate VMEM slot while computing
  page i, so the HBM walk overlaps the MXU work. A q-block of pure pad
  tokens has a zero slot count and issues NO copies at all.

The kernel still emits the per-token WORK counter (kv page blocks
actually computed = ceil(bound/P), 0 for pads) — the ground truth
behind the serving engine's `pad_token_fraction` metric and the tests'
skip-proof, not an estimate.

Softmax is the shared online/flash formulation in f32
(attention_core.softmax_update). On CPU (tier-1) the same kernel —
DMA double-buffering included — runs in Pallas interpret mode, so the
serving engine exercises identical code on every backend.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import I0
from . import attention_core as core

__all__ = ["ragged_paged_attention", "ragged_work_plan",
           "build_block_plan"]


def build_block_plan(page_table, token_seq, bounds, page_size, q_block):
    """HOST-side (numpy) kv-page plan for the blocked kernel: which
    pages each q-block walks, compacted so the DMA loop touches only
    real work.

    Returns (blk_pages, blk_seq, blk_start, blk_n):

        blk_pages [QB, S] int32  page id of each slot (S = B*W cap)
        blk_seq   [QB, S] int32  page_table row owning the slot
        blk_start [QB, S] int32  kv position where the page starts
        blk_n     [QB]    int32  real slots; the kernel loops to this

    A slot exists when ANY token of the q-block has a causal bound
    reaching into that page (bound > page_start). Slots keep
    (row-major, page-minor) order; entries past blk_n are never read.
    Shapes are a pure function of (T, B, W, q_block), so a serving
    executable keyed on (T, B, W) stays one executable."""
    pt = np.asarray(page_table, np.int64)
    seq = np.asarray(token_seq, np.int64).reshape(-1)
    bd = np.asarray(bounds, np.int64).reshape(-1)
    B, W = pt.shape
    T = seq.shape[0]
    q_block = int(q_block)
    if T % q_block:
        raise ValueError(f"tokens {T} not divisible by q_block {q_block}")
    QB = T // q_block
    S = B * W
    # per-(q-block, row) max bound: the page reach of the block's rows
    bb = np.zeros((QB, B), np.int64)
    np.maximum.at(bb, (np.arange(T) // q_block, seq), bd)
    starts = np.arange(W, dtype=np.int64) * int(page_size)
    active = (bb[:, :, None] > starts[None, None, :]).reshape(QB, S)
    # stable partition: active slots first, (row, page) order preserved
    order = np.argsort(~active, axis=1, kind="stable")
    take = lambda a: np.take_along_axis(
        np.broadcast_to(a.reshape(1, S), (QB, S)), order, axis=1)
    return (take(pt.reshape(-1)).astype(np.int32),
            take(np.arange(S) // W).astype(np.int32),
            take((np.arange(S) % W) * int(page_size)).astype(np.int32),
            active.sum(axis=1).astype(np.int32))


def _block_plan_jnp(page_table, token_seq, bounds, page_size, q_block):
    """Traced twin of build_block_plan for callers without a host plan
    (eager tests, kernels jitted standalone): same fixed shapes, same
    slot order, computable on concrete OR traced arrays. The serving
    path never takes this — its plan rides in from plan_ragged."""
    pt = page_table.astype(jnp.int32)
    seq = token_seq.astype(jnp.int32).reshape(-1)
    bd = bounds.astype(jnp.int32).reshape(-1)
    B, W = pt.shape
    T = seq.shape[0]
    QB = T // int(q_block)
    S = B * W
    qb_idx = jnp.arange(T, dtype=jnp.int32) // jnp.int32(q_block)
    bb = jnp.zeros((QB, B), jnp.int32).at[qb_idx, seq].max(bd)
    slot = jnp.arange(S, dtype=jnp.int32)
    rows, pages = slot // W, slot % W
    starts = pages * jnp.int32(page_size)
    active = bb[:, rows] > starts[None, :]                   # [QB, S]
    # stable partition via a composite sort key (inactive rank S floats
    # every active slot ahead while the +slot term keeps their order)
    order = jnp.argsort(
        jnp.where(active, jnp.int32(0), jnp.int32(S)) * S + slot, axis=1)
    take = lambda a: jnp.take_along_axis(
        jnp.broadcast_to(a[None, :], (QB, S)), order, axis=1)
    return (take(pt.reshape(-1)), take(rows), take(starts),
            jnp.sum(active.astype(jnp.int32), axis=1))


def _kernel(bp_ref, bs_ref, bst_ref, bn_ref,      # scalar prefetch
            seq_ref, bd_ref, q_ref,               # blocked VMEM inputs
            k_hbm, v_hbm,                         # full pools (ANY)
            o_ref, w_ref,                         # blocked outputs
            kbuf, vbuf, ksem, vsem,               # DMA double buffers
            *, page_size, scale, fold):
    """One (q-block, kv-head) program: walk the block's planned kv
    pages through the double buffer, online-softmax every page into
    the folded [Bq*fold, D] accumulator under the per-token bounds."""
    qb = pl.program_id(0)
    h = pl.program_id(1)
    n = bn_ref[qb]
    Bq, f, D = q_ref.shape
    M = Bq * fold

    seq = seq_ref[:, 0]                           # [Bq] row per token
    bd = bd_ref[:, 0]                             # [Bq] causal bounds
    if fold == 1:
        q = q_ref[:, 0, :].astype(jnp.float32)    # [M, D]
        seq_f, bd_f = seq, bd
    else:
        q = q_ref[...].astype(jnp.float32).reshape(M, D)
        brd = lambda a: jnp.broadcast_to(
            a[:, None], (Bq, fold)).reshape(M)
        seq_f, bd_f = brd(seq), brd(bd)

    def copies(i, slot):
        page = bp_ref[qb, i]
        return (pltpu.make_async_copy(k_hbm.at[page, :, h],
                                      kbuf.at[slot], ksem.at[slot]),
                pltpu.make_async_copy(v_hbm.at[page, :, h],
                                      vbuf.at[slot], vsem.at[slot]))

    @pl.when(h == 0)
    def _zero_work():
        w_ref[:, 0] = jnp.zeros((Bq,), jnp.int32)

    @pl.when(n > 0)
    def _warmup():                                # first page's DMA
        for c in copies(0, 0):
            c.start()

    def body(i, carry):
        m, l, acc = carry
        two = jnp.asarray(2, i.dtype)
        slot = jax.lax.rem(i, two)

        @pl.when(i + 1 < n)
        def _prefetch():                          # overlap: next page
            for c in copies(i + 1, jax.lax.rem(i + 1, two)):
                c.start()

        for c in copies(i, slot):
            c.wait()
        b = bs_ref[qb, i]
        start = bst_ref[qb, i]
        k = kbuf[slot].astype(jnp.float32)        # [P, D]
        v = vbuf[slot].astype(jnp.float32)        # [P, D]
        s = core.score_dot(q, k, scale)           # [M, P] — MXU-shaped
        pos = start + jax.lax.broadcasted_iota(
            jnp.int32, (M, page_size), 1)
        valid = (seq_f == b)[:, None] & (pos < bd_f[:, None])
        m, l, acc = core.softmax_update(m, l, acc, s, v, valid=valid)

        @pl.when(h == 0)
        def _count():                             # measured work, not
            w_ref[:, 0] += (                      # an estimate
                (seq == b) & (start < bd)).astype(jnp.int32)

        return m, l, acc

    m, l, acc = jax.lax.fori_loop(
        0, n, body, core.softmax_carry(M, D))
    out, _ = core.softmax_finalize(m, l, acc)
    o_ref[...] = out.reshape(Bq, fold, D).astype(o_ref.dtype)


def ragged_paged_attention(q, k_pages, v_pages, page_table, token_seq,
                           bounds, scale=None, interpret=None,
                           return_work=False, block_plan=None,
                           q_block=None):
    """Mixed prefill+decode attention over paged KV state.

    q:          [T, H, D]  query tokens, any mix of sequences/phases
    k_pages:    [n_pages, P, H_kv, D]  shared page pools (H_kv may
                divide H: grouped-query folding puts the group's heads
                in the same score dot)
    v_pages:    [n_pages, P, H_kv, D]
    page_table: [B, W] int32 page ids per sequence (pad page 0)
    token_seq:  [T] int32  page_table row of each token
    bounds:     [T] int32  kv tokens visible to each token (causal:
                history + preceding new tokens + itself); 0 marks a pad
                token that does NO work
    block_plan: optional (blk_pages, blk_seq, blk_start, blk_n) from
                build_block_plan — the serving path precomputes it on
                the host (PagedKVCache.plan_ragged); omitted, the same
                plan is derived in-trace.
    q_block:    rows per q-block; default attention_core.choose_q_block
                (<= MXU_ROWS/fold, halved to divide T).

    Returns [T, H, D] (and, with return_work, the per-token count of
    kv page blocks actually computed — ceil(bound/P), 0 for pads)."""
    T, H, D = q.shape
    n_pages, P, KVH, _ = k_pages.shape
    if H % KVH:
        raise ValueError(f"q heads {H} not a multiple of kv heads {KVH}")
    fold = H // KVH
    B, W = page_table.shape
    scale = core.default_scale(scale, D)
    interpret = core.default_interpret(interpret)
    bq = int(q_block) if q_block else core.choose_q_block(
        T, cap=max(core.MXU_ROWS // fold, 1))
    if T % bq:
        raise ValueError(f"tokens {T} not divisible by q_block {bq}")
    QB = T // bq
    if block_plan is None:
        block_plan = _block_plan_jnp(page_table, token_seq, bounds,
                                     P, bq)
    bp, bs, bst, bn = (jnp.asarray(a, jnp.int32) for a in block_plan)
    if bp.shape != (QB, B * W) or bn.shape != (QB,):
        raise ValueError(
            f"block plan shape {bp.shape}/{bn.shape} does not match "
            f"q_block={bq} over T={T}, B={B}, W={W}")
    out, work = pl.pallas_call(
        functools.partial(_kernel, page_size=P, scale=scale, fold=fold),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(QB, KVH),
            in_specs=[
                pl.BlockSpec((bq, 1), lambda qb, h, *_: (qb, I0)),
                pl.BlockSpec((bq, 1), lambda qb, h, *_: (qb, I0)),
                pl.BlockSpec((bq, fold, D),
                             lambda qb, h, *_: (qb, h, I0)),
                # the pools stay in HBM; the kernel's double-buffered
                # DMA walks exactly the planned pages
                pl.BlockSpec(memory_space=pltpu.ANY),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=[
                pl.BlockSpec((bq, fold, D),
                             lambda qb, h, *_: (qb, h, I0)),
                # work lives in a [T, 1] column: trailing (Bq, 1)
                # blocks keep the revisited counter on one resident
                # tile across the kv-head grid axis
                pl.BlockSpec((bq, 1), lambda qb, h, *_: (qb, I0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, P, D), k_pages.dtype),  # k double buffer
                pltpu.VMEM((2, P, D), v_pages.dtype),  # v double buffer
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((T, H, D), q.dtype),
            jax.ShapeDtypeStruct((T, 1), jnp.int32),
        ],
        interpret=interpret,
    )(bp, bs, bst, bn,
      token_seq.astype(jnp.int32).reshape(T, 1),
      bounds.astype(jnp.int32).reshape(T, 1),
      q, k_pages, v_pages)
    if return_work:
        return out, work[:, 0]
    return out


def ragged_work_plan(bounds, page_size):
    """Host-side mirror of the kernel's work counter: kv blocks each
    token will compute (ceil(bound/P); 0 for pads). The serving engine
    uses this to report `pad_token_fraction` without reading the work
    output back per step."""
    b = np.asarray(bounds, np.int64)
    return -(-b // int(page_size)) * (b > 0)
