"""paddle.utils.dlpack — zero-copy tensor exchange via the DLPack
protocol.

Parity: /root/reference/python/paddle/utils/dlpack.py. jax arrays speak
DLPack natively, so to_dlpack hands out the capsule of the backing
array and from_dlpack imports straight onto the device.
"""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor → DLPack capsule (no copy; the tensor keeps ownership)."""
    if isinstance(x, Tensor):
        x = x.value
    if not hasattr(x, "__dlpack__"):
        raise TypeError(
            f"to_dlpack expects a paddle Tensor or array, got {type(x)}")
    return x.__dlpack__()


class _CapsuleHolder:
    """Adapter giving a raw capsule the __dlpack__ protocol surface
    jnp.from_dlpack expects."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, stream=None):
        return self._capsule

    def __dlpack_device__(self):
        # kDLCPU = 1; jax re-queries the real device from the capsule
        return (1, 0)


def from_dlpack(dlpack):
    """DLPack capsule (or any object exporting __dlpack__) → Tensor."""
    if hasattr(dlpack, "__dlpack__"):
        arr = jnp.from_dlpack(dlpack)
    else:
        arr = jnp.from_dlpack(_CapsuleHolder(dlpack))
    return Tensor(arr)
