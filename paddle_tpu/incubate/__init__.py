"""paddle.incubate. Parity: python/paddle/incubate/__init__.py (subset:
the pieces the training stack uses — fused ops route to Pallas/XLA)."""
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op

__all__ = ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
           "graph_send_recv", "segment_sum", "segment_mean", "segment_max",
           "segment_min", "optimizer", "nn"]


def softmax_mask_fuse(x, mask, name=None):
    return apply_op(
        lambda a, m: jax.nn.softmax(a + m.astype(a.dtype), -1), x, mask)


def softmax_mask_fuse_upper_triangle(x):
    def fn(a):
        T = a.shape[-1]
        causal = jnp.tril(jnp.ones((a.shape[-2], T), bool), k=T - a.shape[-2])
        return jax.nn.softmax(jnp.where(causal, a, -1e30), -1)
    return apply_op(fn, x)


def _segment(op, init):
    def seg(data, segment_ids, name=None):
        def fn(d, ids):
            n = int(jnp.max(ids)) + 1 if not isinstance(
                ids, jax.core.Tracer) else d.shape[0]
            out = jnp.full((n,) + d.shape[1:], init, d.dtype)
            if op == "sum" or op == "mean":
                out = jnp.zeros((n,) + d.shape[1:], d.dtype).at[ids].add(d)
                if op == "mean":
                    cnt = jnp.zeros((n,), d.dtype).at[ids].add(1.0)
                    out = out / jnp.maximum(cnt, 1.0).reshape(
                        (-1,) + (1,) * (d.ndim - 1))
                return out
            if op == "max":
                return out.at[ids].max(d)
            return out.at[ids].min(d)
        return apply_op(fn, data, segment_ids)
    return seg


segment_sum = _segment("sum", 0.0)
segment_mean = _segment("mean", 0.0)
segment_max = _segment("max", -jnp.inf)
segment_min = _segment("min", jnp.inf)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    def fn(a, src, dst):
        gathered = a[src]
        n = a.shape[0] if out_size is None else out_size
        if pool_type in ("sum", "mean"):
            out = jnp.zeros((n,) + a.shape[1:], a.dtype).at[dst].add(
                gathered)
            if pool_type == "mean":
                cnt = jnp.zeros((n,), a.dtype).at[dst].add(1.0)
                out = out / jnp.maximum(cnt, 1.0).reshape(
                    (-1,) + (1,) * (a.ndim - 1))
            return out
        if pool_type == "max":
            return jnp.full((n,) + a.shape[1:], -jnp.inf,
                            a.dtype).at[dst].max(gathered)
        return jnp.full((n,) + a.shape[1:], jnp.inf,
                        a.dtype).at[dst].min(gathered)
    return apply_op(fn, x, src_index, dst_index)


class optimizer:
    """paddle.incubate.optimizer — LookAhead / ModelAverage."""

    class LookAhead:
        def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
            self.inner = inner_optimizer
            self.alpha = alpha
            self.k = k
            self._slow = None
            self._count = 0

        def step(self):
            from ..framework.core import no_grad
            self.inner.step()
            self._count += 1
            if self._slow is None:
                self._slow = [p.value for p in self.inner._parameters]
            if self._count % self.k == 0:
                with no_grad():
                    for p, s in zip(self.inner._parameters, self._slow):
                        new_slow = s + self.alpha * (p.value - s)
                        p.set_value(new_slow)
                    self._slow = [p.value for p in self.inner._parameters]

        def clear_grad(self):
            self.inner.clear_grad()

        def minimize(self, loss):
            loss.backward()
            self.step()

    class ModelAverage:
        def __init__(self, average_window_rate, parameters=None,
                     min_average_window=10000,
                     max_average_window=10000, name=None):
            self.parameters = parameters or []
            self._sum = None
            self._n = 0

        def step(self):
            if self._sum is None:
                self._sum = [p.value for p in self.parameters]
            else:
                self._sum = [s + p.value
                             for s, p in zip(self._sum, self.parameters)]
            self._n += 1

        def apply(self, executor=None, need_restore=True):
            import contextlib

            @contextlib.contextmanager
            def ctx():
                from ..framework.core import no_grad
                backup = [p.value for p in self.parameters]
                with no_grad():
                    for p, s in zip(self.parameters, self._sum):
                        p.set_value(s / max(self._n, 1))
                yield
                if need_restore:
                    with no_grad():
                        for p, b in zip(self.parameters, backup):
                            p.set_value(b)
            return ctx()


class nn:
    """paddle.incubate.nn — fused layer entry points map onto Pallas."""

    class FusedMultiHeadAttention:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                "use paddle_tpu.nn.MultiHeadAttention — it already "
                "dispatches to the fused Pallas flash-attention kernel")

    class FusedFeedForward:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                "XLA fuses the FFN (matmul+gelu+matmul) automatically")

    @staticmethod
    def fused_multi_head_attention(*a, **k):
        raise NotImplementedError(
            "use nn.functional.scaled_dot_product_attention")
