"""paddle.tensor namespace: op modules + Tensor method/operator patching.

Parity: python/paddle/tensor/__init__.py + the monkey-patching done by
python/paddle/fluid/dygraph/math_op_patch.py in the reference.
"""
from . import array, attribute, creation, einsum, linalg, logic, \
    manipulation, math, random, search, stat
from .array import (array_length, array_read, array_write,  # noqa: F401
                    create_array)
from ..framework.core import Tensor

_MODULES = [attribute, creation, einsum, linalg, logic, manipulation, math,
            random, search, stat]

# names that exist in several modules; prefer this resolution order
_EXPORT_SKIP = {"Tensor", "apply_op", "to_tensor", "np", "jnp", "jax",
                "builtins", "convert_dtype", "get_default_dtype"}


def _collect_exports():
    exports = {}
    for mod in _MODULES:
        for name in dir(mod):
            if name.startswith("_") or name in _EXPORT_SKIP:
                continue
            obj = getattr(mod, name)
            if callable(obj):
                exports.setdefault(name, obj)
    return exports


_EXPORTS = _collect_exports()
globals().update(_EXPORTS)


# ---- Tensor method patching ------------------------------------------
_METHOD_NAMES = [
    # math
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "maximum", "minimum", "fmax", "fmin", "abs", "exp",
    "expm1", "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "square",
    "sign", "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
    "tanh", "asinh", "acosh", "atanh", "ceil", "floor", "round", "trunc",
    "frac", "reciprocal", "neg", "erf", "erfinv", "digamma", "lgamma",
    "sigmoid", "angle", "conj", "real", "imag", "deg2rad", "rad2deg",
    "scale", "clip", "lerp", "sum", "nansum", "prod", "mean", "max", "min",
    "amax", "amin", "all", "any", "logsumexp", "count_nonzero", "cumsum",
    "cumprod", "logcumsumexp", "kron", "outer", "inner", "trace",
    "diagonal", "diff", "isfinite", "isinf", "isnan", "atan2", "heaviside",
    "rot90", "take", "nan_to_num", "trapezoid", "renorm", "exp2",
    # inplace math
    "add_", "subtract_", "multiply_", "scale_", "clip_", "ceil_", "floor_",
    "round_", "exp_", "sqrt_", "rsqrt_", "reciprocal_", "tanh_", "zero_",
    "fill_", "fill_diagonal_", "fill_diagonal_tensor",
    "fill_diagonal_tensor_", "uniform_", "bernoulli_", "exponential_",
    # linalg
    "matmul", "dot", "bmm", "mv", "mm", "cross", "norm", "dist", "cholesky",
    "qr", "svd", "eig", "eigvals", "inv", "pinv", "solve", "lstsq",
    "matrix_power", "det", "slogdet", "histogram", "bincount", "addmm",
    # logic
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_xor", "logical_not",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "equal_all",
    "allclose", "isclose",
    # manipulation
    "reshape", "reshape_", "flatten", "squeeze", "squeeze_", "unsqueeze",
    "unsqueeze_", "split", "chunk", "unbind", "tile", "expand",
    "broadcast_to", "expand_as", "transpose", "t", "moveaxis", "swapaxes",
    "flip", "roll", "gather", "gather_nd", "scatter", "scatter_",
    "scatter_nd_add", "index_select", "index_sample", "index_add",
    "masked_select", "masked_fill", "take_along_axis", "put_along_axis",
    "unique", "unique_consecutive", "repeat_interleave", "as_complex",
    "as_real", "tensordot", "slice", "strided_slice", "view", "view_as",
    "cast", "tril", "triu", "diag", "diagflat", "diag_embed",
    # search
    "argmax", "argmin", "argsort", "sort", "topk", "where", "nonzero",
    "kthvalue", "mode", "searchsorted", "bucketize",
    # stat
    "std", "var", "median", "nanmedian", "quantile", "nanquantile", "numel",
    # random
    "multinomial",
    # remaining tensor_method_func parity (reference
    # python/paddle/tensor/__init__.py tensor_method_func list)
    "add_n", "broadcast_shape", "broadcast_tensors", "cholesky_solve",
    "concat", "cond", "cov", "eigvalsh", "erfinv_", "flatten_",
    "floor_mod", "gcd", "increment", "inverse", "is_complex", "is_empty",
    "is_floating_point", "is_integer", "is_tensor", "lcm", "lerp_",
    "logit", "lu", "lu_unpack", "multi_dot", "multiplex",
    "put_along_axis_", "rank", "reverse", "scatter_nd", "shard_index",
    "stack", "stanh", "triangular_solve", "unstack",
]


def _patch_tensor_methods():
    for name in _METHOD_NAMES:
        fn = _EXPORTS.get(name)
        if fn is not None and not hasattr(Tensor, name):
            setattr(Tensor, name, fn)

    m, lg, la, mp, srch = math, logic, linalg, manipulation, search

    def _swap(fn):
        return lambda self, other: fn(other, self)

    Tensor.__add__ = m.add
    Tensor.__radd__ = _swap(m.add)
    Tensor.__sub__ = m.subtract
    Tensor.__rsub__ = _swap(m.subtract)
    Tensor.__mul__ = m.multiply
    Tensor.__rmul__ = _swap(m.multiply)
    Tensor.__truediv__ = m.divide
    Tensor.__rtruediv__ = _swap(m.divide)
    Tensor.__floordiv__ = m.floor_divide
    Tensor.__rfloordiv__ = _swap(m.floor_divide)
    Tensor.__mod__ = m.mod
    Tensor.__rmod__ = _swap(m.mod)
    Tensor.__pow__ = m.pow
    Tensor.__rpow__ = _swap(m.pow)
    Tensor.__neg__ = m.neg
    Tensor.__abs__ = m.abs
    Tensor.__matmul__ = la.matmul
    Tensor.__rmatmul__ = _swap(la.matmul)
    Tensor.__eq__ = lg.equal
    Tensor.__ne__ = lg.not_equal
    Tensor.__lt__ = lg.less_than
    Tensor.__le__ = lg.less_equal
    Tensor.__gt__ = lg.greater_than
    Tensor.__ge__ = lg.greater_equal
    Tensor.__invert__ = lg.logical_not
    Tensor.__and__ = lg.bitwise_and
    Tensor.__or__ = lg.bitwise_or
    Tensor.__xor__ = lg.bitwise_xor
    Tensor.__hash__ = lambda self: id(self)
    Tensor.T = property(lambda self: mp.transpose(
        self, list(range(self.ndim))[::-1]))
    Tensor.mT = property(lambda self: mp.swapaxes(self, -1, -2))


_patch_tensor_methods()
