"""The distributed observatory (ISSUE 13): per-collective timing,
rank-skew/straggler detection, clock-aligned multi-rank traces, and
measured device-time MFU.

Proof points:
- every collective call folds into the rollup and the sampled subset
  emits schema-valid `kind:"collective"` records (eager calls with real
  bandwidth, traced insertions flagged);
- the device-time probe (cadence-gated, lint-fenced) stamps
  `step_time_device_s` / `mfu_measured` / `overlap_fraction` onto
  exactly the steps it measured, schema-valid;
- `kind:"rankstat"` records validate, snapshot atomically into the
  gather dir, and rank 0's gather feeds the straggler detector
  (edge-triggered, naming rank + lag);
- merged traces are CLOCK-ALIGNED: a fabricated 5 s clock skew
  disappears when otherData.clock_offset_s is applied (and survives
  --no-align);
- `load_profiler_result` exposes `.collectives` / `.rankstats` from
  both JSONL and host_stats.json;
- tools/obs_report.py renders the run summary;
- END TO END: a 4-process `launch.py` run with a 300 ms
  `delay@train.step` fault on exactly one rank produces a schema-valid
  rankstat stream and a straggler event naming that rank, plus
  clock-aligned mergeable traces.
"""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as opt
from paddle_tpu import profiler
from paddle_tpu.jit import TrainStep
from paddle_tpu.profiler import (dist_observatory as dobs, monitor,
                                 statistic, flight_recorder)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_dist_obs_worker.py")


def _load_tool(name):
    path = os.path.join(REPO, "tools", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    statistic.reset_statistics()
    monitor.reset_metrics()
    flight_recorder.reset()
    dobs.reset()
    monkeypatch.delenv("PADDLE_TPU_RANKSTAT_DIR", raising=False)
    yield
    dobs.reset()


def _make_step():
    paddle.seed(0)
    m = nn.Linear(8, 8)
    o = opt.SGD(learning_rate=0.01, parameters=m.parameters())
    step = TrainStep(m, lambda out, y: ((out - y) ** 2).mean(), o)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 8).astype(np.float32))
    return step, x


# ------------------------------------------------ collective telemetry
def test_eager_collective_emits_record_and_rollup(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE",
                       str(tmp_path / "m.jsonl"))
    monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_SAMPLE", "1")
    t = paddle.to_tensor(np.ones(1024, np.float32))
    dist.all_reduce(t)
    dist.wait(t)
    roll = dobs.collective_rollup()
    assert roll["all_reduce"]["calls"] == 1
    assert roll["all_reduce"]["bytes"] == 4096
    assert roll["all_reduce"]["wall_s"] > 0
    assert roll["all_reduce"]["traced_calls"] == 0
    recs = [r for r in dobs.collectives_tail()]
    ops = {r["op"] for r in recs}
    assert {"all_reduce", "wait"} <= ops
    ar = next(r for r in recs if r["op"] == "all_reduce")
    assert ar["group"] == "dp" and ar["bytes"] == 4096
    assert ar["traced"] is False and ar["bw_gbps"] > 0
    # the JSONL lines validate against the schema tool
    tool = _load_tool("check_metrics_schema")
    assert tool.validate_file(str(tmp_path / "m.jsonl")) == []


def test_collective_sampling_cadence(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_SAMPLE", "4")
    for _ in range(9):
        dobs.record_collective("psum", "dp", 128, 1e-5)
    # sampled at call 1 (first), 4, 8 — rollup counts all 9
    assert len(dobs.collectives_tail()) == 3
    assert dobs.collective_rollup()["psum"]["calls"] == 9
    assert dobs.collective_rollup()["psum"]["bytes"] == 9 * 128


def test_traced_collective_flagged_not_timed(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_SAMPLE", "1")
    from paddle_tpu.framework.jax_compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    f = jax.jit(shard_map(lambda v: dist.psum(v, "dp"), mesh=mesh,
                          in_specs=P("dp"), out_specs=P()))
    out = f(np.ones(4, np.float32))
    np.testing.assert_allclose(np.asarray(out), 2.0)
    roll = dobs.collective_rollup()["psum"]
    assert roll["traced_calls"] >= 1 and roll["wall_s"] == 0.0
    rec = next(r for r in dobs.collectives_tail() if r["op"] == "psum")
    assert rec["traced"] is True and rec["bw_gbps"] == 0.0
    # eager wait accounting must exclude traced insertion time
    assert dobs.eager_wait_s() == 0.0


# ------------------------------------------------ device-time probe
def test_device_probe_stamps_measured_fields(tmp_path, monkeypatch):
    path = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(path))
    monkeypatch.setenv("PADDLE_TPU_DEVICE_TIME_EVERY", "2")
    step, x = _make_step()
    loss = None
    for _ in range(4):
        loss = step(x, x)
    float(loss.item())
    recs = [json.loads(l) for l in path.read_text().splitlines()
            if l.strip()]
    steps = {r["step"]: r for r in recs if r["kind"] == "step"}
    # probed steps carry the measured fields; unprobed steps don't
    # (step 2 is the first probe: step 1 left the drain handle)
    for i in (2, 4):
        assert steps[i]["step_time_device_s"] > 0
        assert 0.0 <= steps[i]["overlap_fraction"] <= 1.0
        assert steps[i]["mfu_measured"] >= 0.0  # 0.0 on CPU (no peak)
    for i in (1, 3):
        assert "step_time_device_s" not in steps[i]
    summary = dobs.device_time_summary()
    assert summary["samples"] == 2
    assert summary["step_time_device_s"] > 0
    assert monitor.get_metric("train.step_time_device_s").value > 0
    tool = _load_tool("check_metrics_schema")
    assert tool.validate_file(str(path)) == []


def test_probed_step_time_keeps_host_stalls_drops_probe_drain(
        tmp_path, monkeypatch):
    """The probe BLOCKS: without correction the probed step's
    inter-dispatch interval absorbs the drain wait and the next step's
    collapses to ~0 with a faked 'steady' MFU. The fix subtracts ONLY
    the probe's own drain — a real host stall (here a PR-11 injected
    100 ms delay, the straggler scenario) must stay visible in
    step_time_s, while step_time_device_s keeps the pure device
    window."""
    from paddle_tpu.framework import fault_injection
    path = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(path))
    monkeypatch.setenv("PADDLE_TPU_DEVICE_TIME_EVERY", "3")
    fault_injection.configure("delay@train.step=0.1")
    try:
        step, x = _make_step()
        loss = None
        for _ in range(7):
            loss = step(x, x)
        float(loss.item())
    finally:
        fault_injection.configure("")
    steps = {r["step"]: r for r in
             (json.loads(l) for l in path.read_text().splitlines()
              if l.strip()) if r["kind"] == "step"}
    for i in (3, 6):  # the probed steps
        # the injected host delay is part of the step time...
        assert steps[i]["step_time_s"] > 0.09, steps[i]
        # ...but not of the measured device window
        assert steps[i]["step_time_device_s"] < 0.09, steps[i]


def test_emit_rankstat_respects_disable_unless_forced(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_RANKSTAT_EVERY", "0")
    monitor.histogram("train.step_s").observe(0.01)
    assert dobs.emit_rankstat(step=1) is None       # epoch-boundary path
    assert dobs.emit_rankstat(step=1, force=True) is not None  # gate/dryrun


def test_device_probe_off_by_default_env_zero(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_DEVICE_TIME_EVERY", "0")
    step, x = _make_step()
    for _ in range(3):
        loss = step(x, x)
    float(loss.item())
    assert dobs.device_time_summary() == {}


# ------------------------------------------------ rankstat + straggler
def test_rankstat_record_schema_and_snapshot(tmp_path, monkeypatch):
    path = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(path))
    monkeypatch.setenv("PADDLE_TPU_RANKSTAT_DIR",
                       str(tmp_path / "gather"))
    for v in (0.01, 0.02, 0.03):
        monitor.histogram("train.step_s").observe(v)
    rec = dobs.emit_rankstat(step=3)
    assert rec is not None
    assert rec["step_time_p50_s"] > 0
    assert rec["step_time_p99_s"] >= rec["step_time_p50_s"]
    assert 0.0 <= rec["collective_wait_share"] <= 1.0
    # atomic snapshot for the rank-0 gather
    snap = tmp_path / "gather" / "rankstat.0.json"
    assert snap.exists()
    peer = json.loads(snap.read_text())
    assert peer["rank"] == 0 and peer["step_time_p50_s"] > 0
    assert dobs.read_peer_rankstats(str(tmp_path / "gather"))[0]
    tool = _load_tool("check_metrics_schema")
    assert tool.validate_file(str(path)) == []


def test_rank0_gather_emits_straggler_naming_rank(tmp_path, monkeypatch):
    """Single-process simulation of the rank-0 gather: fake peer
    snapshots with one slow rank -> event:'straggler' names it."""
    path = tmp_path / "m.jsonl"
    gather = tmp_path / "gather"
    gather.mkdir()
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(path))
    monkeypatch.setenv("PADDLE_TPU_RANKSTAT_DIR", str(gather))
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
    for r, p50 in ((1, 0.011), (2, 0.31), (3, 0.009)):
        (gather / f"rankstat.{r}.json").write_text(json.dumps(
            {"rank": r, "step": 8, "steps_observed": 8,
             "step_time_p50_s": p50}))
    for _ in range(4):
        monitor.histogram("train.step_s").observe(0.01)
    dobs.emit_rankstat(step=8)
    evs = [e for e in flight_recorder.snapshot()["events"]
           if e.get("event") == "straggler"]
    assert len(evs) == 1, evs
    assert evs[0]["straggler_rank"] == 2
    assert evs[0]["lag_s"] > 0.25
    assert monitor.get_metric("dist.stragglers").value == 1
    # edge-triggered: the same skew again emits nothing new
    dobs.emit_rankstat(step=10)
    evs = [e for e in flight_recorder.snapshot()["events"]
           if e.get("event") == "straggler"]
    assert len(evs) == 1


def test_two_rank_world_straggler_detectable():
    """True median: in a 2-rank world the straggler's own time must not
    become the baseline (the upper-middle pick made it undetectable)."""
    from paddle_tpu.profiler.health import AnomalyDetector
    d = AnomalyDetector()
    evs = d.observe_ranks(5, {0: 0.1, 1: 0.4})
    assert len(evs) == 1 and evs[0]["straggler_rank"] == 1, evs


def test_gather_skips_stale_and_out_of_world_snapshots(
        tmp_path, monkeypatch):
    """An elastic restart reusing the log_dir (frozen snapshots from a
    dead rank / a shrunk world) must not feed phantom stragglers."""
    gather = tmp_path / "gather"
    gather.mkdir()
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE",
                       str(tmp_path / "m.jsonl"))
    monkeypatch.setenv("PADDLE_TPU_RANKSTAT_DIR", str(gather))
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
    now = __import__("time").time()
    # rank 1: fresh, healthy. rank 5: outside the 2-rank world. rank 1
    # variant stale: a frozen slow snapshot from an hour ago
    (gather / "rankstat.1.json").write_text(json.dumps(
        {"rank": 1, "steps_observed": 8, "step_time_p50_s": 0.01,
         "ts": now}))
    (gather / "rankstat.5.json").write_text(json.dumps(
        {"rank": 5, "steps_observed": 8, "step_time_p50_s": 9.0,
         "ts": now}))
    (gather / "rankstat.3.json").write_text(json.dumps(
        {"rank": 3, "steps_observed": 8, "step_time_p50_s": 9.0,
         "ts": now - 3600}))
    for _ in range(4):
        monitor.histogram("train.step_s").observe(0.01)
    dobs.emit_rankstat(step=8)
    evs = [e for e in flight_recorder.snapshot()["events"]
           if e.get("event") == "straggler"]
    assert evs == [], evs  # the phantom slow ranks were filtered out


def test_post_probe_step_kept_out_of_step_time_reservoir(monkeypatch):
    """The step after a probe has no meaningful interval — it must not
    enter the train.step_s reservoir the rankstat p50/p99 come from."""
    monkeypatch.setenv("PADDLE_TPU_DEVICE_TIME_EVERY", "2")
    step, x = _make_step()
    loss = None
    for _ in range(6):  # probes at 2, 4; drained successors 3, 5
        loss = step(x, x)
    float(loss.item())
    hist = monitor.get_metric("train.step_s")
    assert hist.count == 4  # 6 steps minus the 2 post-probe successors


def test_maybe_rankstat_cadence(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_RANKSTAT_EVERY", "4")
    monitor.histogram("train.step_s").observe(0.01)
    assert dobs.maybe_rankstat(1) is not None   # first step always
    assert dobs.maybe_rankstat(2) is None
    assert dobs.maybe_rankstat(3) is None
    assert dobs.maybe_rankstat(4) is not None   # cadence boundary
    monkeypatch.setenv("PADDLE_TPU_RANKSTAT_EVERY", "0")
    assert dobs.maybe_rankstat(8) is None       # disabled


# ------------------------------------------------ schema rejections
def test_schema_rejects_bad_collective_and_rankstat(tmp_path):
    tool = _load_tool("check_metrics_schema")
    base = {"ts": 1.0, "rank": 0}
    bad = tmp_path / "bad.jsonl"
    bad.write_text("\n".join(json.dumps(r) for r in [
        # infinite bandwidth must be named
        dict(base, kind="collective", op="psum", group="dp", bytes=8,
             wall_s=0.0, bw_gbps=float("inf")),
        # negative bytes
        dict(base, kind="collective", op="psum", group="dp", bytes=-1,
             wall_s=0.0, bw_gbps=0.0),
        # rank outside the world
        dict(base, rank=5, kind="rankstat", step=1, world_size=4,
             step_time_p50_s=0.01, step_time_p99_s=0.02,
             host_blocked_s=0.0, collective_wait_s=0.0,
             collective_wait_share=0.0, peak_bytes=0),
        # inverted percentiles
        dict(base, kind="rankstat", step=1, world_size=1,
             step_time_p50_s=0.05, step_time_p99_s=0.01,
             host_blocked_s=0.0, collective_wait_s=0.0,
             collective_wait_share=0.0, peak_bytes=0),
        # share out of range
        dict(base, kind="rankstat", step=1, world_size=1,
             step_time_p50_s=0.01, step_time_p99_s=0.02,
             host_blocked_s=0.0, collective_wait_s=0.0,
             collective_wait_share=1.5, peak_bytes=0),
        # probe fields on a step record: overlap out of range
        dict(base, kind="step", step=1, step_time_s=0.1, compile_s=0.0,
             cache_hit=True, peak_bytes=1, flops=1.0, mfu=0.1,
             step_time_device_s=0.1, mfu_measured=0.2,
             overlap_fraction=1.5),
    ]) + "\n")
    errors = tool.validate_file(str(bad))
    for needle in ("bw_gbps", "bytes must be >= 0", "world_size",
                   "percentiles cannot invert",
                   "collective_wait_share", "overlap_fraction"):
        assert any(needle in e for e in errors), (needle, errors)


# ------------------------------------------------ clock alignment
def _fake_trace(path, rank, offset_s, event_wall_s):
    """A minimal trace whose one slice happened at `event_wall_s` on
    rank 0's clock but was STAMPED with a clock running `offset_s`
    ahead (exactly what a skewed rank exports)."""
    events = [
        {"ph": "M", "name": "process_name", "pid": rank, "tid": 0,
         "ts": 0, "args": {"name": f"paddle_tpu rank {rank}"}},
        {"ph": "M", "name": "thread_name", "pid": rank, "tid": 21,
         "ts": 0, "args": {"name": "collectives"}},
        {"ph": "X", "name": "collective.psum", "cat": "collective",
         "ts": (event_wall_s + offset_s) * 1e6, "dur": 1000.0,
         "pid": rank, "tid": 21, "args": {}},
    ]
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms",
                   "otherData": {"rank": rank,
                                 "clock_offset_s": offset_s}}, f)


def test_merge_traces_clock_aligns(tmp_path):
    mt = _load_tool("merge_traces")
    a, b = str(tmp_path / "r0.json"), str(tmp_path / "r1.json")
    _fake_trace(a, 0, 0.0, event_wall_s=100.0)
    _fake_trace(b, 1, 5.0, event_wall_s=100.0)  # clock 5 s ahead
    out = str(tmp_path / "merged.json")
    assert mt.main(["-o", out, a, b]) == 0
    merged = json.load(open(out))
    assert merged["otherData"]["clock_aligned"] is True
    slices = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    assert len(slices) == 2
    ts = sorted(e["ts"] for e in slices)
    # the SAME physical instant: aligned to within a millisecond
    assert abs(ts[1] - ts[0]) < 1e3, ts
    # metadata is NEVER shifted (a thread_name at ts 0 must not land
    # 5 s before the timeline)
    metas = [e for e in merged["traceEvents"] if e.get("ph") == "M"]
    assert all(e["ts"] == 0 for e in metas), metas
    # the aligned merge still validates as a Chrome trace
    tool = _load_tool("check_metrics_schema")
    assert tool.validate_file(out) == []
    # and --no-align keeps the raw 5 s skew
    out2 = str(tmp_path / "raw.json")
    assert mt.main(["-o", out2, "--no-align", a, b]) == 0
    raw = [e for e in json.load(open(out2))["traceEvents"]
           if e.get("ph") == "X"]
    ts = sorted(e["ts"] for e in raw)
    assert abs(ts[1] - ts[0]) > 4.9e6


def test_trace_export_stamps_clock_offset(tmp_path):
    from paddle_tpu.profiler import trace_export
    monitor.histogram("train.step_s").observe(0.01)
    path = trace_export.write_chrome_trace(str(tmp_path / "t.json"))
    payload = json.load(open(path))
    assert payload["otherData"]["clock_offset_s"] == 0.0


# ------------------------------------------------ load_profiler_result
def test_load_profiler_result_exposes_new_kinds(tmp_path, monkeypatch):
    path = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(path))
    monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_SAMPLE", "1")
    monkeypatch.setenv("PADDLE_TPU_RANKSTAT_EVERY", "2")
    step, x = _make_step()
    loss = None
    for _ in range(4):
        loss = step(x, x)
    float(loss.item())
    t = paddle.to_tensor(np.ones(64, np.float32))
    dist.all_reduce(t)
    # JSONL roundtrip
    res = profiler.load_profiler_result(str(path))
    assert len(res.steps) == 4
    assert any(r["op"] == "all_reduce" for r in res.collectives)
    assert len(res.rankstats) >= 1
    assert res.rankstats[0]["world_size"] >= 1
    assert "collective records" in res.summary()
    # host_stats.json roundtrip (mirrors how .compiles was added)
    monkeypatch.setenv("PADDLE_PROFILER_DIR", str(tmp_path / "prof"))
    prof = profiler.Profiler(timer_only=True)
    prof.start()
    prof.stop()
    res2 = profiler.load_profiler_result(str(tmp_path / "prof"))
    assert any(r["op"] == "all_reduce" for r in res2.collectives)
    assert len(res2.rankstats) >= 1


# ------------------------------------------------ obs_report
def test_obs_report_renders_run_summary(tmp_path, monkeypatch):
    path = tmp_path / "m.jsonl"
    monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(path))
    monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_SAMPLE", "1")
    monkeypatch.setenv("PADDLE_TPU_DEVICE_TIME_EVERY", "2")
    step, x = _make_step()
    loss = None
    for _ in range(4):
        loss = step(x, x)
    float(loss.item())
    t = paddle.to_tensor(np.ones(64, np.float32))
    dist.all_reduce(t)
    flight_recorder.record_event("straggler", step=4,
                                 straggler_rank=2, step_time_s=0.3,
                                 median_s=0.01, lag_s=0.29, world=4)
    rep = _load_tool("obs_report")
    recs = rep.load_records(str(path))
    text = rep.render(recs)
    assert "== training ==" in text
    assert "measured device time" in text
    assert "== collectives ==" in text
    assert "all_reduce" in text
    assert "STRAGGLER rank 2" in text
    assert "== compiles ==" in text
    # the CLI contract
    assert rep.main([str(path)]) == 0
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert rep.main([str(empty)]) == 2


# ------------------------------------------------ end to end, 4 ranks
@pytest.mark.heavy
def test_four_process_straggler_and_clock_alignment(tmp_path):
    """The acceptance-criteria run: 4 launch.py ranks, a 300 ms
    delay@train.step fault on exactly rank 2 -> rank 0's gather emits
    a straggler event naming rank 2; every rank's JSONL (rankstat
    stream included) is schema-valid; every rank's trace carries a
    measured clock offset within same-host tolerance and the merged
    trace is valid and clock-aligned."""
    logdir = tmp_path / "logs"
    straggler = 2
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("XLA_", "JAX_"))}
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "4", "--log_dir", str(logdir),
         WORKER, str(tmp_path), str(straggler)],
        env=env, cwd=REPO, timeout=420,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    out = proc.stdout.decode(errors="replace")
    if proc.returncode != 0:
        for r in range(4):
            log = logdir / f"workerlog.{r}"
            if log.exists():
                out += f"\n--- workerlog.{r} ---\n" + log.read_text()[-2000:]
    assert proc.returncode == 0, out[-6000:]

    results = {}
    for r in range(4):
        with open(tmp_path / f"rank{r}.json") as f:
            results[r] = json.load(f)
        assert results[r]["world"] == 4
        # same-host clocks: the handshake's measured offsets are small
        assert abs(results[r]["clock_offset_s"]) < 0.5, results[r]
        # every rank produced a schema-shaped rankstat
        assert results[r]["rankstat"]["world_size"] == 4
    assert results[0]["clock_offset_s"] == 0.0  # rank 0 IS the reference
    # the injected delay is visible in the straggler's own telemetry
    assert results[straggler]["rankstat"]["step_time_p50_s"] > 0.25
    others = [results[r]["rankstat"]["step_time_p50_s"]
              for r in range(4) if r != straggler]
    assert max(others) < 0.25, others

    # rank 0's gather named the right rank, and ONLY that rank
    rank0_recs = [json.loads(l) for l in
                  (tmp_path / "metrics.rank0.jsonl").read_text()
                  .splitlines() if l.strip()]
    stragglers = [r for r in rank0_recs
                  if r.get("kind") == "event" and
                  r.get("event") == "straggler"]
    assert stragglers, "no straggler event in rank 0's metrics"
    assert {r["straggler_rank"] for r in stragglers} == \
        {straggler}, stragglers
    assert stragglers[0]["lag_s"] > 0.2

    # schema-valid rankstat stream on every rank
    tool = _load_tool("check_metrics_schema")
    for r in range(4):
        mfile = tmp_path / f"metrics.rank{r}.jsonl"
        recs = [json.loads(l) for l in mfile.read_text().splitlines()
                if l.strip()]
        assert sum(1 for x in recs if x.get("kind") == "rankstat") >= 2
        assert sum(1 for x in recs if x.get("kind") == "collective") >= 1
        assert tool.validate_file(str(mfile)) == [], mfile
    # the launch-propagated gather dir holds all 4 snapshots
    gather = logdir / "rankstat"
    assert {f"rankstat.{r}.json" for r in range(4)} <= \
        set(os.listdir(gather))

    # merged multi-rank trace: valid, clock-aligned, with per-rank pids
    mt = _load_tool("merge_traces")
    merged = str(tmp_path / "merged.json")
    assert mt.main(["-o", merged] +
                   [str(tmp_path / f"trace.rank{r}.json")
                    for r in range(4)]) == 0
    payload = json.load(open(merged))
    assert payload["otherData"]["clock_aligned"] is True
    offs = payload["otherData"]["clock_offsets_s"]
    assert len(offs) == 4 and all(abs(o) < 0.5 for o in offs)
    assert tool.validate_file(merged) == []
    pids = {e.get("pid") for e in payload["traceEvents"]}
    assert len(pids) >= 4  # one process group per rank
