"""paddle.utils. Parity: python/paddle/utils/__init__.py."""
import importlib
import os
import sys

__all__ = ["deprecated", "run_check", "try_import", "require_version",
           "unique_name", "download", "cpp_extension"]


def deprecated(update_to="", since="", reason="", level=0):
    def deco(fn):
        return fn
    return deco


def run_check():
    from .install_check import run_check as _full_check
    return _full_check()


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"module {module_name} not found") \
            from e


def require_version(min_version, max_version=None):
    return True


class _UniqueNameGenerator:
    def __init__(self):
        self.ids = {}

    def __call__(self, prefix):
        idx = self.ids.setdefault(prefix, 0)
        self.ids[prefix] += 1
        return f"{prefix}_{idx}"


_generator = _UniqueNameGenerator()


class unique_name:
    @staticmethod
    def generate(prefix):
        return _generator(prefix)

    @staticmethod
    def guard(new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def g():
            yield
        return g()


from . import download  # noqa: E402 — real submodule (cache+md5+unpack)
from . import dlpack  # noqa: E402
from . import install_check  # noqa: E402
