"""paddle.sparse — COO/CSR tensors. Parity: paddle/pten/core/sparse_coo_
tensor.h / sparse_csr_tensor.h + python/paddle/incubate/sparse.

TPU-native: sparse storage lives as index/value arrays; compute densifies
through scatter/gather or uses jax.experimental.sparse (BCOO) for matmul —
XLA has no native sparse MXU path, so the contract is identical semantics
with dense-speed fallbacks.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor"]


class SparseCooTensor:
    def __init__(self, indices, values, shape):
        self.indices = indices if isinstance(indices, Tensor) \
            else Tensor(np.asarray(indices))
        self.values = values if isinstance(values, Tensor) \
            else Tensor(np.asarray(values))
        self.shape = list(shape)

    def to_dense(self):
        def fn(idx, vals):
            out = jnp.zeros(tuple(self.shape), vals.dtype)
            return out.at[tuple(idx[i] for i in range(idx.shape[0]))].add(
                vals)
        return apply_op(fn, self.indices, self.values)

    def coalesce(self):
        idx = self.indices.numpy()
        vals = self.values.numpy()
        flat = np.ravel_multi_index(idx, self.shape)
        uniq, inv = np.unique(flat, return_inverse=True)
        new_vals = np.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
        np.add.at(new_vals, inv, vals)
        new_idx = np.stack(np.unravel_index(uniq, self.shape))
        return SparseCooTensor(new_idx, new_vals, self.shape)

    def nnz(self):
        return self.values.shape[0]

    def matmul(self, other):
        dense = self.to_dense()
        from ..tensor.linalg import matmul as mm
        return mm(dense, other)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()})")


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows = crows if isinstance(crows, Tensor) \
            else Tensor(np.asarray(crows))
        self.cols = cols if isinstance(cols, Tensor) \
            else Tensor(np.asarray(cols))
        self.values = values if isinstance(values, Tensor) \
            else Tensor(np.asarray(values))
        self.shape = list(shape)

    def to_dense(self):
        crows = self.crows.numpy()
        cols = self.cols.numpy()
        vals = self.values.numpy()
        out = np.zeros(tuple(self.shape), vals.dtype)
        for r in range(self.shape[0]):
            lo, hi = crows[r], crows[r + 1]
            out[r, cols[lo:hi]] = vals[lo:hi]
        return Tensor(out)

    def to_coo(self):
        crows = self.crows.numpy()
        rows = np.repeat(np.arange(self.shape[0]), np.diff(crows))
        return SparseCooTensor(np.stack([rows, self.cols.numpy()]),
                               self.values, self.shape)

    def nnz(self):
        return self.values.shape[0]


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    if shape is None:
        idx = np.asarray(indices.numpy() if isinstance(indices, Tensor)
                         else indices)
        shape = (idx.max(axis=1) + 1).tolist()
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(crows, cols, values, shape)
