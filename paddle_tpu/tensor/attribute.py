"""Tensor attribute ops. Parity: python/paddle/tensor/attribute.py."""
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op


def shape(x):
    return Tensor(jnp.asarray(x.shape, dtype=jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(x.ndim, dtype=jnp.int32))


def is_complex(x):
    return jnp.issubdtype(x.value.dtype, jnp.complexfloating)


def is_integer(x):
    return jnp.issubdtype(x.value.dtype, jnp.integer)


def is_floating_point(x):
    return jnp.issubdtype(x.value.dtype, jnp.floating)


def real(x, name=None):
    return apply_op(jnp.real, x)


def imag(x, name=None):
    return apply_op(jnp.imag, x)
