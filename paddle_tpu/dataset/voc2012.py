"""paddle.dataset.voc2012 — Pascal VOC2012 segmentation corpus, legacy
reader API.

Parity: /root/reference/python/paddle/dataset/voc2012.py (VOCtrainval
tar; samples are (jpeg image CHW uint8 array, segmentation label HW)).
"""
import io
import os
import tarfile

import numpy as np

from .common import DATA_HOME

__all__ = []

SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"


def _tar_path():
    return os.path.join(DATA_HOME, "voc2012",
                        "VOCtrainval_11-May-2012.tar")


def reader_creator(filename, sub_name):
    def reader():
        from PIL import Image
        with tarfile.open(filename) as tf:
            names = tf.extractfile(
                SET_FILE.format(sub_name)).read().decode().split()
            for name in names:
                img = np.array(Image.open(io.BytesIO(
                    tf.extractfile(DATA_FILE.format(name)).read())))
                label = np.array(Image.open(io.BytesIO(
                    tf.extractfile(LABEL_FILE.format(name)).read())))
                yield img.transpose(2, 0, 1), label

    return reader


def train():
    return reader_creator(_tar_path(), "trainval")


def test():
    return reader_creator(_tar_path(), "train")


def val():
    return reader_creator(_tar_path(), "val")


def fetch():
    from .common import download
    download("http://host.robots.ox.ac.uk/pascal/VOC/voc2012/"
             "VOCtrainval_11-May-2012.tar", "voc2012", None)
