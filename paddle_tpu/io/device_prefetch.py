"""Device prefetch ring — H2D transfers overlapped with device compute.

The DataLoader's thread/process workers hide host-side batch *assembly*;
this module hides the last hop: `jax.device_put` of the assembled batch
onto the accelerator, placed with the train step's input shardings. A
background thread stages up to `depth` batches ahead of the consumer
while step k computes, so the steady-state step loop pops an
already-resident batch in ~0 time (the `dataloader.next` span goes flat)
and the device never waits on an H2D copy.

    ring = DevicePrefetchRing(loader_iter, depth=2,
                              sharding_fn=step.input_sharding)
    for batch in ring:           # Tensor leaves, already on device
        loss = step(*batch)      # _prep sees the sharding and skips the put

or, one level up, `DataLoader(..., prefetch_to_device=2)` — the hapi
`Model.fit` wires the step's `input_sharding` in automatically.

Telemetry: per-batch staging lands as the "prefetch.h2d" span, real
staging traffic (host arrays moved to device, or device arrays re-placed
to the step's sharding — NOT copy-free pass-throughs of already-placed
batches) in the `prefetch.h2d_bytes` counter, and the ring's fill level
in the `prefetch.depth` gauge (a gauge pinned at 0 means the consumer is
data-bound, not compute-bound).
"""
import queue
import threading
import time

import numpy as np
import jax

from ..framework.core import Tensor
from ..profiler import statistic as _stat
from ..profiler import monitor as _monitor
from ..profiler import mem_observatory as _mobs

__all__ = ["DevicePrefetchRing", "device_prefetch_iterator"]

_END = object()


class _Failure:
    """Carries a producer-side exception to the consumer thread."""

    def __init__(self, exc):
        self.exc = exc


def _stage(x, sharding_fn):
    """device_put every array leaf of a batch structure (list/tuple/dict
    of Tensors / numpy arrays), placed per the step's input sharding;
    non-array leaves (strings, ints) pass through untouched."""
    if isinstance(x, Tensor):
        return Tensor(_put(x.value, sharding_fn))
    if isinstance(x, (list, tuple)):
        return [_stage(v, sharding_fn) for v in x]
    if isinstance(x, dict):
        return {k: _stage(v, sharding_fn) for k, v in x.items()}
    if isinstance(x, (np.ndarray, jax.Array)):
        return Tensor(_put(x, sharding_fn))
    return x


def _put(a, sharding_fn):
    """One staging hop, honestly accounted: a host (numpy) leaf moves to
    its target placement in a single device_put (the sharding_fn only
    reads ndim/shape, which numpy has); a device-resident jax array is
    re-placed only when its sharding differs from the target, and passes
    through FREE otherwise — so `prefetch.h2d_bytes` counts real staging
    traffic, not copy-free commits of already-resident batches."""
    sh = sharding_fn(a) if sharding_fn is not None else None
    if isinstance(a, jax.Array):
        if sh is None or getattr(a, "sharding", None) == sh:
            return a
        a = jax.device_put(a, sh)
    else:
        a = np.asarray(a)  # hot-sync-ok: host ndarray normalization, not a device read
        a = jax.device_put(a, sh) if sh is not None else jax.device_put(a)
    try:
        _monitor.counter("prefetch.h2d_bytes").inc(int(a.nbytes))
    except (AttributeError, TypeError):
        pass
    return a


class DevicePrefetchRing:
    """Bounded ring of device-resident batches, filled by a background
    thread. `depth` bounds device memory: at most `depth` staged batches
    queue ahead of the consumer, plus the one the producer is holding —
    size depth for HBM assuming depth+1 extra batches resident. Iterate
    it like any batch iterator; `close()` (or abandonment via
    `device_prefetch_iterator`) stops the producer promptly."""

    def __init__(self, source, depth=2, sharding_fn=None):
        self.depth = max(1, int(depth))
        self._sharding_fn = sharding_fn
        self._q = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._producer, args=(iter(source),),
            name="device-prefetch", daemon=True)
        self._thread.start()

    def _producer(self, it):
        try:
            while not self._stop.is_set():
                try:
                    batch = next(it)
                except StopIteration:
                    break
                t0 = time.perf_counter()
                staged = _stage(batch, self._sharding_fn)
                _stat.record_span("prefetch.h2d",
                                  time.perf_counter() - t0)
                # memory-observatory attribution: per-array weakrefs to
                # the staged leaves — when the consumer drops the batch
                # the tag's bytes fall to zero by themselves
                _mobs.register_arrays(
                    "prefetch",
                    [x.value if isinstance(x, Tensor) else x
                     for x in jax.tree.leaves(staged)
                     if hasattr(x, "nbytes")
                     or isinstance(x, Tensor)])
                if not self._offer(staged):
                    return
                _monitor.gauge("prefetch.depth").set(self._q.qsize())
        except Exception as e:  # surface in the consumer, not a dead thread
            self._offer(_Failure(e))
            return
        self._offer(_END)

    def _offer(self, item):
        """put() that stays responsive to close(); False when stopped."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self._q.get()
        _monitor.gauge("prefetch.depth").set(self._q.qsize())
        if item is _END:
            self._stop.set()
            raise StopIteration
        if isinstance(item, _Failure):
            self._stop.set()
            raise item.exc
        return item

    def close(self):
        """Stop the producer and release anything it staged."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass

    def __del__(self):
        self._stop.set()


def device_prefetch_iterator(source, depth=2, sharding_fn=None):
    """Generator wrapper around DevicePrefetchRing that closes the ring
    when iteration ends OR is abandoned (break / GC) — the form
    DataLoader and bench.py consume."""
    ring = DevicePrefetchRing(source, depth=depth, sharding_fn=sharding_fn)
    try:
        for batch in ring:
            yield batch
    finally:
        ring.close()
