"""Collective communication API.

Parity: python/paddle/distributed/collective.py. Two modes:

- **SPMD (inside shard_map/jit over the mesh)**: wrappers over
  lax.psum / all_gather / ppermute / all_to_all keyed by mesh axis name.
  This is the TPU path — XLA emits ICI collectives.
- **Eager single-controller**: collectives act on a Tensor sharded over a
  mesh axis (all ranks' data is one array); e.g. all_reduce sums shards.
  This keeps dygraph test code from the reference runnable verbatim.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..framework.core import Tensor, apply_op
from ..profiler import statistic as _stat
from ..profiler import monitor as _monitor
from ..profiler import dist_observatory as _dobs
from .env import get_mesh


def _payload_bytes(args):
    """Sum the byte size of every Tensor/array (or list of them) in
    `args`. Works on tracers too — shape/dtype are known under trace."""
    nbytes = 0
    stack = list(args)
    while stack:
        t = stack.pop()
        if isinstance(t, (list, tuple)):
            stack.extend(t)
            continue
        a = t.value if isinstance(t, Tensor) else t
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is None or dtype is None:
            continue
        try:
            nbytes += int(np.prod(shape)) * np.dtype(dtype).itemsize
        except (TypeError, ValueError):
            continue
    return nbytes


def _any_traced(args):
    """Whether any Tensor/array (or list of them) in `args` is a jax
    tracer — i.e. this collective call is a trace-time INSERTION, not
    an eager execution (its host wall time is trace overhead, not
    communication)."""
    stack = list(args)
    while stack:
        t = stack.pop()
        if isinstance(t, (list, tuple)):
            stack.extend(t)
            continue
        a = t.value if isinstance(t, Tensor) else t
        if isinstance(a, jax.core.Tracer):
            return True
    return False


def _group_label(args, kwargs):
    """The process-group label of one collective call: an explicit
    Group's axis wins, else the first string/tuple positional (the SPMD
    functional collectives pass the mesh axis name there), else the
    default 'dp' axis."""
    g = kwargs.get("group")
    for cand in ([g] if g is not None else []) + list(args):
        if isinstance(cand, Group):
            return str(cand.axis)
        if isinstance(cand, str):
            return cand
        if isinstance(cand, tuple) and cand and all(
                isinstance(c, str) for c in cand):
            return "+".join(cand)
    return "dp"


def _instrumented(fn=None, *, payload=None):
    """Telemetry wrapper for a collective: per-kind call + payload-bytes
    counters, a host span, and the distributed observatory's rollup +
    sampled `kind:"collective"` record (op, group, bytes, wall_s,
    bus-bandwidth GB/s — profiler/dist_observatory.py). Called under
    trace (inside jit/shard_map) this tallies collectives INSERTED per
    traced program — once per compile, not per execution (the record is
    flagged `traced`); eager calls count one-for-one with real wall
    time.

    `payload` selects which positional args carry the transferred data
    (args -> sequence) for APIs that also take an output placeholder
    (reduce_scatter's dst tensor, alltoall's out list) — counting those
    would overstate the traffic by the output size."""
    if fn is None:
        return lambda f: _instrumented(f, payload=payload)
    import functools
    import time
    kind = fn.__name__

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        # bytes BEFORE the call: all_gather/alltoall mutate their list
        # arguments, so counting afterwards would tally outputs too
        sel = payload(args) if payload else args
        nbytes = _payload_bytes(sel)
        traced = _any_traced(sel)
        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            dt = time.perf_counter() - t0
            _stat.record_span(f"collective.{kind}", dt)
            _monitor.counter(f"collective.{kind}.calls").inc()
            _monitor.counter(f"collective.{kind}.bytes").inc(nbytes)
            _dobs.record_collective(kind, _group_label(args, kwargs),
                                    nbytes, dt, traced=traced)
    return wrapper

__all__ = ["ReduceOp", "all_reduce", "all_gather", "broadcast", "reduce",
           "scatter", "alltoall", "send", "recv", "reduce_scatter",
           "split", "new_group", "wait", "get_group",
           "psum", "pmean", "pmax", "all_gather_axis", "ppermute",
           "all_to_all_axis", "axis_index"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    def __init__(self, ranks, axis="dp", gid=0):
        self.ranks = ranks
        self.axis = axis
        self.id = gid
        self.nranks = len(ranks) if ranks else 1

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1


_groups = {0: Group(None, "dp", 0)}


def new_group(ranks=None, backend=None, axis="dp"):
    gid = max(_groups) + 1
    g = Group(ranks, axis, gid)
    _groups[gid] = g
    return g


def get_group(gid=0):
    return _groups.get(gid)


def _in_trace(x):
    return isinstance(x, jax.core.Tracer)


# ---- SPMD functional collectives (use inside shard_map) ----------------
@_instrumented
def psum(x, axis):
    return lax.psum(x, axis)


@_instrumented
def pmean(x, axis):
    return lax.pmean(x, axis)


@_instrumented
def pmax(x, axis):
    return lax.pmax(x, axis)


@_instrumented
def all_gather_axis(x, axis, tiled=True, gather_dim=0):
    return lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


@_instrumented
def ppermute(x, axis, perm):
    return lax.ppermute(x, axis, perm)


@_instrumented
def all_to_all_axis(x, axis, split_axis, concat_axis):
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def axis_index(axis):
    return lax.axis_index(axis)


# ---- Eager controller-level API ---------------------------------------
def _axis_of(group):
    if isinstance(group, Group):
        return group.axis
    return "dp"


@_instrumented
def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Inside shard_map: psum over the group axis. Eager: identity on the
    single controller (the mesh owns all shards already)."""
    if _in_trace(tensor.value if isinstance(tensor, Tensor) else tensor):
        ax = _axis_of(group)
        fn = {ReduceOp.SUM: lax.psum, ReduceOp.MAX: lax.pmax,
              ReduceOp.MIN: lax.pmin,
              ReduceOp.AVG: lax.pmean}[op]
        if isinstance(tensor, Tensor):
            out = apply_op(lambda a: fn(a, ax), tensor)
            tensor._bind(out._slot)
            return tensor
        return fn(tensor, ax)
    return tensor


@_instrumented(payload=lambda a: a[1:2])  # the gathered tensor;
def all_gather(tensor_list, tensor, group=None, sync_op=True):
    if _in_trace(tensor.value if isinstance(tensor, Tensor) else tensor):
        ax = _axis_of(group)
        arr = tensor.value if isinstance(tensor, Tensor) else tensor
        g = lax.all_gather(arr, ax)
        n = g.shape[0]
        for i in range(n):
            tensor_list.append(Tensor(g[i]))
        return tensor_list
    tensor_list.append(tensor)
    return tensor_list


@_instrumented
def broadcast(tensor, src=0, group=None, sync_op=True):
    return tensor  # single-controller: every device sees the same program


@_instrumented
def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # the UNinstrumented all_reduce body: one user call must count as
    # one collective, not as a reduce plus an all_reduce
    return all_reduce.__wrapped__(tensor, op, group, sync_op)


@_instrumented(payload=lambda a: a[1:2])  # the scattered shards
def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        tensor._bind(tensor_list[0]._slot)
    return tensor


@_instrumented(payload=lambda a: a[0:1])  # the input shards
def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True):
    out_tensor_list.extend(in_tensor_list)
    return out_tensor_list


@_instrumented(payload=lambda a: a[1:2])  # the reduced shards
def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    if _in_trace(tensor_list[0].value):
        ax = _axis_of(group)
        stacked = jnp.stack([t.value for t in tensor_list])
        out = lax.psum_scatter(stacked, ax, scatter_dimension=0, tiled=False)
        tensor._bind(Tensor(out)._slot)
        return tensor
    tensor._bind(tensor_list[0]._slot)
    return tensor


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv is expressed as lax.ppermute inside "
        "shard_map on TPU (see meta_parallel.pipeline_parallel)")


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "point-to-point send/recv is expressed as lax.ppermute inside "
        "shard_map on TPU (see meta_parallel.pipeline_parallel)")


@_instrumented
def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and not _in_trace(tensor.value):
        jax.block_until_ready(tensor.value)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Parity: paddle.distributed.split — model-parallel embedding/linear
    helper. Routes to the meta_parallel layers."""
    from .meta_parallel.parallel_layers.mp_layers import (
        ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1],
                                      weight_attr=weight_attr,
                                      has_bias=bias_attr is not False)
        else:
            layer = ColumnParallelLinear(size[0], size[1],
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        return layer(x)
    raise ValueError(f"unknown split operation {operation}")
