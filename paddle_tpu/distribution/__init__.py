"""paddle.distribution. Parity: python/paddle/distribution/."""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op
from ..framework.random import split_key

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Beta",
           "Dirichlet", "Multinomial", "ExponentialFamily",
           "kl_divergence", "register_kl"]


def _arr(x):
    if isinstance(x, Tensor):
        return x.value
    return jnp.asarray(np.asarray(x, dtype=np.float32))


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(self.log_prob(value).value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        eps = jax.random.normal(split_key(), shape)
        return Tensor(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _arr(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var) -
                      jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        out = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return Tensor(jnp.broadcast_to(out, self.batch_shape))

    def probs(self, value):
        return self.prob(value)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(split_key(), shape)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def probs(self, value):
        """Density at `value` (reference uniform.py probs)."""
        return self.prob(value)

    def entropy(self):
        return Tensor(jnp.broadcast_to(jnp.log(self.high - self.low),
                                       self.batch_shape))


class Categorical(Distribution):
    """Reference contract (categorical.py), mirrored exactly — it is
    deliberately split-brained about the constructor argument:
    `probs(value)`/`log_prob(value)` treat it as unnormalized
    probability WEIGHTS (divide by the sum; the probs doc example's
    expected values pin this down), while `sample`/`entropy`/
    `kl_divergence` treat it as LOGITS (softmax via _logits_to_probs
    in sample, max-shift + exp/z in entropy/kl). We store the raw
    input, like the reference's self.logits."""

    def __init__(self, logits=None, probs=None, name=None):
        self.logits = _arr(logits if logits is not None else probs)
        super().__init__(self.logits.shape[:-1])

    def probs(self, value):
        """Probability of the selected category indices: weights/sum
        (a METHOD taking `value`; for a single 1-D distribution the
        result has value's shape)."""
        w = self.logits / jnp.sum(self.logits, -1, keepdims=True)
        idx = _arr(value).astype(jnp.int32)
        if not self.batch_shape:  # one distribution: index categories
            return Tensor(w[idx])
        return Tensor(jnp.take_along_axis(w, idx[..., None], -1)[..., 0])

    def log_prob(self, value):
        return Tensor(jnp.log(self.probs(value).value))

    def sample(self, shape=()):
        # jax.random.categorical samples ∝ exp(logit) — exactly the
        # reference's multinomial(softmax(logits)) path
        shape = tuple(shape)
        out = jax.random.categorical(split_key(), self.logits,
                                     shape=shape + self.batch_shape)
        return Tensor(out.astype(jnp.int64))

    def _log_softmax(self):
        return self.logits - jax.scipy.special.logsumexp(
            self.logits, -1, keepdims=True)

    def entropy(self):
        lp = self._log_softmax()
        return Tensor(-jnp.sum(jnp.exp(lp) * lp, -1))

    def kl_divergence(self, other):
        lp = self._log_softmax()
        lq = other._log_softmax()
        return Tensor(jnp.sum(jnp.exp(lp) * (lp - lq), -1))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s ** 2 * (s + 1)))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        out = jax.random.beta(split_key(), self.alpha, self.beta, shape)
        return Tensor(out)

    def log_prob(self, value):
        v = _arr(value)
        lg = jax.scipy.special.gammaln
        lbeta = lg(self.alpha) + lg(self.beta) - lg(self.alpha + self.beta)
        return Tensor((self.alpha - 1) * jnp.log(v) +
                      (self.beta - 1) * jnp.log1p(-v) - lbeta)

    def entropy(self):
        lg = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        a, b = self.alpha, self.beta
        lbeta = lg(a) + lg(b) - lg(a + b)
        return Tensor(lbeta - (a - 1) * dg(a) - (b - 1) * dg(b) +
                      (a + b - 2) * dg(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _arr(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        c = self.concentration
        return Tensor(c / jnp.sum(c, -1, keepdims=True))

    def sample(self, shape=()):
        out = jax.random.dirichlet(split_key(), self.concentration,
                                   tuple(shape) + self.batch_shape)
        return Tensor(out)

    def log_prob(self, value):
        v = _arr(value)
        c = self.concentration
        lg = jax.scipy.special.gammaln
        norm = jnp.sum(lg(c), -1) - lg(jnp.sum(c, -1))
        return Tensor(jnp.sum((c - 1) * jnp.log(v), -1) - norm)

    def entropy(self):
        c = self.concentration
        lg = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        c0 = jnp.sum(c, -1)
        k = c.shape[-1]
        lnB = jnp.sum(lg(c), -1) - lg(c0)
        return Tensor(lnB + (c0 - k) * dg(c0) -
                      jnp.sum((c - 1) * dg(c), -1))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        p = _arr(probs)
        self.probs_arr = p / jnp.sum(p, -1, keepdims=True)
        super().__init__(p.shape[:-1], p.shape[-1:])

    def sample(self, shape=()):
        cat = jax.random.categorical(
            split_key(), jnp.log(self.probs_arr),
            shape=(self.total_count,) + tuple(shape) + self.batch_shape)
        k = self.probs_arr.shape[-1]
        onehot = jax.nn.one_hot(cat, k)
        return Tensor(jnp.sum(onehot, axis=0))

    def log_prob(self, value):
        v = _arr(value)
        lg = jax.scipy.special.gammaln
        logits = jnp.log(self.probs_arr)
        return Tensor(lg(jnp.asarray(self.total_count + 1.0)) -
                      jnp.sum(lg(v + 1), -1) + jnp.sum(v * logits, -1))


class ExponentialFamily(Distribution):
    pass


_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    def deco(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is not None:
        return fn(p, q)
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = (p.scale / q.scale) ** 2
        t1 = ((p.loc - q.loc) / q.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        return p.kl_divergence(q)
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))
    if isinstance(p, Beta) and isinstance(q, Beta):
        lg = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
        t = lg(a1 + b1) - lg(a1) - lg(b1) - \
            (lg(a2 + b2) - lg(a2) - lg(b2))
        return Tensor(t + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1) +
                      (a2 - a1 + b2 - b1) * dg(a1 + b1))
    if isinstance(p, Dirichlet) and isinstance(q, Dirichlet):
        lg = jax.scipy.special.gammaln
        dg = jax.scipy.special.digamma
        c1, c2 = p.concentration, q.concentration
        s1 = jnp.sum(c1, -1)
        t = lg(s1) - jnp.sum(lg(c1), -1) - \
            (lg(jnp.sum(c2, -1)) - jnp.sum(lg(c2), -1))
        return Tensor(t + jnp.sum(
            (c1 - c2) * (dg(c1) - dg(s1)[..., None]), -1))
    raise NotImplementedError(
        f"kl_divergence({type(p).__name__}, {type(q).__name__})")
