"""paddle.cost_model — per-op/per-program cost estimation.

Parity: /root/reference/python/paddle/cost_model/cost_model.py. The
reference ships a static GPU benchmark json and a profiler hook; here
the numbers come from the live backend — `profile_measure` walls-clock
an Executor run, and the static table is measured on first use (XLA
compile + run of each op at a reference size) then cached, so the data
matches the attached chip instead of somebody else's GPU.
"""
import time

import numpy as np

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        self._static_cost_data = None

    def build_program(self):
        """A tiny fc+mean program pair, mirroring the reference demo."""
        import paddle_tpu as paddle
        from paddle_tpu import static
        paddle.enable_static()
        main_program = static.Program()
        startup_program = static.Program()
        with static.program_guard(main_program=main_program,
                                  startup_program=startup_program):
            data = static.data(name="X", shape=[None, 1],
                               dtype="float32")
            hidden = static.nn.fc(data, 10)
            loss = paddle.mean(hidden)
            paddle.optimizer.SGD(learning_rate=0.01).minimize(loss)
        return startup_program, main_program

    def profile_measure(self, startup_program, main_program,
                        device="tpu", fetch_cost_list=("time",)):
        """Run the program once for compile, then time a second run.
        Returns {"time": seconds, ...} for the requested costs."""
        import paddle_tpu as paddle
        from paddle_tpu import static
        exe = static.Executor(paddle.set_device(
            device if device != "gpu" else "tpu"))
        exe.run(startup_program)
        x = np.random.random(size=(10, 1)).astype("float32")
        exe.run(main_program, feed={"X": x}, fetch_list=[])
        t0 = time.perf_counter()
        exe.run(main_program, feed={"X": x}, fetch_list=[])
        dt = time.perf_counter() - t0
        cost = {}
        for item in fetch_cost_list:
            if item == "time":
                cost["time"] = dt
        return cost

    _OP_BENCH = {
        # op name -> builder returning (fn(a), reference input)
        "matmul": lambda jnp: ((lambda a: a @ a), jnp.ones((256, 256))),
        "relu": lambda jnp: ((lambda a: jnp.maximum(a, 0)),
                             jnp.ones((256, 256))),
        "softmax": lambda jnp: ((lambda a: __import__("jax").nn.softmax(a)),
                                jnp.ones((256, 256))),
        "elementwise_add": lambda jnp: ((lambda a: a + a),
                                        jnp.ones((256, 256))),
        "mean": lambda jnp: ((lambda a: jnp.mean(a)),
                             jnp.ones((256, 256))),
    }

    def static_cost_data(self):
        """Measure the op table once on the live backend; entries match
        the reference schema (op/config/time keys)."""
        if self._static_cost_data is not None:
            return self._static_cost_data
        import jax
        import jax.numpy as jnp
        table = []
        for name, builder in self._OP_BENCH.items():
            fn, x = builder(jnp)
            jit_fn = jax.jit(fn)
            jax.block_until_ready(jit_fn(x))  # compile
            t0 = time.perf_counter()
            for _ in range(10):
                out = jit_fn(x)
            jax.block_until_ready(out)
            dt_ms = (time.perf_counter() - t0) / 10 * 1e3

            jit_bwd = jax.jit(jax.grad(lambda a: jnp.sum(fn(a))))
            jax.block_until_ready(jit_bwd(x))
            t0 = time.perf_counter()
            for _ in range(10):
                g = jit_bwd(x)
            jax.block_until_ready(g)
            bwd_ms = (time.perf_counter() - t0) / 10 * 1e3
            table.append({
                "op": name,
                "config": "float32 [256, 256]",
                "paddle_gpu_time": dt_ms,
                "paddle_gpu_time_backward": bwd_ms,
            })
        self._static_cost_data = table
        return table

    def get_static_op_time(self, op_name, forward=True,
                           dtype="float32"):
        if op_name is None:
            raise ValueError("op_name should not be empty when you "
                             "want to get static op time")
        if self._static_cost_data is None:
            self.static_cost_data()
        op_cost = {}
        for op_data in self._static_cost_data:
            if op_data["op"] == op_name and dtype in op_data["config"]:
                key = ("paddle_gpu_time" if forward
                       else "paddle_gpu_time_backward")
                op_cost["op_time"] = op_data[key]
                op_cost["config"] = op_data["config"]
        return op_cost
