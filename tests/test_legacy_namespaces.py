"""paddle.{compat, callbacks, reader, dataset, cost_model} + inference
utilities — the legacy top-level namespaces a 2.x reference user still
imports (reference: python/paddle/{compat.py, callbacks.py, reader/,
dataset/, cost_model/}).

Dataset parsers are fed synthetic files in the OFFICIAL formats
(idx-gzip, ::-separated dat, tab-separated parallel text) so the
parsing is proven without network access.
"""
import gzip
import io
import os
import struct
import tarfile
import zipfile

import numpy as np
import pytest

import paddle_tpu as paddle


# ---------------------------------------------------------------- compat
def test_compat_text_bytes_round_trip():
    from paddle_tpu import compat
    assert compat.to_text(b"hello") == "hello"
    assert compat.to_bytes("hello") == b"hello"
    nested = {"k": [b"a", b"b"], "v": {b"x"}}
    out = compat.to_text(nested)
    assert out["k"] == ["a", "b"] and out["v"] == {"x"}
    lst = [b"a", [b"b"]]
    assert compat.to_text(lst, inplace=True) is lst
    assert lst == ["a", ["b"]]


def test_compat_round_half_away_from_zero():
    from paddle_tpu import compat
    assert compat.round(0.5) == 1.0
    assert compat.round(-0.5) == -1.0
    assert compat.round(2.675, 2) == 2.68
    assert compat.floor_division(7, 2) == 3
    assert compat.get_exception_message(ValueError("boom")) == "boom"


# ------------------------------------------------------------- callbacks
def test_callbacks_namespace_matches_hapi():
    import paddle_tpu.callbacks as cb
    from paddle_tpu.hapi.callbacks import EarlyStopping
    assert cb.EarlyStopping is EarlyStopping
    for name in ["Callback", "ProgBarLogger", "ModelCheckpoint",
                 "VisualDL", "LRScheduler", "EarlyStopping",
                 "ReduceLROnPlateau"]:
        assert hasattr(cb, name)


# ---------------------------------------------------------------- reader
def test_reader_decorators_compose():
    from paddle_tpu import reader

    def r():
        return iter(range(10))

    assert list(reader.firstn(r, 3)()) == [0, 1, 2]
    assert list(reader.cache(r)()) == list(range(10))
    assert list(reader.chain(r, r)()) == list(range(10)) * 2
    assert list(reader.map_readers(lambda a, b: a + b, r, r)()) == [
        2 * i for i in range(10)]
    assert sorted(reader.shuffle(r, 4)()) == list(range(10))
    assert list(reader.buffered(r, 2)()) == list(range(10))
    got = list(reader.compose(r, r)())
    assert got[0] == (0, 0) and len(got) == 10


def test_reader_compose_alignment_check():
    from paddle_tpu import reader

    def short():
        return iter(range(3))

    def long():
        return iter(range(5))

    with pytest.raises(reader.ComposeNotAligned):
        list(reader.compose(short, long)())
    # unchecked mode just zips to the shorter
    assert len(list(reader.compose(short, long,
                                   check_alignment=False)())) == 3


def test_reader_xmap_ordered_and_unordered():
    from paddle_tpu import reader

    def r():
        return iter(range(20))

    ordered = list(reader.xmap_readers(lambda x: x * 2, r, 3, 4,
                                       order=True)())
    assert ordered == [x * 2 for x in range(20)]
    unordered = list(reader.xmap_readers(lambda x: x * 2, r, 3, 4)())
    assert sorted(unordered) == [x * 2 for x in range(20)]


def test_multiprocess_reader():
    from paddle_tpu import reader
    got = sorted(reader.multiprocess_reader(
        [_mp_reader_a, _mp_reader_b], queue_size=8)())
    assert got == list(range(8))


def _mp_reader_a():
    return iter(range(4))


def _mp_reader_b():
    return iter(range(4, 8))


# --------------------------------------------------------------- dataset
def _write_mnist(home, mode, n=4):
    d = os.path.join(home, "mnist")
    os.makedirs(d, exist_ok=True)
    from paddle_tpu.vision.datasets import MNIST
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, size=(n, 28, 28), dtype=np.uint8)
    labels = (np.arange(n) % 10).astype(np.uint8)
    with gzip.open(os.path.join(d, MNIST.IMG[mode]), "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(os.path.join(d, MNIST.LAB[mode]), "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return imgs, labels


@pytest.fixture
def data_home(tmp_path, monkeypatch):
    home = str(tmp_path / "dataset")
    os.makedirs(home, exist_ok=True)
    import paddle_tpu.dataset.common as common
    import paddle_tpu.vision.datasets as vd
    monkeypatch.setattr(common, "DATA_HOME", home)
    monkeypatch.setattr(vd, "DATA_HOME", home)
    for mod in ("mnist", "imdb", "imikolov", "movielens", "wmt14",
                "wmt16", "conll05", "uci_housing", "voc2012",
                "flowers"):
        m = __import__(f"paddle_tpu.dataset.{mod}", fromlist=[mod])
        if hasattr(m, "DATA_HOME"):
            monkeypatch.setattr(m, "DATA_HOME", home)
    return home


def test_dataset_mnist_reader(data_home, monkeypatch):
    import paddle_tpu.dataset as dataset
    imgs, labels = _write_mnist(data_home, "train")
    samples = list(dataset.mnist.train()())
    assert len(samples) == 4
    img, label = samples[0]
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    np.testing.assert_allclose(
        img, imgs[0].reshape(-1).astype(np.float32) / 255 * 2 - 1)
    assert label == int(labels[0])


def test_dataset_imdb_build_dict_and_reader(data_home):
    import paddle_tpu.dataset.imdb as imdb
    d = os.path.join(data_home, "imdb")
    os.makedirs(d, exist_ok=True)
    docs = {"aclImdb/train/pos/0_9.txt": b"a great great movie!",
            "aclImdb/train/neg/0_1.txt": b"a terrible movie.",
            "aclImdb/test/pos/0_8.txt": b"great fun",
            "aclImdb/test/neg/0_2.txt": b"boring"}
    with tarfile.open(os.path.join(d, "aclImdb_v1.tar.gz"), "w:gz") as tf:
        for name, body in docs.items():
            info = tarfile.TarInfo(name)
            info.size = len(body)
            tf.addfile(info, io.BytesIO(body))
    w = imdb.word_dict(cutoff=0)
    assert "great" in w and "<unk>" in w
    samples = list(imdb.train(w)())
    assert len(samples) == 2
    ids, label = samples[0]
    assert label in (0, 1) and all(isinstance(i, int) for i in ids)


def test_dataset_imikolov_ngram_and_seq(data_home):
    import paddle_tpu.dataset.imikolov as imikolov
    d = os.path.join(data_home, "imikolov")
    os.makedirs(d, exist_ok=True)
    train_text = b"the cat sat\nthe dog sat\n"
    valid_text = b"the cat ran\n"
    with tarfile.open(os.path.join(d, "simple-examples.tgz"),
                      "w:gz") as tf:
        for name, body in [(imikolov.TRAIN_FILE, train_text),
                           (imikolov.TEST_FILE, valid_text)]:
            info = tarfile.TarInfo(name)
            info.size = len(body)
            tf.addfile(info, io.BytesIO(body))
    w = imikolov.build_dict(min_word_freq=0)
    assert "<s>" in w and "<e>" in w and "<unk>" in w
    grams = list(imikolov.train(w, 2)())
    assert all(len(g) == 2 for g in grams) and grams
    seqs = list(imikolov.train(w, 0,
                               imikolov.DataType.SEQ)())
    src, trg = seqs[0]
    assert src[0] == w["<s>"] and trg[-1] == w["<e>"]


def test_dataset_wmt14_reader(data_home):
    import paddle_tpu.dataset.wmt14 as wmt14
    d = os.path.join(data_home, "wmt14")
    os.makedirs(d, exist_ok=True)
    vocab = b"<s>\n<e>\n<unk>\nhello\nworld\n"
    train = b"hello world\tbonjour monde\nhello\tbonjour\n"
    with tarfile.open(os.path.join(d, "wmt14.tgz"), "w:gz") as tf:
        for name, body in [("wmt14/src.dict", vocab),
                           ("wmt14/trg.dict", vocab),
                           ("wmt14/train/train", train)]:
            info = tarfile.TarInfo(name)
            info.size = len(body)
            tf.addfile(info, io.BytesIO(body))
    samples = list(wmt14.train(dict_size=5)())
    assert len(samples) == 2
    src, trg, trg_next = samples[0]
    assert src[0] == 0 and src[-1] == 1  # <s> ... <e>
    assert trg[0] == 0 and trg_next[-1] == 1
    src_d, trg_d = wmt14.get_dict(5)
    assert src_d[3] == "hello"


def test_dataset_wmt16_builds_dict_from_corpus(data_home):
    import paddle_tpu.dataset.wmt16 as wmt16
    d = os.path.join(data_home, "wmt16")
    os.makedirs(d, exist_ok=True)
    train = b"hello world\thallo welt\ngood day\tguten tag\n"
    with tarfile.open(os.path.join(d, "wmt16.tar.gz"), "w:gz") as tf:
        for name, body in [("wmt16/train", train),
                           ("wmt16/test", train),
                           ("wmt16/val", train)]:
            info = tarfile.TarInfo(name)
            info.size = len(body)
            tf.addfile(info, io.BytesIO(body))
    samples = list(wmt16.train(100, 100)())
    assert len(samples) == 2
    src, trg, trg_next = samples[0]
    en_dict = wmt16.get_dict("en", 100)
    assert en_dict["<s>"] == 0 and "hello" in en_dict


def test_dataset_movielens_readers(data_home):
    import paddle_tpu.dataset.movielens as ml
    ml.MOVIE_INFO = None  # reset module cache across DATA_HOME changes
    d = os.path.join(data_home, "movielens")
    os.makedirs(d, exist_ok=True)
    movies = ("1::Toy Story (1995)::Animation|Comedy\n"
              "2::Heat (1995)::Action\n")
    users = "1::M::25::6::12345\n2::F::35::3::54321\n"
    ratings = "1::1::5::978300760\n2::2::3::978302109\n"
    with zipfile.ZipFile(os.path.join(d, "ml-1m.zip"), "w") as z:
        z.writestr("ml-1m/movies.dat", movies)
        z.writestr("ml-1m/users.dat", users)
        z.writestr("ml-1m/ratings.dat", ratings)
    try:
        samples = list(ml.train()())
        assert samples, "train split unexpectedly empty"
        row = samples[0]
        # user(4) + movie(3) + rating(1)
        assert len(row) == 8 and row[-1][0] in (5.0, 1.0)
        assert ml.max_movie_id() == 2 and ml.max_user_id() == 2
        assert ml.max_job_id() == 6
        assert set(ml.movie_categories()) == {"Animation", "Comedy",
                                              "Action"}
        assert "toy" in ml.get_movie_title_dict()
    finally:
        ml.MOVIE_INFO = None


def test_dataset_conll05_expand_props():
    from paddle_tpu.dataset.conll05 import _expand_props
    assert _expand_props(["(A0*", "*", "*)", "(V*)", "*"]) == [
        "B-A0", "I-A0", "I-A0", "B-V", "O"]


def test_dataset_conll05_corpus_reader(data_home):
    import paddle_tpu.dataset.conll05 as conll05
    d = os.path.join(data_home, "conll05st")
    os.makedirs(d, exist_ok=True)
    words = b"The\ncat\nsat\n\n"
    props = b"-\t(A0*\nsat\t*)\n-\t(V*)\n\n"
    # column layout: first col is the verb sense column, later cols one
    # per predicate
    words_gz = gzip.compress(words)
    props_gz = gzip.compress(props)
    tar_path = os.path.join(d, "conll05st-tests.tar.gz")
    with tarfile.open(tar_path, "w:gz") as tf:
        for name, body in [
                ("conll05st-release/test.wsj/words/test.wsj.words.gz",
                 words_gz),
                ("conll05st-release/test.wsj/props/test.wsj.props.gz",
                 props_gz)]:
            info = tarfile.TarInfo(name)
            info.size = len(body)
            tf.addfile(info, io.BytesIO(body))
    reader = conll05.corpus_reader(
        tar_path,
        "conll05st-release/test.wsj/words/test.wsj.words.gz",
        "conll05st-release/test.wsj/props/test.wsj.props.gz")
    out = list(reader())
    assert out == [(["The", "cat", "sat"], "sat",
                    ["B-A0", "I-A0", "B-V"])]


def test_dataset_uci_housing(data_home):
    d = os.path.join(data_home, "uci_housing")
    os.makedirs(d, exist_ok=True)
    rng = np.random.RandomState(0)
    np.savetxt(os.path.join(d, "housing.data"),
               rng.rand(20, 14).astype(np.float32))
    import paddle_tpu.dataset.uci_housing as uci
    import paddle_tpu.text as text
    orig = text.DATA_HOME
    text.DATA_HOME = data_home
    try:
        train = list(uci.train()())
        test = list(uci.test()())
        assert len(train) == 16 and len(test) == 4
        x, y = train[0]
        assert x.shape == (13,) and y.shape == (1,)
    finally:
        text.DATA_HOME = orig


def test_dataset_zero_egress_error_is_clear(data_home):
    import paddle_tpu.dataset.common as common
    with pytest.raises(FileNotFoundError, match="no network access"):
        common.download("http://example.com/foo.tgz", "foo", None)


def test_dataset_common_split_and_cluster(tmp_path, data_home,
                                          monkeypatch):
    import paddle_tpu.dataset.common as common

    def r():
        return iter(range(10))

    monkeypatch.chdir(tmp_path)
    written = common.split(r, 4)
    assert len(written) >= 2
    shard0 = list(common.cluster_files_reader("0000*.pickle", 2, 0)())
    shard1 = list(common.cluster_files_reader("0000*.pickle", 2, 1)())
    assert sorted(shard0 + shard1) == list(range(10))


def test_dataset_image_utils():
    from paddle_tpu.dataset import image
    im = np.random.randint(0, 255, (64, 48, 3), dtype=np.uint8)
    small = image.resize_short(im, 32)
    assert min(small.shape[:2]) == 32
    crop = image.center_crop(small, 24)
    assert crop.shape[:2] == (24, 24)
    chw = image.to_chw(crop)
    assert chw.shape == (3, 24, 24)
    flipped = image.left_right_flip(im)
    np.testing.assert_array_equal(flipped, im[:, ::-1, :])
    out = image.simple_transform(im, 40, 32, is_train=False,
                                 mean=[1.0, 2.0, 3.0])
    assert out.shape == (3, 32, 32) and out.dtype == np.float32


# ------------------------------------------------------------ cost_model
def test_cost_model_static_table_and_program():
    from paddle_tpu.cost_model import CostModel
    cm = CostModel()
    data = cm.static_cost_data()
    ops = {d["op"] for d in data}
    assert {"matmul", "relu"} <= ops
    t = cm.get_static_op_time("matmul")
    assert t["op_time"] > 0
    t_b = cm.get_static_op_time("matmul", forward=False)
    assert t_b["op_time"] > 0
    startup, main = cm.build_program()
    cost = cm.profile_measure(startup, main, device="cpu")
    assert cost["time"] > 0
    import paddle_tpu as paddle
    paddle.disable_static()


# ------------------------------------------------------------- inference
def test_inference_utility_surface():
    from paddle_tpu import inference
    assert inference.get_num_bytes_of_data_type(
        inference.DataType.FLOAT32) == 4
    assert inference.get_num_bytes_of_data_type(
        inference.DataType.INT64) == 8
    assert "paddle_tpu" in inference.get_version()
    assert inference.get_trt_compile_version() == (0, 0, 0)
    assert inference.get_trt_runtime_version() == (0, 0, 0)


def test_top_level_namespaces_importable():
    for name in ("compat", "callbacks", "reader", "dataset",
                 "cost_model", "batch"):
        assert hasattr(paddle, name), name
