// paddle_tpu native runtime core.
//
// TPU-native counterpart of the reference's C++ reader/feeder machinery
// (paddle/fluid/operators/reader/buffered_reader.cc + blocking_queue.h):
// a bounded MPMC ring buffer used by the DataLoader to overlap host-side
// batch assembly with device compute, and a multithreaded memcpy batch
// collator (the reference stacks samples inside DataFeeder; here large
// numeric batches bypass numpy's single-threaded np.stack).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
#include <atomic>
#include <cctype>
#include <cerrno>
#include <charconv>
#include <cmath>
#include <locale.h>
#include <chrono>
#include <cstdlib>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

extern "C" {

// ---------------- bounded MPMC ring buffer of opaque handles ------------
struct RingBuffer {
    std::deque<uint64_t> items;
    size_t capacity;
    bool closed = false;
    std::mutex mu;
    std::condition_variable not_full;
    std::condition_variable not_empty;
};

void* rb_create(size_t capacity) {
    auto* rb = new RingBuffer();
    rb->capacity = capacity ? capacity : 1;
    return rb;
}

// returns 0 on success, -1 if closed
int rb_push(void* handle, uint64_t item, int timeout_ms) {
    auto* rb = static_cast<RingBuffer*>(handle);
    std::unique_lock<std::mutex> lk(rb->mu);
    auto pred = [rb] { return rb->closed || rb->items.size() < rb->capacity; };
    if (timeout_ms > 0) {
        if (!rb->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   pred))
            return -2;  // timeout
    } else {
        rb->not_full.wait(lk, pred);
    }
    if (rb->closed) return -1;
    rb->items.push_back(item);
    rb->not_empty.notify_one();
    return 0;
}

// returns 0 on success, -1 if closed+empty, -2 on timeout
int rb_pop(void* handle, uint64_t* out, int timeout_ms) {
    auto* rb = static_cast<RingBuffer*>(handle);
    std::unique_lock<std::mutex> lk(rb->mu);
    auto pred = [rb] { return rb->closed || !rb->items.empty(); };
    if (timeout_ms > 0) {
        if (!rb->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    pred))
            return -2;
    } else {
        rb->not_empty.wait(lk, pred);
    }
    if (rb->items.empty()) return -1;  // closed and drained
    *out = rb->items.front();
    rb->items.pop_front();
    rb->not_full.notify_one();
    return 0;
}

void rb_close(void* handle) {
    auto* rb = static_cast<RingBuffer*>(handle);
    {
        std::lock_guard<std::mutex> lk(rb->mu);
        rb->closed = true;
    }
    rb->not_full.notify_all();
    rb->not_empty.notify_all();
}

size_t rb_size(void* handle) {
    auto* rb = static_cast<RingBuffer*>(handle);
    std::lock_guard<std::mutex> lk(rb->mu);
    return rb->items.size();
}

void rb_destroy(void* handle) {
    delete static_cast<RingBuffer*>(handle);
}

// ---------------- multithreaded batch collation ------------------------
// Stack n_samples buffers of item_bytes each into dst (contiguous).
// Released-GIL callers get parallel memcpy across worker threads.
void fast_stack(const void** srcs, size_t n_samples, size_t item_bytes,
                void* dst, int n_threads) {
    if (n_threads <= 1 || n_samples < 4) {
        for (size_t i = 0; i < n_samples; ++i) {
            std::memcpy(static_cast<char*>(dst) + i * item_bytes, srcs[i],
                        item_bytes);
        }
        return;
    }
    std::vector<std::thread> threads;
    size_t per = (n_samples + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
        size_t lo = t * per;
        size_t hi = lo + per < n_samples ? lo + per : n_samples;
        if (lo >= hi) break;
        threads.emplace_back([=] {
            for (size_t i = lo; i < hi; ++i) {
                std::memcpy(static_cast<char*>(dst) + i * item_bytes,
                            srcs[i], item_bytes);
            }
        });
    }
    for (auto& th : threads) th.join();
}

// ---------------- host pinned-staging copy (device feed) ----------------
// Chunked parallel memcpy used when staging a large batch into the
// transfer buffer handed to PjRt.
void parallel_copy(const void* src, void* dst, size_t nbytes,
                   int n_threads) {
    if (n_threads <= 1 || nbytes < (1u << 20)) {
        std::memcpy(dst, src, nbytes);
        return;
    }
    std::vector<std::thread> threads;
    size_t per = (nbytes + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
        size_t lo = t * per;
        size_t hi = lo + per < nbytes ? lo + per : nbytes;
        if (lo >= hi) break;
        threads.emplace_back([=] {
            std::memcpy(static_cast<char*>(dst) + lo,
                        static_cast<const char*>(src) + lo, hi - lo);
        });
    }
    for (auto& th : threads) th.join();
}

// ---------------- MultiSlot in-memory dataset engine --------------------
// Native counterpart of the reference's MultiSlotInMemoryDataFeed
// (paddle/fluid/framework/data_feed.cc): parse "<n> v1..vn <m> u1..um"
// text records into per-slot CSR arrays with parallel worker threads,
// shuffle by permutation, and fill contiguous batch buffers for numpy.
// Slot types: 0 = float32, 1 = int64.

struct MSSlot {
    std::vector<float> fvals;
    std::vector<int64_t> ivals;
    std::vector<uint64_t> offsets;  // per-record value counts -> prefix sums
};

struct MSDataset {
    int n_slots;
    std::vector<int> types;
    std::vector<MSSlot> slots;   // offsets.size() == n_records + 1
    uint64_t n_records = 0;
    std::vector<uint64_t> perm;  // shuffle permutation over records
    std::mutex mu;
};

namespace {

// number parsing must be locale-independent (an embedding host may have
// set a comma-decimal LC_NUMERIC); one cached "C" locale for strtof_l
locale_t c_numeric_locale() {
    static locale_t loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
    return loc;
}

// Parse one chunk of complete lines into a thread-local shard.
// Returns false on malformed input. One record per line: a line with
// missing/extra slots is an error (like the reference's CheckFile),
// never silently merged with its neighbours.
bool ms_parse_chunk(const char* p, const char* end, int n_slots,
                    const int* types, std::vector<MSSlot>& shard,
                    uint64_t& n_records) {
    auto skip_sp = [&] {
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\r'))
            ++p;
    };
    auto skip_blank_lines = [&] {
        while (p < end) {
            skip_sp();
            if (p < end && *p == '\n') { ++p; continue; }
            break;
        }
    };
    while (true) {
        skip_blank_lines();
        if (p >= end) return true;
        for (int s = 0; s < n_slots; ++s) {
            skip_sp();
            if (p >= end || *p == '\n') return false;  // short line
            int64_t n = 0;
            auto rc = std::from_chars(p, end, n);
            if (rc.ec != std::errc() || n < 0) return false;
            p = rc.ptr;
            MSSlot& sl = shard[s];
            for (int64_t i = 0; i < n; ++i) {
                skip_sp();
                if (p >= end || *p == '\n') return false;  // short line
                if (types[s] == 1) {
                    int64_t v = 0;
                    auto r = std::from_chars(p, end, v);
                    if (r.ec != std::errc()) return false;
                    p = r.ptr;
                    sl.ivals.push_back(v);
                } else {
                    // strtof_l in the "C" locale, not std::from_chars:
                    // libstdc++ < 11 ships no floating-point from_chars
                    // overload, and plain strtof would misparse
                    // '.'-decimal data under a comma-decimal LC_NUMERIC
                    // set by an embedding host. The buffer is not
                    // NUL-terminated mid-chunk, but every chunk ends at
                    // a record boundary ('\n' <= end), so the parse
                    // always stops before running past `end`.
                    // strtof skips ANY leading whitespace (\n/\v/\f
                    // included) — guard so a short line can never
                    // silently consume a number from the next record
                    if (std::isspace(static_cast<unsigned char>(*p)))
                        return false;
                    char* stop = nullptr;
                    errno = 0;
                    float v = strtof_l(p, &stop, c_numeric_locale());
                    // ERANGE alone is not an error: glibc sets it on
                    // underflow to a (valid) subnormal too — only
                    // overflow to +/-HUGE_VALF is malformed input
                    if (stop == p || stop > end ||
                        (errno == ERANGE &&
                         (v == HUGE_VALF || v == -HUGE_VALF)))
                        return false;
                    p = stop;
                    sl.fvals.push_back(v);
                }
            }
            sl.offsets.push_back(static_cast<uint64_t>(n));
        }
        skip_sp();
        if (p < end && *p != '\n') return false;  // trailing tokens
        ++n_records;
    }
}

}  // namespace

void* ms_create(int n_slots, const int* types) {
    auto* ds = new MSDataset();
    ds->n_slots = n_slots;
    ds->types.assign(types, types + n_slots);
    ds->slots.resize(n_slots);
    for (auto& s : ds->slots) s.offsets.push_back(0);
    return ds;
}

// Parse `path` with n_threads workers; returns records added, -1 on error.
int64_t ms_load_file(void* handle, const char* path, int n_threads) {
    auto* ds = static_cast<MSDataset*>(handle);
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    // non-seekable input (FIFO etc.) -> -1 so the Python reader takes over
    if (std::fseek(f, 0, SEEK_END) != 0) { std::fclose(f); return -1; }
    long fsize = std::ftell(f);
    if (fsize < 0 || std::fseek(f, 0, SEEK_SET) != 0) {
        std::fclose(f);
        return -1;
    }
    std::string buf(static_cast<size_t>(fsize), '\0');
    size_t got = std::fread(buf.data(), 1, buf.size(), f);
    std::fclose(f);
    buf.resize(got);
    if (n_threads < 1) n_threads = 1;
    if (got < (1u << 16)) n_threads = 1;

    // split at line boundaries
    std::vector<const char*> starts{buf.data()};
    const char* bend = buf.data() + buf.size();
    for (int t = 1; t < n_threads; ++t) {
        const char* p = buf.data() + buf.size() * t / n_threads;
        while (p < bend && *p != '\n') ++p;
        starts.push_back(p < bend ? p + 1 : bend);
    }
    starts.push_back(bend);

    int nt = static_cast<int>(starts.size()) - 1;
    std::vector<std::vector<MSSlot>> shards(nt);
    std::vector<uint64_t> counts(nt, 0);
    std::vector<char> ok(nt, 1);
    std::vector<std::thread> threads;
    for (int t = 0; t < nt; ++t) {
        shards[t].resize(ds->n_slots);
        threads.emplace_back([&, t] {
            ok[t] = ms_parse_chunk(starts[t], starts[t + 1], ds->n_slots,
                                   ds->types.data(), shards[t], counts[t])
                        ? 1 : 0;
        });
    }
    for (auto& th : threads) th.join();
    for (int t = 0; t < nt; ++t)
        if (!ok[t]) return -1;

    std::lock_guard<std::mutex> lk(ds->mu);
    uint64_t added = 0;
    for (int t = 0; t < nt; ++t) {
        for (int s = 0; s < ds->n_slots; ++s) {
            MSSlot& dst = ds->slots[s];
            MSSlot& src = shards[t][s];
            uint64_t base = dst.offsets.back();
            for (uint64_t c : src.offsets)
                dst.offsets.push_back(base += c);
            if (ds->types[s] == 1)
                dst.ivals.insert(dst.ivals.end(), src.ivals.begin(),
                                 src.ivals.end());
            else
                dst.fvals.insert(dst.fvals.end(), src.fvals.begin(),
                                 src.fvals.end());
        }
        added += counts[t];
    }
    ds->n_records += added;
    ds->perm.resize(ds->n_records);
    for (uint64_t i = 0; i < ds->n_records; ++i) ds->perm[i] = i;
    return static_cast<int64_t>(added);
}

void ms_shuffle(void* handle, uint64_t seed) {
    auto* ds = static_cast<MSDataset*>(handle);
    std::lock_guard<std::mutex> lk(ds->mu);
    std::mt19937_64 rng(seed);
    for (uint64_t i = ds->n_records; i > 1; --i) {
        uint64_t j = rng() % i;
        std::swap(ds->perm[i - 1], ds->perm[j]);
    }
}

uint64_t ms_num_records(void* handle) {
    return static_cast<MSDataset*>(handle)->n_records;
}

// Per-record value counts (post-permutation) for records
// [start, start+count); returns the total across the batch.
uint64_t ms_batch_lens(void* handle, uint64_t start, uint64_t count,
                       int slot, uint64_t* lens_out) {
    auto* ds = static_cast<MSDataset*>(handle);
    const MSSlot& sl = ds->slots[slot];
    uint64_t total = 0;
    for (uint64_t i = 0; i < count; ++i) {
        uint64_t r = ds->perm[start + i];
        uint64_t len = sl.offsets[r + 1] - sl.offsets[r];
        lens_out[i] = len;
        total += len;
    }
    return total;
}

// Concatenate slot values of records [start, start+count) into out
// (caller sized it via ms_batch_lens).
void ms_fill_batch_f32(void* handle, uint64_t start, uint64_t count,
                       int slot, float* out) {
    auto* ds = static_cast<MSDataset*>(handle);
    const MSSlot& sl = ds->slots[slot];
    for (uint64_t i = 0; i < count; ++i) {
        uint64_t r = ds->perm[start + i];
        uint64_t lo = sl.offsets[r], hi = sl.offsets[r + 1];
        std::memcpy(out, sl.fvals.data() + lo, (hi - lo) * sizeof(float));
        out += hi - lo;
    }
}

void ms_fill_batch_i64(void* handle, uint64_t start, uint64_t count,
                       int slot, int64_t* out) {
    auto* ds = static_cast<MSDataset*>(handle);
    const MSSlot& sl = ds->slots[slot];
    for (uint64_t i = 0; i < count; ++i) {
        uint64_t r = ds->perm[start + i];
        uint64_t lo = sl.offsets[r], hi = sl.offsets[r + 1];
        std::memcpy(out, sl.ivals.data() + lo,
                    (hi - lo) * sizeof(int64_t));
        out += hi - lo;
    }
}

void ms_release(void* handle) {
    auto* ds = static_cast<MSDataset*>(handle);
    std::lock_guard<std::mutex> lk(ds->mu);
    ds->slots.assign(ds->n_slots, MSSlot());
    for (auto& s : ds->slots) s.offsets.push_back(0);
    ds->n_records = 0;
    ds->perm.clear();
}

void ms_destroy(void* handle) {
    delete static_cast<MSDataset*>(handle);
}

}  // extern "C"
