"""Data parallel. Parity: python/paddle/fluid/dygraph/parallel.py
(DataParallel with the C++ reducer, imperative/reducer.cc).

TPU-native: there is no per-rank process holding a replica — the jit path
shards the batch over the 'dp' mesh axis and XLA inserts one fused psum
over the gradients (the moral equivalent of the reducer's bucketed
allreduce, but scheduled by the compiler). DataParallel therefore wraps
the layer for API parity and marks it so fleet/TrainStep builders shard
the batch; eager single-device behavior is identity.
"""
from ..framework.core import Tensor

__all__ = ["DataParallel"]


class DataParallel:
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        self._layers = layers
        layers._is_data_parallel = True
        self.find_unused_parameters = find_unused_parameters

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass  # XLA emits the dp psum inside the jitted step

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)
