"""Layer semantics tests (SURVEY.md §2.3)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

pytestmark = pytest.mark.heavy  # slow-compiling: tier-1 yes, quick commit gate no


def t(a, sg=True):
    return paddle.to_tensor(np.asarray(a), stop_gradient=sg)


class TestLinearEmbedding:
    def test_linear_math(self):
        lin = nn.Linear(3, 2)
        w = np.arange(6, dtype=np.float32).reshape(3, 2)
        b = np.array([1.0, -1.0], np.float32)
        lin.weight.set_value(w)
        lin.bias.set_value(b)
        x = np.ones((4, 3), np.float32)
        np.testing.assert_allclose(lin(t(x)).numpy(), x @ w + b, rtol=1e-6)

    def test_embedding_padding_idx(self):
        emb = nn.Embedding(5, 3, padding_idx=0)
        assert np.all(emb.weight.numpy()[0] == 0)
        out = emb(t(np.array([0, 2])))
        assert np.all(out.numpy()[0] == 0)

    def test_embedding_grad_rows(self):
        emb = nn.Embedding(5, 3)
        idx = t(np.array([1, 1, 3]))
        emb(idx).sum().backward()
        g = emb.weight.grad.numpy()
        assert np.all(g[1] == 2.0) and np.all(g[3] == 1.0)
        assert np.all(g[0] == 0.0)


class TestConv:
    def test_conv2d_vs_torch(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        x = rng.rand(2, 3, 8, 8).astype(np.float32)
        w = rng.rand(5, 3, 3, 3).astype(np.float32)
        b = rng.rand(5).astype(np.float32)
        ref = tF.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                        stride=2, padding=1).numpy()
        got = F.conv2d(t(x), t(w), t(b), stride=2, padding=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_conv2d_groups_dilation(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(1)
        x = rng.rand(1, 4, 10, 10).astype(np.float32)
        w = rng.rand(8, 2, 3, 3).astype(np.float32)
        ref = tF.conv2d(torch.tensor(x), torch.tensor(w), None, padding=2,
                        dilation=2, groups=2).numpy()
        got = F.conv2d(t(x), t(w), None, padding=2, dilation=2,
                       groups=2).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_conv2d_transpose_vs_torch(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(2)
        x = rng.rand(1, 4, 5, 5).astype(np.float32)
        w = rng.rand(4, 6, 3, 3).astype(np.float32)
        ref = tF.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                  stride=2, padding=1).numpy()
        got = F.conv2d_transpose(t(x), t(w), stride=2, padding=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)

    def test_conv1d(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(3)
        x = rng.rand(2, 3, 12).astype(np.float32)
        w = rng.rand(4, 3, 3).astype(np.float32)
        ref = tF.conv1d(torch.tensor(x), torch.tensor(w), padding=1).numpy()
        got = F.conv1d(t(x), t(w), padding=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


class TestNorm:
    def test_layer_norm_vs_torch(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        x = rng.rand(2, 5, 8).astype(np.float32)
        w = rng.rand(8).astype(np.float32)
        b = rng.rand(8).astype(np.float32)
        ref = tF.layer_norm(torch.tensor(x), [8], torch.tensor(w),
                            torch.tensor(b)).numpy()
        got = F.layer_norm(t(x), [8], t(w), t(b)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_batch_norm_train_running_stats(self):
        bn = nn.BatchNorm1D(4, momentum=0.9, data_format="NCL")
        x = t(np.random.RandomState(0).rand(8, 4, 6).astype(np.float32))
        bn.train()
        bn(x)
        assert not np.allclose(bn._mean.numpy(), 0.0)
        bn.eval()
        y1 = bn(x).numpy()
        y2 = bn(x).numpy()
        np.testing.assert_allclose(y1, y2)

    def test_batch_norm_eval_math(self):
        bn = nn.BatchNorm2D(3)
        bn.eval()
        x = np.random.RandomState(1).rand(2, 3, 4, 4).astype(np.float32)
        got = bn(t(x)).numpy()
        np.testing.assert_allclose(got, x / np.sqrt(1 + 1e-5), rtol=1e-5)

    def test_group_norm_vs_torch(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(2)
        x = rng.rand(2, 6, 4, 4).astype(np.float32)
        ref = tF.group_norm(torch.tensor(x), 3).numpy()
        got = F.group_norm(t(x), 3).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_instance_norm_vs_torch(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(3)
        x = rng.rand(2, 3, 5, 5).astype(np.float32)
        ref = tF.instance_norm(torch.tensor(x)).numpy()
        got = F.instance_norm(t(x)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


class TestActivations:
    @pytest.mark.parametrize("name,torch_name", [
        ("relu", "relu"), ("gelu", "gelu"), ("silu", "silu"),
        ("mish", "mish"), ("relu6", "relu6"), ("hardswish", "hardswish"),
        ("softplus", "softplus"), ("elu", "elu"), ("selu", "selu"),
        ("leaky_relu", "leaky_relu"),
    ])
    def test_vs_torch(self, name, torch_name):
        import torch
        import torch.nn.functional as tF
        x = np.linspace(-3, 3, 31).astype(np.float32)
        ref = getattr(tF, torch_name)(torch.tensor(x)).numpy()
        got = getattr(F, name)(t(x)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_softmax_logsoftmax(self):
        from scipy.special import softmax as ssoftmax, log_softmax as sls
        x = np.random.RandomState(0).rand(3, 5).astype(np.float32)
        np.testing.assert_allclose(F.softmax(t(x)).numpy(),
                                   ssoftmax(x, -1), rtol=1e-5)
        np.testing.assert_allclose(F.log_softmax(t(x)).numpy(),
                                   sls(x, -1), rtol=1e-5)

    def test_glu_maxout(self):
        x = np.random.RandomState(0).rand(4, 6).astype(np.float32)
        out = F.glu(t(x)).numpy()
        assert out.shape == (4, 3)
        xm = np.random.RandomState(0).rand(2, 6, 3).astype(np.float32)
        assert F.maxout(t(xm), 2, axis=1).shape == [2, 3, 3]


class TestLoss:
    def test_cross_entropy_vs_torch(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        logits = rng.rand(6, 10).astype(np.float32)
        labels = rng.randint(0, 10, size=(6,))
        ref = tF.cross_entropy(torch.tensor(logits),
                               torch.tensor(labels)).item()
        got = F.cross_entropy(t(logits), t(labels)).item()
        assert abs(ref - got) < 1e-5

    def test_cross_entropy_ignore_index(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        logits = rng.rand(6, 10).astype(np.float32)
        labels = np.array([1, 2, -100, 4, -100, 5])
        ref = tF.cross_entropy(torch.tensor(logits), torch.tensor(labels),
                               ignore_index=-100).item()
        got = F.cross_entropy(t(logits), t(labels),
                              ignore_index=-100).item()
        assert abs(ref - got) < 1e-5

    def test_cross_entropy_soft_label(self):
        rng = np.random.RandomState(0)
        logits = rng.rand(4, 5).astype(np.float32)
        soft = np.abs(rng.rand(4, 5).astype(np.float32))
        soft /= soft.sum(-1, keepdims=True)
        from scipy.special import log_softmax as sls
        ref = float((-soft * sls(logits, -1)).sum(-1).mean())
        got = F.cross_entropy(t(logits), t(soft), soft_label=True).item()
        assert abs(ref - got) < 1e-5

    def test_bce_mse_l1(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        p = rng.rand(8).astype(np.float32) * 0.9 + 0.05
        y = (rng.rand(8) > 0.5).astype(np.float32)
        assert abs(F.binary_cross_entropy(t(p), t(y)).item() -
                   tF.binary_cross_entropy(torch.tensor(p),
                                           torch.tensor(y)).item()) < 1e-5
        z = rng.randn(8).astype(np.float32)
        assert abs(
            F.binary_cross_entropy_with_logits(t(z), t(y)).item() -
            tF.binary_cross_entropy_with_logits(
                torch.tensor(z), torch.tensor(y)).item()) < 1e-5
        a, b = rng.rand(5).astype(np.float32), rng.rand(5).astype(np.float32)
        assert abs(F.mse_loss(t(a), t(b)).item() -
                   float(((a - b) ** 2).mean())) < 1e-6
        assert abs(F.l1_loss(t(a), t(b)).item() -
                   float(np.abs(a - b).mean())) < 1e-6

    def test_kl_smooth_l1(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        logp = np.log(rng.dirichlet(np.ones(5), 4).astype(np.float32))
        q = rng.dirichlet(np.ones(5), 4).astype(np.float32)
        ref = tF.kl_div(torch.tensor(logp), torch.tensor(q),
                        reduction="mean").item()
        got = F.kl_div(t(logp), t(q), reduction="mean").item()
        assert abs(ref - got) < 1e-5

    def test_ctc_loss_vs_torch(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        T, N, C, S = 12, 3, 6, 4
        logits = rng.randn(T, N, C).astype(np.float32)
        labels = rng.randint(1, C, size=(N, S)).astype(np.int64)
        ilen = np.array([12, 10, 8], np.int64)
        llen = np.array([4, 3, 2], np.int64)
        ref = tF.ctc_loss(
            torch.tensor(logits).log_softmax(-1), torch.tensor(labels),
            torch.tensor(ilen), torch.tensor(llen), blank=0,
            reduction="none").numpy()
        got = F.ctc_loss(t(logits), t(labels), t(ilen), t(llen), blank=0,
                         reduction="none").numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


class TestPooling:
    def test_pool_vs_torch(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        x = rng.rand(2, 3, 8, 8).astype(np.float32)
        ref = tF.max_pool2d(torch.tensor(x), 2).numpy()
        np.testing.assert_allclose(F.max_pool2d(t(x), 2).numpy(), ref)
        ref = tF.avg_pool2d(torch.tensor(x), 3, stride=2,
                            padding=1).numpy()
        got = F.avg_pool2d(t(x), 3, stride=2, padding=1,
                           exclusive=False).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_adaptive(self):
        import torch
        import torch.nn.functional as tF
        rng = np.random.RandomState(0)
        x = rng.rand(2, 3, 9, 9).astype(np.float32)
        ref = tF.adaptive_avg_pool2d(torch.tensor(x), 3).numpy()
        np.testing.assert_allclose(
            F.adaptive_avg_pool2d(t(x), 3).numpy(), ref, rtol=1e-5)
        ref = tF.adaptive_max_pool2d(torch.tensor(x), 4).numpy()
        np.testing.assert_allclose(
            F.adaptive_max_pool2d(t(x), 4).numpy(), ref, rtol=1e-5)


class TestContainers:
    def test_sequential_layerlist(self):
        m = nn.Sequential(nn.Linear(4, 4), nn.ReLU())
        assert len(m) == 2
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        ll.append(nn.Linear(2, 2))
        assert len(ll) == 4 and len(ll.parameters()) == 8

    def test_layerdict(self):
        d = nn.LayerDict({"a": nn.Linear(2, 2)})
        d["b"] = nn.ReLU()
        assert "a" in d and len(d) == 2

    def test_apply_train_eval(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training

    def test_hooks(self):
        m = nn.Linear(2, 2)
        calls = []
        h = m.register_forward_post_hook(
            lambda layer, inp, out: calls.append(1))
        m(t(np.ones((1, 2), np.float32)))
        assert calls == [1]
        h.remove()
        m(t(np.ones((1, 2), np.float32)))
        assert calls == [1]


class TestGradClip:
    def test_global_norm(self):
        lin = nn.Linear(4, 4)
        x = t(np.ones((2, 4), np.float32))
        (lin(x) * 100).sum().backward()
        clip = nn.ClipGradByGlobalNorm(1.0)
        pg = clip([(p, p.grad) for p in lin.parameters()])
        total = np.sqrt(sum(float((g.numpy() ** 2).sum()) for _, g in pg))
        assert abs(total - 1.0) < 1e-4

    def test_by_value(self):
        lin = nn.Linear(2, 2)
        lin(t(np.ones((1, 2), np.float32))).sum().backward()
        clip = nn.ClipGradByValue(0.5)
        pg = clip([(p, p.grad) for p in lin.parameters()])
        for _, g in pg:
            assert g.numpy().max() <= 0.5


class TestUtils:
    def test_params_vector_roundtrip(self):
        m = nn.Linear(3, 2)
        from paddle_tpu.nn.utils import parameters_to_vector, \
            vector_to_parameters
        vec = parameters_to_vector(m.parameters())
        assert vec.shape == [8]
        vector_to_parameters(vec * 0 + 1.0, m.parameters())
        assert np.all(m.weight.numpy() == 1.0)

    def test_weight_norm(self):
        from paddle_tpu.nn.utils import weight_norm, remove_weight_norm
        lin = weight_norm(nn.Linear(3, 4))
        names = dict(lin.named_parameters())
        assert "weight_g" in names and "weight_v" in names
        x = t(np.ones((1, 3), np.float32))
        y1 = lin(x).numpy()
        remove_weight_norm(lin)
        y2 = lin(x).numpy()
        np.testing.assert_allclose(y1, y2, rtol=1e-5)
