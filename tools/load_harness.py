#!/usr/bin/env python
"""Open-loop load harness for the serving front door
(docs/SERVING.md, docs/OBSERVABILITY.md "The fleet observatory").

Closed-loop clients (bench.py --serve) hide overload: a slow fleet
slows its own offered load, so attainment looks fine right up to the
cliff. This harness is OPEN-LOOP — the arrival schedule is generated
up front (seeded, deterministic) and the submit thread walks it by the
wall clock, never waiting on completions — so a 10x burst keeps
arriving whether or not the fleet keeps up, which is the only regime
where admission rejection, deadline expiry, and the fleet observatory's
pressure events actually fire.

Three pieces:

- `generate_trace(seed, ...)` — a deterministic request trace: Poisson
  arrivals (exponential inter-arrival gaps) with a configurable burst
  window at `factor` x the base rate, heavy-tailed (lognormal, clipped)
  prompt/output lengths, and a tiered SLO mix (interactive / standard /
  batch deadlines). Same seed, same trace — byte for byte.
- `OpenLoopHarness(router, trace)` — drives any ServingRouter through
  the trace: submits on schedule (recording per-request submit
  lateness, the open-loop honesty metric), counts rejections at the
  front door, tracks peak in-flight, and joins per-request TTFT / TPOT
  / attainment from the serving observatory's request ring (the
  records carry ttft_s / slo_class / deadline_met — emitted by the
  engines, not re-measured here).
- ONE `kind:"harness"` summary record per run (schema:
  tools/check_metrics_schema.py): goodput tokens/s, per-class SLO
  attainment, TTFT/TPOT p50/p99, rejected/expired fractions, peak
  in-flight, and per-phase (before / burst / after) sub-summaries.

Standalone CLI (CPU-friendly tiny GPT, 2-engine disaggregated router):

    python tools/load_harness.py --seed 0 --requests 24 --rate 4 \
        --burst-factor 10

`--speculate` drives the SAME trace through the fleet twice — once
plain, once with a SpeculativeConfig threaded through the router
(docs/SERVING.md "Speculative decoding") — and prints both goodputs
next to the fleet accept rate, so burst-regime speculation overhead
is measured against an identical arrival schedule.

`bench.py --serve` runs the same harness as its load stage
(BENCH_SERVE_LOAD=0 skips) and persists the headline numbers in
serve_history.
"""
import argparse
import json
import os
import sys
import threading
import time

import numpy as np

# SLO tiers: (class, deadline_ms, mix weight). The bounds sit inside
# the router's DEFAULT_SLO_CLASSES bands so the stamped class matches.
SLO_TIERS = (("interactive", 8_000, 0.3),
             ("standard", 60_000, 0.5),
             ("batch", 600_000, 0.2))


def generate_trace(seed, n_requests, rate_rps=4.0,
                   burst=(0.4, 0.7, 10.0), prompt_mean=8.0,
                   prompt_sigma=0.6, max_prompt=48, out_mean=4.0,
                   out_sigma=0.5, max_out=8, vocab=128):
    """A deterministic open-loop request trace: a list of dicts
    {"t": arrival offset s, "prompt": 1-D int array, "max_new": int,
    "slo_class": str, "deadline_ms": int}, sorted by arrival.

    Arrivals are Poisson at `rate_rps`, except inside the burst window
    — (start_frac, end_frac, factor) over the request INDEX space —
    where the rate multiplies by `factor` (a 10x burst arrives 10x
    faster, it is not 10x more requests). Lengths are lognormal
    (heavy-tailed) clipped to [1, max]; the SLO class is drawn from
    the tiered mix. Everything comes from one RandomState(seed)."""
    rng = np.random.RandomState(int(seed))
    b_lo, b_hi, b_factor = burst
    names = [t[0] for t in SLO_TIERS]
    deadlines = {t[0]: t[1] for t in SLO_TIERS}
    weights = np.array([t[2] for t in SLO_TIERS], np.float64)
    weights = weights / weights.sum()
    trace, t = [], 0.0
    for i in range(int(n_requests)):
        frac = i / max(int(n_requests) - 1, 1)
        rate = rate_rps * (b_factor if b_lo <= frac < b_hi else 1.0)
        t += float(rng.exponential(1.0 / max(rate, 1e-9)))
        plen = int(np.clip(rng.lognormal(np.log(prompt_mean),
                                         prompt_sigma), 1, max_prompt))
        out = int(np.clip(rng.lognormal(np.log(out_mean), out_sigma),
                          1, max_out))
        cls = names[int(rng.choice(len(names), p=weights))]
        trace.append({
            "t": round(t, 6),
            "prompt": rng.randint(0, int(vocab), (plen,)),
            "max_new": out,
            "slo_class": cls,
            "deadline_ms": deadlines[cls],
        })
    return trace


def _pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class OpenLoopHarness:
    """Drive one ServingRouter through a generated trace, open-loop.

    The submit thread is the caller's thread (run() blocks for the
    schedule + a drain timeout); completions land via Future
    add_done_callback — tiny callbacks that stamp an outcome under the
    harness lock, so in-flight accounting never waits on a result."""

    def __init__(self, router, trace, drain_timeout_s=120.0,
                 burst=(0.4, 0.7)):
        self.router = router
        self.trace = list(trace)
        self.drain_timeout_s = drain_timeout_s
        # the burst window the TRACE was generated with, as index
        # fractions — the before/burst/after phase buckets derive from
        # it, so a trace built with a non-default window must hand the
        # same tuple here or its phase stats mislabel. generate_trace's
        # 3-tuple (lo, hi, factor) is accepted as-is.
        self.burst_lo = float(burst[0])
        self.burst_hi = float(burst[1])
        self._lock = threading.Lock()
        self._in_flight = 0
        self._peak_in_flight = 0
        self._done = 0
        self._submitted = []  # (request_id, scheduled_t, lateness_s, i)
        self._rejected = 0

    def _on_done(self, fut):
        # Future callback thread context: counters only, under the lock
        with self._lock:
            self._in_flight -= 1
            self._done += 1

    def run(self):
        """Walk the schedule, drain, and return the summary dict (also
        exported as the run's ONE `kind:"harness"` record)."""
        from paddle_tpu.inference.serving import QueueFullError
        from paddle_tpu.profiler import monitor as _pmon
        from paddle_tpu.profiler import serve_observatory as _sobs

        handles = []
        t0 = time.perf_counter()
        for i, req in enumerate(self.trace):
            target = t0 + req["t"]
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            # open-loop honesty: the submit happens when the SCHEDULE
            # says, late only by what submit() itself cost us earlier —
            # recorded, never silently absorbed
            lateness = time.perf_counter() - target
            try:
                h = self.router.submit(
                    req["prompt"], max_new_tokens=req["max_new"],
                    deadline_ms=req["deadline_ms"])
            except QueueFullError:
                with self._lock:
                    self._rejected += 1
                    self._submitted.append((None, req["t"],
                                            lateness, i))
                continue
            with self._lock:
                self._in_flight += 1
                if self._in_flight > self._peak_in_flight:
                    self._peak_in_flight = self._in_flight
                self._submitted.append((h.request_id, req["t"],
                                        lateness, i))
            h.future.add_done_callback(self._on_done)
            handles.append(h)
        # drain: bounded wait per outstanding handle — open-loop ends
        # at the LAST ARRIVAL; the drain just lets in-flight work land
        deadline = time.perf_counter() + self.drain_timeout_s
        for h in handles:
            left = deadline - time.perf_counter()
            if left <= 0:
                break
            try:
                h.result(timeout=left)
            except Exception:
                pass  # expiry/error shows up in the records
        duration = time.perf_counter() - t0
        return self._summarize(duration, _pmon, _sobs)

    # -- the rollup ------------------------------------------------------
    def _summarize(self, duration, _pmon, _sobs):
        # join the engines' own request records by request_id — the
        # harness measures the OFFERED side; the observed side comes
        # from the observatory ledger (terminal records only: the
        # prefill "handoff" halves are superseded by their decode half)
        recs = {}
        for r in _sobs.requests_tail():
            rid = r.get("request_id")
            if rid and r.get("outcome") != "handoff":
                recs[rid] = r
        with self._lock:
            submitted = list(self._submitted)
            rejected = self._rejected
            peak = self._peak_in_flight
        n = len(submitted)
        by_rid = {}
        for rid, sched_t, lateness, i in submitted:
            if rid is not None and rid in recs:
                by_rid[rid] = (recs[rid], sched_t, i)
        ttfts, tpots, lates = [], [], []
        expired = completed = goodput_tokens = 0
        attain = {}
        phase_stats = {}
        n_idx = max(len(self.trace) - 1, 1)

        def _phase_of(i):
            frac = i / n_idx
            return "before" if frac < self.burst_lo else \
                "burst" if frac < self.burst_hi else "after"

        # every OFFERED request lands in its phase bucket — a rejected
        # one has no engine record but its rejection is the phase's
        # whole story during the burst
        for rid, sched_t, lateness, i in submitted:
            ps = phase_stats.setdefault(
                _phase_of(i), {"requests": 0, "rejected": 0,
                               "met": 0, "dl": 0})
            ps["requests"] += 1
            if rid is None:
                ps["rejected"] += 1
        for rid, (r, sched_t, i) in by_rid.items():
            ps = phase_stats[_phase_of(i)]
            if r.get("outcome") == "expired":
                expired += 1
            elif r.get("outcome") == "completed":
                completed += 1
            gen = int(r.get("generated_tokens", 0))
            met = r.get("deadline_met")
            if met:
                goodput_tokens += gen
            if met is not None:
                cls = str(r.get("slo_class", "batch"))
                c = attain.setdefault(cls, [0, 0])
                c[0] += 1 if met else 0
                c[1] += 1
                ps["dl"] += 1
                ps["met"] += 1 if met else 0
            ttft = r.get("ttft_s")
            if isinstance(ttft, (int, float)):
                ttfts.append(float(ttft))
                if gen > 1:
                    tpots.append(
                        (float(r.get("latency_s", 0.0)) - float(ttft))
                        / (gen - 1))
        for _, _, lateness, _ in submitted:
            lates.append(max(lateness, 0.0))
        ttfts.sort()
        tpots.sort()
        lates.sort()
        rec = {
            "ts": time.time(),
            "rank": _pmon.rank(),
            "kind": "harness",
            "router": str(getattr(self.router, "name", "router")),
            "seed": int(getattr(self, "seed", -1)),
            "requests": n,
            "duration_s": round(duration, 6),
            "goodput_tokens_per_s": round(
                goodput_tokens / duration, 4) if duration > 0 else 0.0,
            "rejected_fraction": round(rejected / n, 4) if n else 0.0,
            "expired_fraction": round(expired / n, 4) if n else 0.0,
            "peak_in_flight": peak,
            "ttft_p50_s": round(_pct(ttfts, 50), 6),
            "ttft_p99_s": round(_pct(ttfts, 99), 6),
            "tpot_p50_s": round(_pct(tpots, 50), 6),
            "tpot_p99_s": round(_pct(tpots, 99), 6),
            "submit_lateness_p99_s": round(_pct(lates, 99), 6),
            "completed": completed,
            "attainment_by_class": {
                cls: round(c[0] / c[1], 4)
                for cls, c in sorted(attain.items()) if c[1]},
            "phases": {
                ph: dict(s, attainment=round(s["met"] / s["dl"], 4)
                         if s["dl"] else None)
                for ph, s in sorted(phase_stats.items())},
        }
        _pmon.counter("fleet.harness_runs").inc()
        _pmon.export_step(rec, kind="harness")
        return rec


def run_harness(router, trace, seed=0, drain_timeout_s=120.0,
                snapshot_after=True, burst=(0.4, 0.7)):
    """Convenience wrapper: run the harness, force a closing fleet
    snapshot (so the run's last window lands in the JSONL), and return
    the summary record. `burst` is the window the trace was generated
    with (generate_trace's 3-tuple is accepted) — the phase buckets
    in the summary derive from it."""
    h = OpenLoopHarness(router, trace, drain_timeout_s=drain_timeout_s,
                        burst=burst)
    h.seed = int(seed)
    summary = h.run()
    mon = getattr(router, "_fleet_mon", None)
    if snapshot_after and mon is not None:
        mon.snapshot()
        summary["pressure_events"] = len(mon.pressure.events)
    return summary


def _build_router(args, speculative=None, name="harness_router"):
    """CPU-friendly tiny disaggregated fleet for the CLI."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import ServingRouter
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_position_embeddings=64,
                    dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    return ServingRouter.disaggregated(
        model, n_pages=64, page_size=8, max_batch=2,
        max_new_tokens=args.max_new, max_queue=args.max_queue,
        name=name, fleet_snapshot_s=args.snapshot_s,
        speculative=speculative)


def _spec_config(args):
    """The --speculate draft: a 1-layer sibling of the target (same
    vocab/width — random-init stand-in for a distilled draft; the
    harness measures the speculation MACHINERY under burst load, not a
    tuned accept rate)."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import SpeculativeConfig
    from paddle_tpu.models.gpt import GPTForCausalLM, GPTConfig

    paddle.seed(1)
    dcfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=1,
                     num_heads=2, max_position_embeddings=64,
                     dropout=0.0)
    draft = GPTForCausalLM(dcfg)
    draft.eval()
    return SpeculativeConfig(draft, k=args.spec_k)


def main(argv=None):
    ap = argparse.ArgumentParser(
        "load_harness",
        description="open-loop load harness for the serving front door")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--rate", type=float, default=4.0,
                    help="base arrival rate, requests/s")
    ap.add_argument("--burst-factor", type=float, default=10.0,
                    help="rate multiplier inside the burst window")
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--max-queue", type=int, default=4,
                    help="per-engine admission queue bound (small => "
                         "the burst actually rejects)")
    ap.add_argument("--snapshot-s", type=float, default=0.5,
                    help="fleet snapshot cadence during the run")
    ap.add_argument("--drain-timeout", type=float, default=120.0)
    ap.add_argument("--speculate", action="store_true",
                    help="drive the SAME trace twice — speculative "
                         "decoding off, then on — and report both "
                         "goodputs side by side with the fleet accept "
                         "rate (each pass exports its own harness "
                         "record)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="speculation depth for --speculate")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    burst = (0.4, 0.7, args.burst_factor)
    trace = generate_trace(args.seed, args.requests,
                           rate_rps=args.rate, burst=burst,
                           max_out=args.max_new)
    router = _build_router(args)
    try:
        summary = run_harness(router, trace, seed=args.seed,
                              drain_timeout_s=args.drain_timeout,
                              burst=burst)
    finally:
        router.shutdown()
    if args.speculate:
        # same seed, same schedule, same prompts — the only variable
        # is the speculative pipeline, so the goodput delta is real
        spec_router = _build_router(args, speculative=_spec_config(args),
                                    name="harness_router_spec")
        try:
            spec_summary = run_harness(
                spec_router, trace, seed=args.seed,
                drain_timeout_s=args.drain_timeout, burst=burst)
            rep = spec_router.load_report()
        finally:
            spec_router.shutdown()
        engines = rep.get("engines", {}) if isinstance(rep, dict) else {}
        prop = sum(int(e.get("proposed_tokens", 0))
                   for e in engines.values())
        acc = sum(int(e.get("accepted_tokens", 0))
                  for e in engines.values())
        off = float(summary.get("goodput_tokens_per_s", 0.0))
        on = float(spec_summary.get("goodput_tokens_per_s", 0.0))
        summary = {
            "spec_off": summary,
            "spec_on": spec_summary,
            "speculate": {
                "k": int(args.spec_k),
                "goodput_off_tokens_per_s": off,
                "goodput_on_tokens_per_s": on,
                "goodput_ratio": round(on / off, 4) if off else None,
                "proposed_tokens": prop,
                "accepted_tokens": acc,
                "accept_rate": round(acc / prop, 4) if prop else 0.0,
            },
        }
    print(json.dumps(summary, default=str), flush=True)
    return 0


if __name__ == "__main__":
    # script execution puts tools/ (not the repo root) on sys.path —
    # the framework import needs the root
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    raise SystemExit(main(sys.argv[1:]))
