// paddle_tpu native runtime core.
//
// TPU-native counterpart of the reference's C++ reader/feeder machinery
// (paddle/fluid/operators/reader/buffered_reader.cc + blocking_queue.h):
// a bounded MPMC ring buffer used by the DataLoader to overlap host-side
// batch assembly with device compute, and a multithreaded memcpy batch
// collator (the reference stacks samples inside DataFeeder; here large
// numeric batches bypass numpy's single-threaded np.stack).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---------------- bounded MPMC ring buffer of opaque handles ------------
struct RingBuffer {
    std::deque<uint64_t> items;
    size_t capacity;
    bool closed = false;
    std::mutex mu;
    std::condition_variable not_full;
    std::condition_variable not_empty;
};

void* rb_create(size_t capacity) {
    auto* rb = new RingBuffer();
    rb->capacity = capacity ? capacity : 1;
    return rb;
}

// returns 0 on success, -1 if closed
int rb_push(void* handle, uint64_t item, int timeout_ms) {
    auto* rb = static_cast<RingBuffer*>(handle);
    std::unique_lock<std::mutex> lk(rb->mu);
    auto pred = [rb] { return rb->closed || rb->items.size() < rb->capacity; };
    if (timeout_ms > 0) {
        if (!rb->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   pred))
            return -2;  // timeout
    } else {
        rb->not_full.wait(lk, pred);
    }
    if (rb->closed) return -1;
    rb->items.push_back(item);
    rb->not_empty.notify_one();
    return 0;
}

// returns 0 on success, -1 if closed+empty, -2 on timeout
int rb_pop(void* handle, uint64_t* out, int timeout_ms) {
    auto* rb = static_cast<RingBuffer*>(handle);
    std::unique_lock<std::mutex> lk(rb->mu);
    auto pred = [rb] { return rb->closed || !rb->items.empty(); };
    if (timeout_ms > 0) {
        if (!rb->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    pred))
            return -2;
    } else {
        rb->not_empty.wait(lk, pred);
    }
    if (rb->items.empty()) return -1;  // closed and drained
    *out = rb->items.front();
    rb->items.pop_front();
    rb->not_full.notify_one();
    return 0;
}

void rb_close(void* handle) {
    auto* rb = static_cast<RingBuffer*>(handle);
    {
        std::lock_guard<std::mutex> lk(rb->mu);
        rb->closed = true;
    }
    rb->not_full.notify_all();
    rb->not_empty.notify_all();
}

size_t rb_size(void* handle) {
    auto* rb = static_cast<RingBuffer*>(handle);
    std::lock_guard<std::mutex> lk(rb->mu);
    return rb->items.size();
}

void rb_destroy(void* handle) {
    delete static_cast<RingBuffer*>(handle);
}

// ---------------- multithreaded batch collation ------------------------
// Stack n_samples buffers of item_bytes each into dst (contiguous).
// Released-GIL callers get parallel memcpy across worker threads.
void fast_stack(const void** srcs, size_t n_samples, size_t item_bytes,
                void* dst, int n_threads) {
    if (n_threads <= 1 || n_samples < 4) {
        for (size_t i = 0; i < n_samples; ++i) {
            std::memcpy(static_cast<char*>(dst) + i * item_bytes, srcs[i],
                        item_bytes);
        }
        return;
    }
    std::vector<std::thread> threads;
    size_t per = (n_samples + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
        size_t lo = t * per;
        size_t hi = lo + per < n_samples ? lo + per : n_samples;
        if (lo >= hi) break;
        threads.emplace_back([=] {
            for (size_t i = lo; i < hi; ++i) {
                std::memcpy(static_cast<char*>(dst) + i * item_bytes,
                            srcs[i], item_bytes);
            }
        });
    }
    for (auto& th : threads) th.join();
}

// ---------------- host pinned-staging copy (device feed) ----------------
// Chunked parallel memcpy used when staging a large batch into the
// transfer buffer handed to PjRt.
void parallel_copy(const void* src, void* dst, size_t nbytes,
                   int n_threads) {
    if (n_threads <= 1 || nbytes < (1u << 20)) {
        std::memcpy(dst, src, nbytes);
        return;
    }
    std::vector<std::thread> threads;
    size_t per = (nbytes + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
        size_t lo = t * per;
        size_t hi = lo + per < nbytes ? lo + per : nbytes;
        if (lo >= hi) break;
        threads.emplace_back([=] {
            std::memcpy(static_cast<char*>(dst) + lo,
                        static_cast<const char*>(src) + lo, hi - lo);
        });
    }
    for (auto& th : threads) th.join();
}

}  // extern "C"
