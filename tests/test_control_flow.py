"""Traceable control flow: static.nn.cond/while_loop/case/switch_case must
lower to lax.cond/lax.while_loop/lax.switch when the predicate is traced
(reference converts Python control flow for static graph:
fluid/dygraph/dygraph_to_static/convert_operators.py:26,191)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static.nn as snn
from paddle_tpu.jit import to_static


def test_cond_eager():
    x = paddle.to_tensor([2.0])
    out = snn.cond(x.sum() > 1.0, lambda: x * 2, lambda: x / 2)
    np.testing.assert_allclose(out.numpy(), [4.0])


def test_cond_traced():
    import jax

    def f(x):
        t = paddle.to_tensor(x)
        out = snn.cond(t.sum() > 1.0,
                       lambda: t * 2,
                       lambda: t / 2)
        return out.value

    jf = jax.jit(f)
    np.testing.assert_allclose(jf(np.array([2.0], np.float32)), [4.0])
    np.testing.assert_allclose(jf(np.array([0.25], np.float32)), [0.125])


def test_cond_traced_tuple_output():
    import jax

    def f(x):
        t = paddle.to_tensor(x)
        a, b = snn.cond(t.sum() > 0,
                        lambda: (t + 1, t - 1),
                        lambda: (t * 0, t * 0))
        return a.value, b.value

    a, b = jax.jit(f)(np.array([3.0], np.float32))
    np.testing.assert_allclose(a, [4.0])
    np.testing.assert_allclose(b, [2.0])


def test_while_loop_eager():
    i = paddle.to_tensor(0)
    out = snn.while_loop(lambda i: i < 5, lambda i: i + 1, [i])
    assert int(out[0].item()) == 5


def test_while_loop_traced():
    import jax

    def f(n):
        i = paddle.to_tensor(np.int32(0))
        s = paddle.to_tensor(np.float32(0.0))
        nt = paddle.to_tensor(n)
        i, s = snn.while_loop(lambda i, s: i < nt,
                              lambda i, s: (i + 1, s + 2.0),
                              [i, s])
        return s.value

    jf = jax.jit(f)
    assert float(jf(np.int32(5))) == 10.0
    assert float(jf(np.int32(3))) == 6.0  # same compiled program


def test_while_loop_traced_value_composes():
    """jitted while_loop composes with surrounding traced math (value
    path only: lax.while_loop is not reverse-differentiable)."""
    import jax

    def f(x):
        t = paddle.to_tensor(x)
        i = paddle.to_tensor(np.int32(0))
        i, t = snn.while_loop(lambda i, t: i < 3,
                              lambda i, t: (i + 1, t * 2.0),
                              [i, t])
        return t.value.sum()

    out = jax.jit(f)(np.array([1.0, 2.0], np.float32))
    assert float(out) == 24.0


def test_case_traced():
    import jax

    def f(x):
        t = paddle.to_tensor(x)
        out = snn.case([(t.sum() < 0, lambda: t * 10),
                        (t.sum() < 10, lambda: t + 100)],
                       default=lambda: t)
        return out.value

    jf = jax.jit(f)
    np.testing.assert_allclose(jf(np.array([1.0], np.float32)), [101.0])
    np.testing.assert_allclose(jf(np.array([-2.0], np.float32)), [-20.0])
    np.testing.assert_allclose(jf(np.array([50.0], np.float32)), [50.0])


def test_switch_case_traced():
    import jax

    def f(idx, x):
        t = paddle.to_tensor(x)
        i = paddle.to_tensor(idx)
        out = snn.switch_case(i, {1: lambda: t + 1, 3: lambda: t + 3},
                              default=lambda: t * 0)
        return out.value

    jf = jax.jit(f)
    np.testing.assert_allclose(jf(np.int32(1), np.float32(10)), 11.0)
    np.testing.assert_allclose(jf(np.int32(3), np.float32(10)), 13.0)
    np.testing.assert_allclose(jf(np.int32(7), np.float32(10)), 0.0)


def test_to_static_routes_control_flow():
    """A to_static function with data-dependent control flow compiles once
    and follows the right branch for different values."""
    calls = {"n": 0}

    @to_static
    def f(x):
        calls["n"] += 1
        return snn.cond(x.sum() > 0, lambda: x * 2, lambda: x * -1)

    a = f(paddle.to_tensor([3.0]))
    b = f(paddle.to_tensor([-4.0]))
    np.testing.assert_allclose(a.numpy(), [6.0])
    np.testing.assert_allclose(b.numpy(), [4.0])
    assert calls["n"] == 1, "same shapes must not retrace"
