"""The serving observatory: per-request lifecycle tracing, KV page-pool
telemetry, and SLO/goodput accounting for the continuous-batching
engines (`paddle_tpu/inference/serving.py`).

Sibling of `compile_observatory.py`, built for the same reason at a
different layer: the serving engines (PR 4/8) are the path to the
"millions of users" north star, and the disaggregated multi-engine
router (ROADMAP open item 3) cannot be built on process-global
aggregates alone. Three pieces:

- **Per-request lifecycle ledger** — every request submitted to either
  engine gets an id and a `RequestTrace` accumulating
  submit/admit/first-token/terminal timestamps, token counts
  (prompt / prefix-hit / generated), prefill-chunk count, peak KV pages
  held, and the outcome. ONE `kind:"request"` record is emitted at the
  terminal state (ringed in the flight recorder always, JSONL when
  `PADDLE_TPU_METRICS_FILE` is set) — per-request aggregation, never
  per-token records, and every trace method is pure host arithmetic
  (no device reads: the module is fenced whole by
  tools/check_no_hot_sync.py).

  Outcomes: ``completed`` (result delivered), ``expired`` (deadline
  passed before admission), ``rejected`` (queue-full / stopped-engine
  fast fail at submit), ``cancelled`` (caller cancel, or work shed by
  `shutdown(wait=False)`), ``error`` (failed onto the future),
  ``handoff`` (prefill half of a disaggregated pair: the chain moved
  to a decode engine, which opened a fresh trace under the SAME
  request_id — `handoff_of` names the other engine on both records,
  and profiler/fleet_observatory.py joins the pair into ONE
  `kind:"journey"` record at the decode terminal).

- **KV page-pool telemetry** — `record_pool_stats(engine, cache)`
  turns `PagedKVCache.pool_stats()` into a periodic `kind:"kvcache"`
  snapshot (free/held/shared/registered/drawn pages, refcount
  histogram, prefix-registry size, copy-on-write and LRU-reclaim
  counters) plus `serve.kv_*` gauges, emitted from the engine loop
  every `kv_snapshot_every` steps.

- **SLO/goodput accounting** — deadline attainment by outcome
  (`slo_report()`), `serve.goodput_tokens` (tokens generated for
  requests that completed) vs `serve.wasted_tokens` (tokens generated
  for requests that later expired / were cancelled / errored), and
  `serve.tpot_s` (time per output token, decode phase) feeding
  `GenerationEngine.load_report()`'s tail percentiles — the admission
  snapshot a load-aware router will consume.

Debug bundles (`flight_recorder.dump`) pull `requests_tail()` (the ring
of recent terminal request records -> `requests_tail.jsonl`) and
`debug_payload()` (per-registered-engine `load_report` + `pool_stats`
-> `serve_state.json`), so a hung serving loop names the requests in
flight. See docs/SERVING.md "The serving observatory".
"""
import collections
import itertools
import threading
import time
import weakref

from . import monitor as _monitor

__all__ = ["RequestTrace", "start_request", "record_pool_stats",
           "register_engine", "requests_tail", "slo_report",
           "debug_payload", "reset", "OUTCOMES", "REQUEST_RING"]

OUTCOMES = ("completed", "expired", "rejected", "error", "cancelled",
            "handoff")

REQUEST_RING = 512  # terminal request records kept for bundle tails

_lock = threading.RLock()
_ids = itertools.count()
_requests = collections.deque(maxlen=REQUEST_RING)
_outcomes = collections.Counter()
# deadline-carrying requests only: outcome -> [met, total]
_deadline_by_outcome = {}
# deadline-carrying requests only: slo class -> [met, total] (the
# router stamps `trace.slo_class`; engine-only traffic has no class)
_deadline_by_class = {}
_engines = collections.OrderedDict()  # name -> weakref(engine)
MAX_ENGINES = 16


class RequestTrace:
    """One request's lifecycle accumulator. Created at submit
    (`start_request`), mutated by the engine as the request moves
    through admit / prefill / decode, closed exactly once by
    `finish(outcome)` — which emits the `kind:"request"` record and
    folds the request into the SLO/goodput aggregates. Every method is
    a few host float/int ops; `finish` additionally does the (ring +
    optional JSONL) export."""

    __slots__ = ("request_id", "engine", "rows", "prompt_tokens",
                 "max_new_tokens", "deadline_s", "prefix_hit_tokens",
                 "generated_tokens", "prefill_chunks", "peak_pages_held",
                 "proposed_tokens", "accepted_tokens",
                 "t_submit", "t_admit", "t_first", "done",
                 "slo_class", "handoff_of", "journey",
                 "cache_strategy")

    def __init__(self, engine, rows=1, prompt_tokens=0,
                 max_new_tokens=None, deadline_s=None):
        self.request_id = f"{engine}-r{next(_ids)}"
        self.engine = str(engine)
        self.rows = int(rows)
        self.prompt_tokens = int(prompt_tokens)
        self.max_new_tokens = max_new_tokens
        self.deadline_s = deadline_s
        self.prefix_hit_tokens = 0
        self.generated_tokens = 0
        self.prefill_chunks = 0
        self.peak_pages_held = 0
        # speculative decoding (inference/speculative.py): draft tokens
        # this request was offered vs the ones the target's verify row
        # accepted — zeros on every non-speculative path
        self.proposed_tokens = 0
        self.accepted_tokens = 0
        self.t_submit = time.perf_counter()
        self.t_admit = None
        self.t_first = None
        self.done = False
        self.slo_class = None   # router-stamped SLO class name
        self.handoff_of = None  # the OTHER engine of a handed-off pair
        self.journey = None     # fleet_observatory.Journey (decode side
        #                         of a handoff; emits at terminal)
        self.cache_strategy = "paged"  # engine-stamped at submit/adopt

    # -- lifecycle marks (engine loop; pure host arithmetic) -----------
    def admitted(self):
        """The request left the queue (claimed by the scheduler)."""
        if self.t_admit is None:
            self.t_admit = time.perf_counter()

    def first_token(self):
        """First generated token streamed (TTFT boundary)."""
        if self.t_first is None:
            self.t_first = time.perf_counter()

    def note_prefix(self, n_tokens):
        """Prompt tokens served from the refcounted prefix cache."""
        self.prefix_hit_tokens += int(n_tokens)

    def note_chunk(self):
        """One prefill chunk of this request's prompt dispatched."""
        self.prefill_chunks += 1

    def note_token(self, pages_held=0):
        """One token generated; `pages_held` updates the peak."""
        self.generated_tokens += 1
        if pages_held > self.peak_pages_held:
            self.peak_pages_held = int(pages_held)

    def note_speculation(self, proposed, accepted):
        """One verify row's verdict: `proposed` draft tokens went in,
        `accepted` survived (the bonus sample is a generated token,
        not an accepted one — accepted <= proposed always)."""
        self.proposed_tokens += int(proposed)
        self.accepted_tokens += int(accepted)

    # -- terminal state -------------------------------------------------
    def finish(self, outcome, error=None):
        """Close the trace: emit the ONE `kind:"request"` record and
        update the SLO/goodput aggregates. Idempotent (the first call
        wins — engine teardown paths may race a completion) and never
        raises. Returns the record (None on the duplicate call)."""
        with _lock:
            if self.done:
                return None
            self.done = True
        try:
            return self._emit(outcome, error)
        except Exception:
            return None  # telemetry must never take down the engine

    def _emit(self, outcome, error):
        outcome = str(outcome)
        t_end = time.perf_counter()
        latency = max(t_end - self.t_submit, 0.0)
        admit = self.t_admit if self.t_admit is not None else t_end
        queue_s = max(min(admit, t_end) - self.t_submit, 0.0)
        prefill_s = decode_s = 0.0
        if self.t_first is not None:
            if self.t_admit is not None:
                prefill_s = max(self.t_first - admit, 0.0)
            decode_s = max(t_end - self.t_first, 0.0)
        elif self.t_admit is not None:
            # admitted but no token ever streamed: the post-queue time
            # is all prefill (e.g. errored/cancelled mid-prefill)
            prefill_s = max(t_end - admit, 0.0)
        # full exported shape (ts/rank/kind included): the ring copy in
        # requests_tail.jsonl must validate standalone, not only the
        # JSONL line export_step re-stamps
        rec = {
            "ts": time.time(),
            "rank": _monitor.rank(),
            "kind": "request",
            "engine": self.engine,
            "request_id": self.request_id,
            "cache_strategy": str(self.cache_strategy),
            "outcome": outcome,
            "rows": self.rows,
            "prompt_tokens": self.prompt_tokens,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "generated_tokens": self.generated_tokens,
            "prefill_chunks": self.prefill_chunks,
            "peak_pages_held": self.peak_pages_held,
            "proposed_tokens": self.proposed_tokens,
            "accepted_tokens": self.accepted_tokens,
            "accept_rate": (self.accepted_tokens / self.proposed_tokens)
            if self.proposed_tokens else 0.0,
            "queue_s": round(queue_s, 6),
            "prefill_s": round(prefill_s, 6),
            "decode_s": round(decode_s, 6),
            "latency_s": round(latency, 6),
        }
        if self.max_new_tokens is not None:
            rec["max_new_tokens"] = int(self.max_new_tokens)
        if self.t_first is not None:
            rec["ttft_s"] = round(max(self.t_first - self.t_submit,
                                      0.0), 6)
        if self.slo_class is not None:
            rec["slo_class"] = str(self.slo_class)
        if self.handoff_of is not None:
            rec["handoff_of"] = str(self.handoff_of)
        met = None
        # outcome "handoff" is not a terminal state of the REQUEST —
        # the decode-side trace (same request_id) carries the journey
        # to its real outcome and does ALL the deadline/goodput
        # accounting; counting the prefill half too would double-book
        if self.deadline_s is not None and outcome != "handoff":
            met = outcome == "completed" and latency <= self.deadline_s
            rec["deadline_s"] = round(self.deadline_s, 6)
            rec["deadline_met"] = bool(met)
        if error:
            rec["error"] = str(error)[:300]
        # SLO/goodput aggregates
        with _lock:
            _outcomes[outcome] += 1
            if met is not None:
                bucket = _deadline_by_outcome.setdefault(outcome, [0, 0])
                bucket[0] += 1 if met else 0
                bucket[1] += 1
                if self.slo_class is not None:
                    cbucket = _deadline_by_class.setdefault(
                        str(self.slo_class), [0, 0])
                    cbucket[0] += 1 if met else 0
                    cbucket[1] += 1
        gen = self.generated_tokens
        if gen and outcome != "handoff":
            if outcome == "completed":
                _monitor.counter("serve.goodput_tokens").inc(gen)
            else:
                # generated for a request nobody will use the output of
                _monitor.counter("serve.wasted_tokens").inc(gen)
        if outcome == "completed" and gen >= 2 and self.t_first is not None:
            _monitor.histogram("serve.tpot_s").observe(
                decode_s / (gen - 1))
        _monitor.export_step(rec, kind="request")
        with _lock:
            _requests.append(rec)
        if self.journey is not None:
            try:  # the journey emits its own record; its failure must
                self.journey.complete(rec)  # not lose the request rec
            except Exception:
                pass
        return rec


def start_request(engine, rows=1, prompt_tokens=0, max_new_tokens=None,
                  deadline_s=None):
    """New RequestTrace for one submitted request (both engines call
    this from submit, after validation — caller-bug ValueErrors produce
    no record, queue-full rejections do)."""
    return RequestTrace(engine, rows=rows, prompt_tokens=prompt_tokens,
                        max_new_tokens=max_new_tokens,
                        deadline_s=deadline_s)


# -- KV page-pool telemetry ----------------------------------------------

def record_pool_stats(engine, cache, extra=None):
    """One `kind:"kvcache"` snapshot of a PagedKVCache's pool state
    (`cache.pool_stats()`: free/held/shared/registered/drawn pages,
    refcount histogram, prefix-registry size, CoW/reclaim counters) +
    the `serve.kv_*` gauges. Called periodically from the engine loop —
    pure host-side dict math, never raises. Returns the record."""
    try:
        stats = cache.pool_stats()
        rec = {"engine": str(engine)}
        rec.update(stats)
        if extra:
            rec.update(extra)
        held = int(stats.get("held_pages", 0))
        _monitor.gauge("serve.kv_free_pages").set(
            int(stats.get("free_pages", 0)))
        _monitor.gauge("serve.kv_held_pages").set(held)
        _monitor.gauge("serve.kv_registered_pages").set(
            int(stats.get("registered_pages", 0)))
        _monitor.gauge("serve.kv_evictable_pages").set(
            int(stats.get("evictable_pages", 0)))
        peak = _monitor.gauge("serve.kv_peak_held_pages")
        if held > peak.value:
            peak.set(held)
        _monitor.export_step(rec, kind="kvcache")
        return rec
    except Exception:
        return None


# -- engine registry (debug bundles) -------------------------------------

def register_engine(engine):
    """Remember a live engine (weakref — an abandoned engine stays
    collectible) so debug bundles can snapshot its `load_report()` /
    pool state. Bounded; oldest forgotten."""
    try:
        name = str(getattr(engine, "name", "serve"))
        with _lock:
            _engines.pop(name, None)
            _engines[name] = weakref.ref(engine)
            while len(_engines) > MAX_ENGINES:
                _engines.popitem(last=False)
    except Exception:
        pass


def live_engines():
    """[(name, engine)] for the registered engines still alive."""
    out = []
    with _lock:
        items = list(_engines.items())
    for name, ref in items:
        eng = ref()
        if eng is not None:
            out.append((name, eng))
    return out


# -- aggregates / bundle payloads ----------------------------------------

def requests_tail():
    """The ring of recent terminal `kind:"request"` records (oldest
    first) — what a debug bundle writes as requests_tail.jsonl."""
    with _lock:
        return [dict(r) for r in _requests]


def slo_report():
    """Deadline attainment by outcome + the goodput/wasted token split:
    {"requests", "outcomes": {outcome: n}, "deadline": {"requests",
    "met", "attainment"}, "deadline_by_outcome": {outcome: {met,
    total}}, "deadline_by_class": {slo class: {met, total,
    attainment}}, "goodput_tokens", "wasted_tokens"}. `attainment` is
    None until a deadline-carrying request finishes. A handed-off
    request counts ONCE in the deadline/goodput aggregates (its
    decode-side terminal), but its prefill half appears in `outcomes`
    under "handoff"."""
    with _lock:
        outcomes = dict(_outcomes)
        by_outcome = {k: {"met": v[0], "total": v[1]}
                      for k, v in _deadline_by_outcome.items()}
        by_class = {k: {"met": v[0], "total": v[1],
                        "attainment": v[0] / v[1] if v[1] else None}
                    for k, v in _deadline_by_class.items()}
    met = sum(v["met"] for v in by_outcome.values())
    total = sum(v["total"] for v in by_outcome.values())
    good = _monitor.get_metric("serve.goodput_tokens")
    waste = _monitor.get_metric("serve.wasted_tokens")
    return {
        "requests": sum(outcomes.values()),
        "outcomes": outcomes,
        "deadline": {"requests": total, "met": met,
                     "attainment": (met / total) if total else None},
        "deadline_by_outcome": by_outcome,
        "deadline_by_class": by_class,
        "goodput_tokens": int(good.value) if good else 0,
        "wasted_tokens": int(waste.value) if waste else 0,
    }


def debug_payload():
    """Per-registered-engine state for a debug bundle: each live
    engine's `observatory_snapshot()` (load_report + pool_stats) plus
    the SLO aggregate. Never raises; engines that refuse to snapshot
    are reported by error string instead."""
    engines = {}
    for name, eng in live_engines():
        try:
            snap = eng.observatory_snapshot()
        except Exception as e:  # a wedged engine must not kill the dump
            snap = {"error": f"{type(e).__name__}: {e}"[:200]}
        engines[name] = snap
    try:
        slo = slo_report()
    except Exception:
        slo = {}
    return {"engines": engines, "slo": slo}


def reset():
    """Drop request ring + SLO aggregates (tests). The engine registry
    persists (it self-cleans via weakrefs)."""
    with _lock:
        _requests.clear()
        _outcomes.clear()
        _deadline_by_outcome.clear()
        _deadline_by_class.clear()
