"""Comparison & logical ops. Parity: python/paddle/tensor/logic.py."""
import jax.numpy as jnp

from ..framework.core import Tensor, apply_op
from .math import _wrap_binary, _wrap_unary

equal = _wrap_binary(lambda a, b: a == b)
not_equal = _wrap_binary(lambda a, b: a != b)
greater_than = _wrap_binary(lambda a, b: a > b)
greater_equal = _wrap_binary(lambda a, b: a >= b)
less_than = _wrap_binary(lambda a, b: a < b)
less_equal = _wrap_binary(lambda a, b: a <= b)
logical_and = _wrap_binary(jnp.logical_and)
logical_or = _wrap_binary(jnp.logical_or)
logical_xor = _wrap_binary(jnp.logical_xor)
logical_not = _wrap_unary(jnp.logical_not)
bitwise_and = _wrap_binary(jnp.bitwise_and)
bitwise_or = _wrap_binary(jnp.bitwise_or)
bitwise_xor = _wrap_binary(jnp.bitwise_xor)
bitwise_not = _wrap_unary(jnp.bitwise_not)


def equal_all(x, y, name=None):
    return apply_op(lambda a, b: jnp.array_equal(a, b), x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                              equal_nan=equal_nan), x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                             equal_nan=equal_nan), x, y)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
