"""fleet.utils.fs — filesystem abstraction for checkpoint/data paths.

Parity: /root/reference/python/paddle/distributed/fleet/utils/fs.py.
LocalFS is fully functional (it backs sharded-checkpoint paths);
HDFSClient shells out to the `hadoop` CLI exactly like the reference
and degrades to a clear error when no hadoop binary is on PATH (TPU
pods normally mount GCS via local paths instead).
"""
import os
import shutil
import subprocess

__all__ = []


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class FSShellCmdAborted(ExecuteError):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def upload_dir(self, local_dir, dest_dir):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def cat(self, fs_path=None):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem client (used by checkpoint save/load paths)."""

    def ls_dir(self, fs_path):
        """Returns (dirs, files) directly under fs_path."""
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for f in os.listdir(fs_path):
            if os.path.isdir(os.path.join(fs_path, f)):
                dirs.append(f)
            else:
                files.append(f)
        return dirs, files

    def mkdirs(self, fs_path):
        assert not os.path.isfile(fs_path), \
            f"{fs_path} is already a file"
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def _rmr(self, fs_path):
        shutil.rmtree(fs_path)

    def _rm(self, fs_path):
        os.remove(fs_path)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isfile(fs_path):
            return self._rm(fs_path)
        return self._rmr(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        with open(fs_path, "a"):
            pass

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        if self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        return self.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        """Directory names directly under fs_path."""
        if not self.is_exist(fs_path):
            return []
        return [f for f in os.listdir(fs_path)
                if os.path.isdir(os.path.join(fs_path, f))]

    def cat(self, fs_path=None):
        with open(fs_path) as f:
            return f.read().rstrip("\n")


def _handle_errors(max_time_out=None):
    """Retry decorator for flaky shell-backed operations."""
    import functools
    import time

    def decorator(f):
        @functools.wraps(f)
        def handler(*args, **kwargs):
            o = args[0]
            time_out = max_time_out or o._time_out
            inter = o._sleep_inter
            start = time.time() * 1000
            last_print_time = start
            while True:
                try:
                    return f(*args, **kwargs)
                except FSShellCmdAborted:
                    raise  # permanent (misconfiguration) — no retry
                except ExecuteError:
                    now = time.time() * 1000
                    if now - start > time_out:
                        raise FSTimeOut(
                            f"args:{args} timeout:{now - start}ms")
                    time.sleep(inter / 1000.0)
                    if now - last_print_time > 30000:
                        print(f"hadoop operation retrying, args: "
                              f"{args} elapsed: {now - start}ms")
                        last_print_time = now

        return handler

    return decorator


class HDFSClient(FS):
    """HDFS client shelling to the hadoop CLI (reference behavior).

    Raises a clear ExecuteError when no hadoop binary is available —
    on TPU pods, mount the store (e.g. GCS fuse) and use LocalFS.
    """

    def __init__(self, hadoop_home, configs, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._base_cmd = os.path.join(hadoop_home, "bin/hadoop")
        if configs:
            for k, v in configs.items():
                self._base_cmd += f" -D{k}={v}"
        self._time_out = time_out
        self._sleep_inter = sleep_inter
        self._bd_err_re = None

    def _run_cmd(self, cmd, redirect_stderr=False):
        binary = self._base_cmd.split()[0]
        if not os.path.exists(binary):
            # permanent misconfiguration: fail fast (FSShellCmdAborted
            # is not retried by _handle_errors)
            raise FSShellCmdAborted(
                f"no hadoop binary at {binary}; HDFSClient needs a "
                "hadoop install (use LocalFS + a mounted filesystem "
                "on TPU pods)")
        full = f"{self._base_cmd} {cmd}"
        proc = subprocess.run(
            full, shell=True, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT if redirect_stderr else
            subprocess.PIPE)
        out = proc.stdout.decode(errors="replace").splitlines()
        return proc.returncode, out

    @staticmethod
    def _test_says_no(ret, out):
        """FAIL CLOSED: only `hadoop fs -test` exit code 1 with benign
        output is a clean "no". Hadoop emits benign stderr noise
        (SLF4J/native-loader WARNs, log4j 'ERROR StatusLogger' config
        complaints), so lines are benign unless they carry a java
        exception. Any OTHER nonzero exit (JVM OOM 137, classpath 127,
        generic failure 255, kerberos/cluster exceptions) must NOT be
        read as "checkpoint absent" — a caller that trusts a false "no"
        restarts training from scratch over a transient cluster error."""
        if ret != 1:
            return False
        return not any("Exception" in line and "No such file" not in line
                       for line in out)

    @_handle_errors()
    def is_exist(self, fs_path):
        ret, out = self._run_cmd(f"fs -test -e {fs_path}",
                                 redirect_stderr=True)
        if ret == 0:
            return True
        if self._test_says_no(ret, out):
            return False
        raise ExecuteError(
            f"is_exist {fs_path}: rc={ret} " + "\n".join(out[:5]))

    @_handle_errors()
    def is_dir(self, fs_path):
        ret, out = self._run_cmd(f"fs -test -d {fs_path}",
                                 redirect_stderr=True)
        if ret == 0:
            return True
        if self._test_says_no(ret, out):
            return False
        raise ExecuteError(
            f"is_dir {fs_path}: rc={ret} " + "\n".join(out[:5]))

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    @_handle_errors()
    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        ret, lines = self._run_cmd(f"fs -ls {fs_path}")
        if ret != 0:
            raise ExecuteError(f"ls_dir {fs_path}")
        dirs, files = [], []
        for line in lines:
            arr = line.split()
            if len(arr) != 8:
                continue
            name = os.path.basename(arr[7])
            if arr[0].startswith("d"):
                dirs.append(name)
            else:
                files.append(name)
        return dirs, files

    def list_dirs(self, fs_path):
        if not self.is_exist(fs_path):
            return []
        dirs, _ = self.ls_dir(fs_path)
        return dirs

    @_handle_errors()
    def mkdirs(self, fs_path):
        if self.is_exist(fs_path):
            return
        ret, _ = self._run_cmd(f"fs -mkdir -p {fs_path}")
        if ret != 0:
            raise ExecuteError(f"mkdirs {fs_path}")

    @_handle_errors()
    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        ret, _ = self._run_cmd(f"fs -rm -r {fs_path}")
        if ret != 0:
            raise ExecuteError(f"delete {fs_path}")

    @_handle_errors()
    def upload(self, local_path, fs_path):
        if self.is_exist(fs_path):
            raise FSFileExistsError(f"{fs_path} exists")
        if not os.path.exists(local_path):
            raise FSFileNotExistsError(f"{local_path} not exists")
        ret, _ = self._run_cmd(f"fs -put {local_path} {fs_path}")
        if ret != 0:
            raise ExecuteError(f"upload {local_path} {fs_path}")

    @_handle_errors()
    def download(self, fs_path, local_path):
        if not self.is_exist(fs_path):
            raise FSFileNotExistsError(f"{fs_path} not exists")
        ret, _ = self._run_cmd(f"fs -get {fs_path} {local_path}")
        if ret != 0:
            raise ExecuteError(f"download {fs_path} {local_path}")

    @_handle_errors()
    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        ret, _ = self._run_cmd(f"fs -touchz {fs_path}")
        if ret != 0:
            raise ExecuteError(f"touch {fs_path}")

    @_handle_errors()
    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=True):
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        if test_exists:
            if not self.is_exist(fs_src_path):
                raise FSFileNotExistsError(fs_src_path)
            if self.is_exist(fs_dst_path):
                raise FSFileExistsError(fs_dst_path)
        ret, _ = self._run_cmd(f"fs -mv {fs_src_path} {fs_dst_path}")
        if ret != 0:
            raise ExecuteError(f"mv {fs_src_path} {fs_dst_path}")

    def need_upload_download(self):
        return True

    @_handle_errors()
    def cat(self, fs_path=None):
        if not self.is_file(fs_path):
            return ""
        ret, lines = self._run_cmd(f"fs -cat {fs_path}")
        if ret != 0:
            raise ExecuteError(f"cat {fs_path}")
        return "\n".join(lines)
