from .recompute import recompute, recompute_sequential, recompute_hybrid
