"""Role makers. Parity: python/paddle/distributed/fleet/base/role_maker.py
(Role, PaddleCloudRoleMaker, UserDefinedRoleMaker).

On TPU every process is a collective worker over the jax mesh — there is
no parameter-server role split — so role makers reduce to rank/world
bookkeeping: PaddleCloudRoleMaker reads the launcher's env vars,
UserDefinedRoleMaker takes explicit kwargs.
"""
import os

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_num = 1
        self._worker_endpoints = []
        self._server_endpoints = []

    def is_worker(self):
        return self._role in (Role.WORKER, Role.ALL)

    def is_server(self):
        return self._role in (Role.SERVER, Role.ALL)

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def role_id(self):
        return self._current_id

    def worker_num(self):
        return self._worker_num

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return list(self._worker_endpoints)

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)

    def _barrier(self, comm_world=None):
        from ... import env
        env.barrier()


class PaddleCloudRoleMaker(RoleMakerBase):
    """Collective role maker driven by the launch env
    (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS,
    as exported by paddle.distributed.launch)."""

    def __init__(self, is_collective=True, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._kwargs = kwargs
        self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._worker_num = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = [e for e in eps.split(",") if e]
        self._role = Role.WORKER

    def _is_collective_mode(self):
        return self._is_collective


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Explicit role maker: ranks/endpoints passed as kwargs instead of
    read from the environment."""

    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        super().__init__(is_collective=is_collective, **kwargs)
        self._current_id = kwargs.get(
            "current_id", self._current_id)
        self._role = kwargs.get("role", Role.WORKER)
        if "worker_num" in kwargs:
            self._worker_num = kwargs["worker_num"]
        if "worker_endpoints" in kwargs:
            self._worker_endpoints = list(kwargs["worker_endpoints"])
            self._worker_num = len(self._worker_endpoints)
        if "server_endpoints" in kwargs:
            self._server_endpoints = list(kwargs["server_endpoints"])
