"""use-after-donate pass: dataflow from donated buffers through
dispatch calls to later reads of the same binding.

`donate_argnums` hands a buffer's storage to XLA: after the dispatch
returns, the Python binding still points at a deleted array, and the
next read raises the jax "array has been deleted" RuntimeError — the
class PR 5 converted to a loud FloatingPointError by hand in the
train step, and PR 8's paged-decode path re-found in the pool
handoff. This pass mechanizes it:

1. **Donation registry** — scan the fileset for donation
   declarations and record donated positional indices per callable
   name:
     - `@functools.partial(jax.jit, donate_argnums=(0,))` decorators
       on module functions (ops/pallas pool ops);
     - `X = jax.jit(fn, donate_argnums=(1, 2))` assignments, X a
       local name or `self.<attr>` (models/gpt.py decode programs;
       chained `fn = self._jit_fn = jax.jit(...)` registers both).
   Dynamic argnums (`donate_argnums=donate_argnums`) are
   unresolvable and skipped — the jit/api.py TrainStep guards that
   path at runtime already.
2. **Call-site dataflow** — within each function, a call to a
   registered donating callable (matched by local name, `self.attr`,
   or a local alias assigned from one) CONSUMES the plain-name
   arguments at the donated positions. Any later read of a consumed
   name in the same function — before a rebinding assignment —
   is `use-after-donate`. Rebinding through the dispatch result
   (`pool = step(pool, x)`) is the correct idiom and clears the
   taint.

The taint walk is source-order linear but BRANCH-SENSITIVE: a donate
in one arm of an `if` never taints reads in the other arm (the two
are mutually exclusive), while sibling `if`s — which can both run —
still propagate. Known limitation (documented, fixture-tested): a
donation at the BOTTOM of a loop body
whose next iteration re-reads the name above it is not modeled.
Every in-repo donation site either rebinds from the result or hands
the binding off (the gpt.py pool programs), so the linear walk covers
the real idiom; revisit if a loop-carried donation pattern appears.

False positives (e.g. a read guarded by an is-deleted check) take
`# lint-ok[use-after-donate]: <why>` on the read line.
"""
import ast

from .core import Finding, _dotted

PASS_NAME = "use-after-donate"


def _exclusive(p1, p2):
    """True when two branch paths sit in DIFFERENT arms of the same
    `if`: control flow can execute one or the other, never both in
    one pass through the function."""
    for a, b in zip(p1, p2):
        if a[0] != b[0]:
            return False  # sibling ifs: both arms can run in sequence
        if a[1] != b[1]:
            return True
    return False


def _donated_positions(call):
    """The literal donate_argnums of a jit-wrapping Call, else None."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and \
                    isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)) and all(
                    isinstance(e, ast.Constant) and
                    isinstance(e.value, int) for e in v.elts):
                return tuple(e.value for e in v.elts)
            return None  # dynamic: unresolvable statically
    return None


def _is_jit_call(call):
    d = _dotted(call.func) or ""
    return d.endswith("jit") or d.endswith("pjit") or \
        d.endswith("aot_compile")


class _Registry:
    """Donating callables of one file: name -> donated positions.
    Names: 'func' (module function), 'Class.attr' (self-attribute),
    'qualfunc.local' (function-local binding)."""

    def __init__(self, sf):
        self.positions = {}
        if sf.tree is None:
            return
        self._scan(sf.tree, None, "")

    def _scan(self, node, cls, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._scan(child, child.name, prefix)
                continue
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                self._decorated(child)
                self._scan(child, cls, f"{prefix}{child.name}.")
                continue
            if isinstance(child, (ast.Assign, ast.AnnAssign)) and \
                    isinstance(child.value, ast.Call):
                call = child.value
                pos = None
                if _is_jit_call(call):
                    pos = _donated_positions(call)
                elif isinstance(call.func, ast.Attribute) and \
                        call.func.attr == "partial":
                    pos = _donated_positions(call)
                if pos:
                    targets = child.targets \
                        if isinstance(child, ast.Assign) \
                        else [child.target]
                    for t in targets:
                        if isinstance(t, ast.Name):
                            # function-local bindings stay scoped to
                            # their qualified key — a bare-name entry
                            # would taint unrelated same-named
                            # callables in other functions (the
                            # in-function `aliases` map covers local
                            # call sites)
                            self.positions[f"{prefix}{t.id}"] = pos
                        elif isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self" and cls:
                            self.positions[f"{cls}.{t.attr}"] = pos
            self._scan(child, cls, prefix)

    def _decorated(self, fn):
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call):
                d = _dotted(dec.func) or ""
                if d.endswith("partial") or _is_jit_call(dec):
                    inner_is_jit = any(
                        isinstance(a, (ast.Name, ast.Attribute)) and
                        (_dotted(a) or "").endswith("jit")
                        for a in dec.args) or _is_jit_call(dec)
                    pos = _donated_positions(dec)
                    if pos and inner_is_jit:
                        self.positions[fn.name] = pos

    def lookup(self, call, cls):
        """Donated positions for this call's target, else None."""
        f = call.func
        if isinstance(f, ast.Name):
            return self.positions.get(f.id)
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name):
            if f.value.id == "self" and cls:
                return self.positions.get(f"{cls}.{f.attr}")
        return None


class UseAfterDonatePass:
    name = PASS_NAME

    def run(self, ctx):
        findings = []
        for sf in ctx.files:
            if sf.tree is None:
                continue
            reg = _Registry(sf)
            if not reg.positions:
                continue
            for info in ctx.functions.values():
                if info.file is sf:
                    findings.extend(self._check_function(sf, info, reg))
        return findings

    def _check_function(self, sf, info, reg):
        """Linear taint walk over the function's statements in source
        order: donating calls taint their donated Name arguments;
        rebinding clears; a tainted Load is a finding."""
        events = []  # (line, col, kind, name, extra)

        # local aliases of donating self-attrs: `fn = self._jit_fn`
        aliases = dict(reg.positions)

        def visit(node, path):
            if isinstance(node, ast.Assign):
                if isinstance(node.value, ast.Attribute) and \
                        isinstance(node.value.value, ast.Name) and \
                        node.value.value.id == "self" and \
                        info.class_name:
                    pos = reg.positions.get(
                        f"{info.class_name}.{node.value.attr}")
                    if pos:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                aliases[t.id] = pos
                # chained `fn = self._x = jax.jit(...)`: registry
                # already holds Class._x; bind the local names too
                if isinstance(node.value, ast.Call):
                    pos = _donated_positions(node.value) \
                        if _is_jit_call(node.value) else None
                    if pos:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                aliases[t.id] = pos
                # the rebinding takes effect AFTER the value runs:
                # key it at the statement's end so `pool =
                # update(pool, x)` (the correct idiom) ends clean
                for t in node.targets:
                    self._rebinds(t, events,
                                  getattr(node, "end_lineno",
                                          node.lineno), path)
            elif isinstance(node, ast.AnnAssign) and \
                    node.value is not None:
                # `pool: Pool = step(pool, x)` rebinds exactly like the
                # unannotated spelling (a bare `pool: Pool` does not);
                # the annotated jit-binding also registers as an alias
                if isinstance(node.value, ast.Call):
                    pos = _donated_positions(node.value) \
                        if _is_jit_call(node.value) else None
                    if pos and isinstance(node.target, ast.Name):
                        aliases[node.target.id] = pos
                self._rebinds(node.target, events,
                              getattr(node, "end_lineno", node.lineno),
                              path)
            elif isinstance(node, ast.AugAssign):
                self._rebinds(node.target, events,
                              getattr(node, "end_lineno", node.lineno),
                              path)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._rebinds(node.target, events, node.lineno, path)
            elif isinstance(node, ast.Call):
                pos = self._call_positions(node, info, reg, aliases)
                if pos:
                    label = _dotted(node.func) or "<call>"
                    # anchor the taint at the call's END line: the
                    # arguments of a multi-line call are reads of the
                    # not-yet-donated value, not uses-after
                    end = getattr(node, "end_lineno", node.lineno)
                    for i in pos:
                        if i < len(node.args) and \
                                isinstance(node.args[i], ast.Name):
                            events.append(
                                (end, node.col_offset, "donate",
                                 node.args[i].id,
                                 (label, i, node.lineno), path))
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load):
                events.append((node.lineno, node.col_offset, "read",
                               node.id, None, path))
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)) and \
                        child is not info.node:
                    continue
                # branch sensitivity: an If's body and orelse are
                # mutually exclusive — a donate in one arm cannot
                # reach a read in the other
                if isinstance(node, ast.If) and child in node.orelse:
                    visit(child, path + ((id(node), "orelse"),))
                elif isinstance(node, ast.If) and child in node.body:
                    visit(child, path + ((id(node), "body"),))
                else:
                    visit(child, path)

        visit(info.node, ())
        events.sort(key=lambda e: (e[0], e[1]))
        tainted = {}  # name -> (end line, label, argpos, line, path)
        findings = []
        for line, _col, kind, name, extra, path in events:
            if kind == "rebind":
                t = tainted.get(name)
                # a rebind in a branch EXCLUSIVE with the donate does
                # not clear the other arm's taint
                if t is not None and not _exclusive(t[4], path):
                    tainted.pop(name, None)
            elif kind == "donate":
                # the sort line is the call's END line — the call's
                # own argument reads happen at or before it; taint
                # only reads strictly after (extra[2] = the call's
                # first line, for the message)
                tainted[name] = (line, extra[0], extra[1], extra[2],
                                 path)
            elif kind == "read" and name in tainted:
                dline, label, argpos, at, dpath = tainted[name]
                if line <= dline:
                    continue  # same-statement read (the arg itself)
                if _exclusive(dpath, path):
                    continue  # the donate's arm never reaches this one
                findings.append(Finding(
                    PASS_NAME, "use-after-donate", sf.rel, line,
                    f"{name} read after being donated to {label}() "
                    f"(arg {argpos}, donated at {sf.rel}:{at}) — "
                    "the buffer was handed to XLA; rebind the name "
                    "from the dispatch result or copy before donating"))
                tainted.pop(name, None)  # one finding per taint
        return findings

    def _call_positions(self, call, info, reg, aliases):
        pos = reg.lookup(call, info.class_name)
        if pos:
            return pos
        f = call.func
        if isinstance(f, ast.Name):
            return aliases.get(f.id)
        return None

    @staticmethod
    def _rebinds(target, events, at_line, path):
        if isinstance(target, ast.Name):
            events.append((at_line, 1 << 20, "rebind", target.id,
                           None, path))
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                UseAfterDonatePass._rebinds(el, events, at_line, path)
        elif isinstance(target, ast.Starred):
            UseAfterDonatePass._rebinds(target.value, events, at_line,
                                        path)
