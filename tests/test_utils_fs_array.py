"""tensor.array ops, fleet.utils.fs.LocalFS, utils.{dlpack, download,
install_check} — round-4 surface additions (reference:
python/paddle/tensor/array.py, distributed/fleet/utils/fs.py,
utils/{dlpack,download,install_check}.py).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


# ----------------------------------------------------------- tensor.array
def test_tensor_array_write_read_length():
    arr = paddle.tensor.create_array(dtype="float32")
    x = paddle.full(shape=[1, 3], fill_value=5, dtype="float32")
    i = paddle.zeros(shape=[1], dtype="int32")
    arr = paddle.tensor.array_write(x, i, array=arr)
    item = paddle.tensor.array_read(arr, i)
    np.testing.assert_allclose(item.numpy(), np.full((1, 3), 5.0))
    n = paddle.tensor.array_length(arr)
    assert n.numpy().tolist() == [1]


def test_tensor_array_append_and_overwrite():
    a = paddle.to_tensor([1.0])
    b = paddle.to_tensor([2.0])
    arr = paddle.tensor.array_write(a, paddle.zeros([1], "int64"))
    arr = paddle.tensor.array_write(b, paddle.to_tensor([1]), array=arr)
    assert len(arr) == 2
    # overwrite position 0
    arr = paddle.tensor.array_write(b, paddle.to_tensor([0]), array=arr)
    np.testing.assert_allclose(
        paddle.tensor.array_read(arr, paddle.to_tensor([0])).numpy(),
        [2.0])
    # sparse write auto-grows (reference control_flow.py:1479 writes at
    # subscript 10 of a fresh array -> length 11)
    arr = paddle.tensor.array_write(a, paddle.to_tensor([5]), array=arr)
    assert len(arr) == 6
    np.testing.assert_allclose(
        paddle.tensor.array_read(arr, paddle.to_tensor([5])).numpy(),
        [1.0])
    fresh = paddle.tensor.array_write(a, paddle.to_tensor([10]))
    assert len(fresh) == 11
    with pytest.raises(IndexError):
        paddle.tensor.array_write(a, paddle.to_tensor([-1]), array=arr)


def test_tensor_array_initialized_list_validation():
    t = paddle.to_tensor([1.0])
    arr = paddle.tensor.create_array("float32", initialized_list=[t])
    assert len(arr) == 1
    with pytest.raises(TypeError):
        paddle.tensor.create_array("float32", initialized_list=[1.0])
    with pytest.raises(TypeError):
        paddle.tensor.create_array("float32", initialized_list=5)


# ---------------------------------------------------------- fleet fs
def test_localfs_round_trip(tmp_path):
    from paddle_tpu.distributed.fleet.utils import LocalFS
    fs = LocalFS()
    d = str(tmp_path / "ckpt")
    fs.mkdirs(d)
    assert fs.is_dir(d) and fs.is_exist(d)
    f = os.path.join(d, "meta")
    fs.touch(f)
    assert fs.is_file(f)
    with open(f, "w") as fh:
        fh.write("step=7\n")
    assert fs.cat(f) == "step=7"
    sub = os.path.join(d, "shard0")
    fs.mkdirs(sub)
    dirs, files = fs.ls_dir(d)
    assert dirs == ["shard0"] and files == ["meta"]
    assert fs.list_dirs(d) == ["shard0"]
    dst = os.path.join(d, "meta2")
    fs.mv(f, dst)
    assert fs.is_file(dst) and not fs.is_exist(f)
    assert not fs.need_upload_download()
    fs.delete(d)
    assert not fs.is_exist(d)


def test_localfs_mv_guards(tmp_path):
    from paddle_tpu.distributed.fleet.utils import (
        LocalFS, FSFileExistsError, FSFileNotExistsError)
    fs = LocalFS()
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    fs.touch(a)
    fs.touch(b)
    with pytest.raises(FSFileExistsError):
        fs.mv(a, b)
    fs.mv(a, b, overwrite=True)
    with pytest.raises(FSFileNotExistsError):
        fs.mv(str(tmp_path / "nope"), b)
    with pytest.raises(FSFileExistsError):
        fs.touch(b, exist_ok=False)


def test_tensor_array_gap_slots_are_zeros_of_written_shape():
    """Sparse write at idx 3: slots 0..2 fill with zeros of the WRITTEN
    tensor's shape/dtype (bfloat16 included — np.dtype(str(...)) used to
    mangle it), so stack/concat over the array works far from the
    write site."""
    x = paddle.full([2, 4], 7.0, dtype="bfloat16")
    arr = paddle.tensor.array_write(x, paddle.to_tensor([3]))
    assert len(arr) == 4
    for filler in arr[:3]:
        assert filler.shape == [2, 4]
        assert str(filler.value.dtype) == "bfloat16"
        np.testing.assert_allclose(
            filler.astype("float32").numpy(), np.zeros((2, 4)))
    stacked = paddle.stack(arr)
    assert stacked.shape == [4, 2, 4]
    assert str(stacked.value.dtype) == "bfloat16"


def _fake_hadoop(tmp_path, rc, message):
    """A hadoop_home whose bin/hadoop prints `message` and exits rc."""
    home = tmp_path / f"hadoop_rc{rc}"
    (home / "bin").mkdir(parents=True)
    binpath = home / "bin" / "hadoop"
    binpath.write_text(f"#!/bin/sh\necho '{message}'\nexit {rc}\n")
    binpath.chmod(0o755)
    return str(home)


def test_hdfs_test_rc1_benign_means_no(tmp_path):
    from paddle_tpu.distributed.fleet.utils import HDFSClient
    home = _fake_hadoop(
        tmp_path, 1, "SLF4J: Class path contains multiple bindings")
    client = HDFSClient(home, None, time_out=1, sleep_inter=1)
    assert client.is_exist("/ckpt") is False
    assert client.is_dir("/ckpt") is False


def test_hdfs_test_fails_closed_on_unexplained_exit(tmp_path):
    """rc=255 (generic failure), rc=1+java exception: must raise, never
    report "checkpoint absent" — a silent False restarts training from
    scratch over a transient cluster error."""
    from paddle_tpu.distributed.fleet.utils import HDFSClient
    from paddle_tpu.distributed.fleet.utils.fs import (ExecuteError,
                                                       FSTimeOut)
    for rc, msg in ((255, "connection reset"),
                    (1, "java.net.ConnectException: Exception from "
                        "RPC channel"),
                    (137, "JVM killed")):
        client = HDFSClient(_fake_hadoop(tmp_path, rc, msg), None,
                            time_out=1, sleep_inter=1)
        with pytest.raises((ExecuteError, FSTimeOut)):
            client.is_exist("/ckpt")
        with pytest.raises((ExecuteError, FSTimeOut)):
            client.is_dir("/ckpt")


def test_hdfs_client_clear_error_without_hadoop(tmp_path):
    from paddle_tpu.distributed.fleet.utils import HDFSClient
    from paddle_tpu.distributed.fleet.utils.fs import (ExecuteError,
                                                       FSTimeOut)
    client = HDFSClient(str(tmp_path / "no-hadoop"), None,
                        time_out=1, sleep_inter=1)
    with pytest.raises((ExecuteError, FSTimeOut)):
        client.is_exist("/tmp/x")
    assert client.need_upload_download()


# ------------------------------------------------------------ utils.*
def test_dlpack_round_trip():
    from paddle_tpu.utils.dlpack import to_dlpack, from_dlpack
    t = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    capsule = to_dlpack(t)
    t2 = from_dlpack(capsule)
    np.testing.assert_allclose(t2.numpy(), t.numpy())
    with pytest.raises(TypeError):
        to_dlpack("not a tensor")


def test_dlpack_interop_with_torch():
    torch = pytest.importorskip("torch")
    from paddle_tpu.utils.dlpack import from_dlpack
    src = torch.arange(12, dtype=torch.float32).reshape(3, 4)
    t = from_dlpack(src.__dlpack__())
    np.testing.assert_allclose(t.numpy(), src.numpy())


def test_download_cache_and_decompress(tmp_path, monkeypatch):
    import tarfile
    from paddle_tpu.utils import download as dl
    url = "https://example.com/weights/model.pdparams"
    with pytest.raises(RuntimeError, match="zero-egress"):
        dl.get_path_from_url(url, str(tmp_path))
    # pre-placed file resolves
    target = tmp_path / "model.pdparams"
    target.write_bytes(b"abc")
    got = dl.get_path_from_url(url, str(tmp_path))
    assert got == str(target)
    # md5 mismatch refuses the cache
    with pytest.raises(RuntimeError):
        dl.get_path_from_url(url, str(tmp_path), md5sum="0" * 32)
    # archives are unpacked
    arc_dir = tmp_path / "payload"
    arc_dir.mkdir()
    (arc_dir / "w.bin").write_bytes(b"xyz")
    arc = tmp_path / "payload.tar"
    with tarfile.open(arc, "w") as tf:
        tf.add(arc_dir, arcname="payload")
    got = dl.get_path_from_url("https://example.com/payload.tar",
                               str(tmp_path))
    assert got == str(tmp_path / "payload")
    assert not dl.is_url("/local/path")


def test_install_check_run_check(capsys):
    assert paddle.utils.run_check() is True
    out = capsys.readouterr().out
    assert "installed successfully" in out
    assert "8 cpu devices" in out  # the virtual test mesh
