"""paddle.dataset.wmt16 — WMT'16 en↔de multimodal-task corpus, legacy
reader API.

Parity: /root/reference/python/paddle/dataset/wmt16.py (tar with
wmt16/{train,test,val} tab-separated en\tde lines; dictionaries are
built from corpus frequency on first use and cached under DATA_HOME).
"""
import collections
import os
import tarfile

from .common import DATA_HOME, must_mkdirs

__all__ = []

TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220

START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"


def _tar_path():
    return os.path.join(DATA_HOME, "wmt16", "wmt16.tar.gz")


def __build_dict(tar_file, dict_size, save_path, lang):
    word_dict = collections.defaultdict(int)
    col = 0 if lang == "en" else 1
    with tarfile.open(tar_file) as f:
        for line in f.extractfile("wmt16/train"):
            parts = line.decode().strip().split("\t")
            if len(parts) != 2:
                continue
            for w in parts[col].split():
                word_dict[w] += 1
    with open(save_path, "w") as fout:
        fout.write(f"{START_MARK}\n{END_MARK}\n{UNK_MARK}\n")
        for idx, word in enumerate(
                sorted(word_dict.items(), key=lambda x: x[1],
                       reverse=True)):
            if idx + 3 == dict_size:
                break
            fout.write(word[0] + "\n")


def __load_dict(tar_file, dict_size, lang, reverse=False):
    dict_path = os.path.join(DATA_HOME, "wmt16",
                             f"{lang}_{dict_size}.dict")
    dict_found = False
    if os.path.exists(dict_path):
        with open(dict_path) as d:
            dict_found = len(d.readlines()) == dict_size
    if not dict_found:
        must_mkdirs(os.path.dirname(dict_path))
        __build_dict(tar_file, dict_size, dict_path, lang)
    word_dict = {}
    with open(dict_path) as fdict:
        for idx, line in enumerate(fdict):
            if reverse:
                word_dict[idx] = line.strip()
            else:
                word_dict[line.strip()] = idx
    return word_dict


def __get_dict_size(src_dict_size, trg_dict_size, src_lang):
    src_dict_size = min(src_dict_size, (TOTAL_EN_WORDS if src_lang == "en"
                                        else TOTAL_DE_WORDS))
    trg_dict_size = min(trg_dict_size, (TOTAL_DE_WORDS if src_lang == "en"
                                        else TOTAL_EN_WORDS))
    return src_dict_size, trg_dict_size


def reader_creator(tar_file, file_name, src_dict_size, trg_dict_size,
                   src_lang):
    def reader():
        src_dict = __load_dict(tar_file, src_dict_size, src_lang)
        trg_dict = __load_dict(tar_file, trg_dict_size,
                               "de" if src_lang == "en" else "en")
        start_id = src_dict[START_MARK]
        end_id = src_dict[END_MARK]
        unk_id = src_dict[UNK_MARK]
        src_col = 0 if src_lang == "en" else 1
        trg_col = 1 - src_col
        with tarfile.open(tar_file) as f:
            for line in f.extractfile(file_name):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                src_ids = ([start_id]
                           + [src_dict.get(w, unk_id)
                              for w in parts[src_col].split()]
                           + [end_id])
                trg_ids = [trg_dict.get(w, unk_id)
                           for w in parts[trg_col].split()]
                trg_ids_next = trg_ids + [end_id]
                trg_ids = [start_id] + trg_ids
                yield src_ids, trg_ids, trg_ids_next

    return reader


def _check_lang(src_lang):
    if src_lang not in ("en", "de"):
        raise ValueError('src_lang must be one of ["en", "de"]')


def train(src_dict_size, trg_dict_size, src_lang="en"):
    _check_lang(src_lang)
    src_dict_size, trg_dict_size = __get_dict_size(
        src_dict_size, trg_dict_size, src_lang)
    return reader_creator(_tar_path(), "wmt16/train", src_dict_size,
                          trg_dict_size, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    _check_lang(src_lang)
    src_dict_size, trg_dict_size = __get_dict_size(
        src_dict_size, trg_dict_size, src_lang)
    return reader_creator(_tar_path(), "wmt16/test", src_dict_size,
                          trg_dict_size, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    _check_lang(src_lang)
    src_dict_size, trg_dict_size = __get_dict_size(
        src_dict_size, trg_dict_size, src_lang)
    return reader_creator(_tar_path(), "wmt16/val", src_dict_size,
                          trg_dict_size, src_lang)


def get_dict(lang, dict_size, reverse=False):
    dict_size = min(dict_size, (TOTAL_EN_WORDS if lang == "en"
                                else TOTAL_DE_WORDS))
    return __load_dict(_tar_path(), dict_size, lang, reverse)


def fetch():
    from .common import download
    download("http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz",
             "wmt16", None)
