"""Sharded checkpoint/resume for the hybrid train step (ref fleet utils
fs.py + sharding checkpoint; orbax underneath): training resumed from a
checkpoint must replay the exact loss trajectory."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.checkpoint import (save_train_state,
                                               load_train_state)
from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

pytestmark = [pytest.mark.slow, pytest.mark.heavy]  # multi-minute: out of tier-1 and the quick gate


def _loss_fn():
    def f(out, y):
        return nn.functional.cross_entropy(
            out.reshape([-1, out.shape[-1]]), y.reshape([-1]))
    return f


def _build(seed=0):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["dp_degree"] = 4
    strategy.hybrid_configs["sharding_degree"] = 2
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(seed)
    m = GPTForCausalLM(gpt_tiny())
    o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
    return fleet.build_train_step(m, _loss_fn(), o)


@pytest.mark.heavy
def test_resume_replays_trajectory(tmp_path):
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 1024, size=(8, 16)))
    step = _build()
    for _ in range(2):
        step(ids, ids)
    save_train_state(step, str(tmp_path / "ckpt"))
    cont = [step(ids, ids).item() for _ in range(2)]

    fresh = _build(seed=123)  # different init — must be overwritten
    load_train_state(fresh, str(tmp_path / "ckpt"))
    assert fresh._step_i == 2
    resumed = [fresh(ids, ids).item() for _ in range(2)]
    np.testing.assert_allclose(cont, resumed, rtol=1e-5, atol=1e-6)
    # sharded layout preserved on restore
    pk = "gpt.h.0.attn.qkv_proj.weight"
    assert "sharding" in str(fresh.opt_state[pk][0].sharding.spec)
